#!/usr/bin/env python
"""racecheck: static race & lock-discipline analyzer CLI (tpurace).

Whole-repo AST pass over spark_tpu/ (no jax import, no device work; safe
inside the tier-1 budget). Rules: shared-mutation, lock-order,
bare-submit, worker-reinit — see spark_tpu/analysis/race_lint.py. The
runtime half is utils/lockwatch.py, cross-checked by
`dev/validate_trace.py --race`.

Usage:
  python dev/racecheck.py [paths...] [--baseline dev/race_baseline.json]
                          [--write-baseline] [--rule RULE]
                          [--format text|json] [--dump-model]

Exit codes: 0 clean (or all violations baselined), 1 new violations,
2 usage error. The baseline counts violations per (file, rule) bucket —
same workflow as tpulint: existing debt doesn't block CI, NEW debt does.
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# Import the analyzer directly off its file path: `import spark_tpu`
# pulls in the whole engine (and jax); the AST pass must stay light
# enough for CI's tier-1 budget.
import importlib.util

_spec = importlib.util.spec_from_file_location(
    "racecheck_impl",
    os.path.join(_ROOT, "spark_tpu", "analysis", "race_lint.py"))
rlint = importlib.util.module_from_spec(_spec)
sys.modules["racecheck_impl"] = rlint
_spec.loader.exec_module(rlint)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="racecheck", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_ROOT, "spark_tpu")])
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; violations beyond its per-bucket "
                         "counts fail the run")
    ap.add_argument("--write-baseline", action="store_true",
                    help="(re)write the baseline from the current state "
                         "and exit 0")
    ap.add_argument("--rule", action="append", default=None,
                    choices=list(rlint.RULES),
                    help="restrict to specific rule(s)")
    ap.add_argument("--format", default="text", choices=("text", "json"))
    ap.add_argument("--dump-model", action="store_true",
                    help="print the repo concurrency model (locks, "
                         "states, nesting edges, annotations) as JSON — "
                         "the surface the --race dynamic gate consumes")
    args = ap.parse_args(argv)
    if args.write_baseline and args.rule:
        ap.error("--write-baseline with --rule would drop every other "
                 "rule's buckets from the baseline; run it unfiltered")

    paths = [p if os.path.isabs(p) else os.path.join(os.getcwd(), p)
             for p in args.paths]
    model = rlint.build_model(paths, repo_root=_ROOT)
    violations = model.violations
    if args.rule:
        violations = [v for v in violations if v.rule in set(args.rule)]

    if args.dump_model:
        print(json.dumps(model.to_dict(), indent=1))
        return 0

    if args.write_baseline:
        target = args.baseline or os.path.join(_HERE, "race_baseline.json")
        rlint.write_baseline(target, violations)
        print(f"racecheck: baseline written to {target} "
              f"({len(violations)} violations over "
              f"{len(rlint.baseline_counts(violations))} buckets)")
        return 0

    if args.baseline:
        baseline = rlint.load_baseline(args.baseline)
        offending = rlint.new_violations(violations, baseline)
        label = "new violation(s) beyond baseline"
    else:
        baseline = {}
        offending = violations
        label = "violation(s)"

    if args.format == "json":
        print(json.dumps({
            "total": len(violations),
            "new": [v.__dict__ for v in offending],
        }, indent=1))
    else:
        for v in offending:
            print(v)
        by_rule = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
        print(f"racecheck: {len(violations)} total "
              f"({summary or 'clean'}); {len(offending)} {label}")
    return 1 if offending else 0


if __name__ == "__main__":
    sys.exit(main())
