#!/usr/bin/env python
"""Postmortem CLI over diagnostic bundles (obs/blackbox.py).

Usage:
    python dev/diagnose.py <bundle_dir>              # list bundles
    python dev/diagnose.py <bundle_dir> <bundle_id>  # render postmortem
    python dev/diagnose.py <bundle_dir> --latest     # newest bundle
    python dev/diagnose.py <bundle_dir> <id> --tar   # pack to .tar.gz

Renders entirely from the bundle directory — no live process, no
profile store, no cluster: the bundle is the self-contained black box.
The report covers the trigger timeline (what fired and the full finding
chain), counter drift against the embedded same-key baseline history,
and the per-executor straggler/HBM map (driver live rows + the worker
diagnostic rings pulled at capture time).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Render a postmortem report from a diagnostic "
                    "bundle directory (spark.tpu.obs.bundleDir)")
    p.add_argument("bundle_dir")
    p.add_argument("bundle_id", nargs="?", default=None,
                   help="bundle to render (omit to list the ring)")
    p.add_argument("--latest", action="store_true",
                   help="render the newest bundle")
    p.add_argument("--tar", action="store_true",
                   help="pack the bundle directory into one .tar.gz "
                        "archive instead of rendering")
    p.add_argument("-o", "--out", default=None,
                   help="archive path for --tar (default: "
                        "<bundle_dir>/bundle-<id>.tar.gz)")
    args = p.parse_args(argv)

    from spark_tpu.obs.blackbox import list_bundles, pack_bundle
    from spark_tpu.obs.diagnose import render_index, render_postmortem

    bid = args.bundle_id
    if args.latest and bid is None:
        entries = list_bundles(args.bundle_dir)
        if not entries:
            print(f"no bundles under {args.bundle_dir}", file=sys.stderr)
            return 1
        bid = entries[0]["id"]
    if bid is None:
        sys.stdout.write(render_index(args.bundle_dir))
        return 0
    if args.tar:
        try:
            path = pack_bundle(args.bundle_dir, bid, out=args.out)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 1
        print(path)
        return 0
    try:
        sys.stdout.write(render_postmortem(args.bundle_dir, bid))
    except KeyError:
        print(f"unknown bundle id {bid} (pruned from the retention "
              "ring?)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
