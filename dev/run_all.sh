#!/usr/bin/env bash
# Full verification: native build, tests (with batch validation), examples,
# micro-benchmarks, headline bench (role of the reference's dev/run-tests.py).
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== tpulint (static analysis vs baseline) =="
python dev/tpulint.py spark_tpu --baseline dev/tpulint_baseline.json

echo "== racecheck (static race & lock-discipline model vs baseline) =="
python dev/racecheck.py spark_tpu --baseline dev/race_baseline.json

echo "== native build =="
make -C native

echo "== tests (batch validation on) =="
SPARK_TPU_VALIDATE=1 python -m pytest tests/ -q

echo "== examples =="
for ex in examples/*.py; do
    echo "-- $ex"
    python "$ex" > /dev/null
done

echo "== trace gate (bench --smoke --trace + validation + drift + resources) =="
SPARK_TPU_TRACE_PATH=/tmp/sparktpu_smoke_trace.json \
    python bench.py --smoke --trace
JAX_PLATFORMS=cpu python dev/validate_trace.py /tmp/sparktpu_smoke_trace.json

echo "== cluster trace gate (worker shipping + flows + live telemetry) =="
SPARK_TPU_TRACE_PATH=/tmp/sparktpu_cluster_trace.json \
    python bench.py --smoke --trace --cluster groupby
JAX_PLATFORMS=cpu python dev/validate_trace.py --cluster --live \
    /tmp/sparktpu_cluster_trace.json

echo "== mesh gate (SPMD stage fusion on the 8-device virtual mesh) =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python dev/validate_trace.py --mesh

echo "== encoded gate (compressed execution: dict-native kernels, code shuffle) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --encoded
python bench.py --smoke --encoded encoded

echo "== adaptive gate (runtime join filters: on/off identity, honest drift) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --adaptive
python bench.py --smoke --adaptive adaptive

echo "== whole-query gate (one jitted program per step, 3-tier differential) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --whole-query
python bench.py --smoke --whole-query whole_query

echo "== mesh whole-query gate (entire sharded plan as ONE shard_map program) =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python dev/validate_trace.py --mesh-whole
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python bench.py --smoke --mesh-whole mesh_whole

echo "== chaos gate (fault injection: retry/exclusion/degrade, fixed seed) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --chaos

echo "== profile gate (flight recorder: fingerprints, store, regression) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --profile

echo "== persist gate (cold→warm subprocess restart: disk-hit/zero-launch) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --persist
python bench.py --smoke --serve-restart serve_restart

echo "== serve gate (fair pools, admission, scope-exact attribution, drain) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --serve
python bench.py --smoke --serve serve

echo "== metrics gate (export plane: scrape identity, zero overhead, drain ring) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --metrics

echo "== bundles gate (black box: chaos-seeded SLO capture, zero overhead, retention) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --bundles

echo "== race gate (lockwatch: guard checks + acquisition orders vs static model) =="
JAX_PLATFORMS=cpu python dev/validate_trace.py --race

echo "== perfcheck (deterministic counters of bench --smoke vs baseline) =="
python dev/perfcheck.py

echo "== micro-benchmarks =="
python benchmarks/run_benchmarks.py --rows "${BENCH_ROWS:-2000000}"

echo "== headline bench =="
python bench.py
