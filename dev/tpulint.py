#!/usr/bin/env python
"""tpulint: static analyzer CLI for host-sync / recompile / fusion hazards.

Source-level pass over spark_tpu/ (AST only — no jax import, no device
work; safe inside the tier-1 budget). Rules: host-sync, row-loop, raw-jit,
config-key — see spark_tpu/analysis/lint.py. The plan/trace-level pass is
its sibling: df.explain("analysis") / QueryExecution.analysis_report().

Usage:
  python dev/tpulint.py [paths...] [--baseline dev/tpulint_baseline.json]
                        [--write-baseline] [--rule RULE] [--format text|json]

Exit codes: 0 clean (or all violations baselined), 1 new violations,
2 usage error. The baseline counts violations per (file, rule) bucket, so
existing debt doesn't block CI while NEW violations do.
"""

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

# Import the lint module directly off its file path: `import spark_tpu`
# pulls in the whole engine (and jax); the AST lint must stay light enough
# for CI's tier-1 budget.
import importlib.util

_spec = importlib.util.spec_from_file_location(
    "tpulint_impl", os.path.join(_ROOT, "spark_tpu", "analysis", "lint.py"))
lint = importlib.util.module_from_spec(_spec)
sys.modules["tpulint_impl"] = lint
_spec.loader.exec_module(lint)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpulint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(_ROOT, "spark_tpu")])
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; violations beyond its per-bucket "
                         "counts fail the run")
    ap.add_argument("--write-baseline", action="store_true",
                    help="(re)write the baseline from the current state "
                         "and exit 0")
    ap.add_argument("--rule", action="append", default=None,
                    choices=list(lint.RULES),
                    help="restrict to specific rule(s)")
    ap.add_argument("--format", default="text", choices=("text", "json"))
    args = ap.parse_args(argv)
    if args.write_baseline and args.rule:
        ap.error("--write-baseline with --rule would drop every other "
                 "rule's buckets from the baseline; run it unfiltered")

    paths = [p if os.path.isabs(p) else os.path.join(os.getcwd(), p)
             for p in args.paths]
    violations = lint.lint_paths(paths, repo_root=_ROOT)
    if args.rule:
        violations = [v for v in violations if v.rule in set(args.rule)]

    if args.write_baseline:
        target = args.baseline or os.path.join(_HERE,
                                               "tpulint_baseline.json")
        lint.write_baseline(target, violations)
        print(f"tpulint: baseline written to {target} "
              f"({len(violations)} violations over "
              f"{len(lint.baseline_counts(violations))} buckets)")
        return 0

    if args.baseline:
        baseline = lint.load_baseline(args.baseline)
        offending = lint.new_violations(violations, baseline)
        label = "new violation(s) beyond baseline"
    else:
        baseline = {}
        offending = violations
        label = "violation(s)"

    if args.format == "json":
        print(json.dumps({
            "total": len(violations),
            "new": [v.__dict__ for v in offending],
        }, indent=1))
    else:
        for v in offending:
            print(v)
        by_rule = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(by_rule.items()))
        print(f"tpulint: {len(violations)} total ({summary or 'clean'}); "
              f"{len(offending)} {label}")
    return 1 if offending else 0


if __name__ == "__main__":
    sys.exit(main())
