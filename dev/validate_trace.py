#!/usr/bin/env python
"""CI gate for the observability layer (dev/run_all.sh).

Two checks, both hard failures:

1. Trace validation — the Chrome-trace JSON emitted by `bench.py --smoke
   --trace` must be well-formed (a non-empty `traceEvents` list of
   complete/metadata events with sane fields), spans must nest properly
   per thread track (stack discipline: no partial overlap), and at least
   one span must carry non-empty kernel attribution (`args.launches`) —
   proving the KernelCache→operator attribution path is live end to end.

2. Drift gate — EXPLAIN ANALYZE on a representative fused aggregation
   runs predicted-vs-measured reconciliation; any finding of severity
   `error` (unexplained drift between analysis/plan_lint.py's launch
   model and the execution layer) fails the build.

Usage: python dev/validate_trace.py <trace.json>
"""

import json
import os
import sys

# runs as `python dev/validate_trace.py` — spark_tpu lives one level up
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def fail(msg: str) -> "NoReturn":  # noqa: F821
    print(f"validate_trace: FAIL — {msg}")
    sys.exit(1)


def validate_trace(path: str) -> None:
    if not os.path.isfile(path):
        fail(f"trace file {path} does not exist")
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            fail(f"trace file is not valid JSON: {e}")
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail("traceEvents missing or empty")
    complete = [e for e in events if e.get("ph") == "X"]
    if not complete:
        fail("no complete ('ph': 'X') span events")
    for e in complete:
        for k in ("name", "ts", "dur", "pid", "tid"):
            if k not in e:
                fail(f"span event missing field {k!r}: {e}")
        if e["dur"] < 0 or e["ts"] < 0:
            fail(f"negative ts/dur: {e}")

    # nesting: per tid, spans must obey stack discipline — any two spans
    # either nest or are disjoint (1 µs fuzz for float rounding)
    fuzz = 1.0
    by_tid: dict = {}
    for e in complete:
        by_tid.setdefault(e["tid"], []).append(e)
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in evs:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"] \
                    - fuzz:
                stack.pop()
            if stack:
                parent = stack[-1]
                if e["ts"] + e["dur"] > parent["ts"] + parent["dur"] + fuzz:
                    fail(f"span {e['name']!r} partially overlaps "
                         f"{parent['name']!r} on tid {tid} "
                         "(broken nesting)")
            stack.append(e)

    attributed = [e for e in complete
                  if (e.get("args") or {}).get("launches", 0) > 0]
    if not attributed:
        fail("no span carries kernel attribution (args.launches > 0) — "
             "the KernelCache→operator attribution scope is dead")
    cats = {e.get("cat") for e in complete}
    print(f"validate_trace: trace OK — {len(complete)} spans, "
          f"{len(by_tid)} thread tracks, {len(attributed)} with kernel "
          f"attribution, categories={sorted(c for c in cats if c)}")


def drift_gate() -> None:
    """EXPLAIN ANALYZE a fused aggregation; severity-error drift findings
    (launch-model divergence) fail the gate."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession

    session = TpuSession("trace-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.fusion.minRows": "0",
    })
    rng = np.random.default_rng(11)
    n = 4000
    session.createDataFrame(pa.table({
        "k": rng.integers(0, 9, n),
        "v": rng.integers(-20, 80, n),
    })).createOrReplaceTempView("gate_t")
    df = session.sql(
        "select k, sum(v) s, count(*) c from gate_t where v > 0 group by k")
    report = df.query_execution.analyzed_report()
    errors = [f for f in report.findings if f["severity"] == "error"]
    if errors:
        print(report.render())
        fail("EXPLAIN ANALYZE reported unexplained drift: "
             + "; ".join(f["msg"] for f in errors))
    print("validate_trace: drift gate OK — predicted "
          f"{sum(report.predicted.values())} == measured "
          f"{sum(report.measured.values())} launches, "
          f"{len(report.findings)} non-error findings")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print(__doc__)
        return 2
    validate_trace(argv[0])
    drift_gate()
    print("validate_trace: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
