#!/usr/bin/env python
"""CI gate for the observability layer (dev/run_all.sh).

Four checks, all hard failures:

1. Trace validation — the Chrome-trace JSON emitted by `bench.py --smoke
   --trace` must be well-formed (a non-empty `traceEvents` list of
   complete/metadata events with sane fields), spans must nest properly
   per thread track (stack discipline: no partial overlap), at least
   one span must carry non-empty kernel attribution (`args.launches`) —
   proving the KernelCache→operator attribution path is live end to end
   — and every Perfetto flow arrow must have referential integrity:
   each flow id resolves to exactly one "s" and one "f" event, each
   anchored inside a complete span on its thread track. With --cluster
   (the `bench.py --smoke --trace --cluster` leg), the trace must also
   contain at least one worker-track span (`worker:<id>/...` thread
   name), proving worker-side span shipping crossed the process
   boundary.

2. Drift gate — EXPLAIN ANALYZE on a representative fused aggregation
   runs predicted-vs-measured reconciliation; any finding of severity
   `error` (unexplained drift between analysis/plan_lint.py's launch
   model and the execution layer) fails the build. With --cluster the
   gate query runs under ClusterDAGScheduler and additionally requires
   non-empty per-operator metrics whose attributed-launch total equals
   the measured (driver + worker) launch total.

3. Resource gate — after the drift-gate query, the device ledger
   (obs/resources.py) must verify internally: non-negative balances
   everywhere (global, per-query, per-operator), the identity table
   reconciling with the byte counter, attribution sums never exceeding
   the global ledger; the KernelCache's captured cost table must be
   non-empty with positive cumulative bytes accessed; and the gate
   query's HBM record must show a positive measured watermark with
   per-operator attribution.

4. Live-telemetry gate (--live) — a cluster smoke run with a fast
   executor heartbeat must surface at least one MID-STAGE obs delta on
   the driver before any task returns (the reference's periodic
   Heartbeater streaming accumulator updates), and after completion the
   merged live records must reconcile with the final task-return
   records (monotonic merge converged: every task done, partial
   counters superseded exactly, zero straggler findings on the healthy
   run).

5. Mesh gate (--mesh) — on a virtual 8-device CPU mesh, a fused
   power-of-two repartition+agg must run its shuffle stage as
   mesh_stage dispatches that plan_lint predicts EXACTLY, with zero
   unexplained drift, attribution totals matching the measured
   launches under shard_map, span nesting holding on the exported
   trace, a donated (donate_argnums) stage program in the kernel
   cache, and a balanced device ledger afterwards. Self-contained:
   `validate_trace.py --mesh` with no trace path runs only this gate.

6. Encoded gate (--encoded) — compressed execution: a dictionary-heavy
   string-keyed repartition + group-by must produce byte-identical
   results encoded vs decoded (spark.tpu.encoding.enabled
   differential), predict launch counts exactly on the encoded path
   (dense-on-codes, zero krange3 probes) fusion on and off, and show
   zero unexplained EXPLAIN ANALYZE drift. Self-contained:
   `validate_trace.py --encoded` with no trace path runs only this
   gate.

7. Chaos gate (--chaos) — deterministic fault injection under a fixed
   seed: a transient block-fetch flap must be absorbed by the bounded
   fetch retry with zero stage regenerations; exhausted fetch retries
   must regenerate from lineage correctly and an unbounded failure
   stream must terminate in the classified StageRegenerationLimitError
   with zero leaked shuffle blocks; a transient worker-task fault must
   fail over to another executor with per-operator kernel attribution
   still equal to driver+worker totals; a whole-tier runtime dispatch
   fault must degrade to the stage tier with identical results. Every
   scenario runs under a watchdog (a hang fails the gate) and the
   device ledger must verify balanced afterwards. Self-contained:
   `validate_trace.py --chaos` with no trace path runs only this gate.

8. Profile gate (--profile) — query flight recorder end to end: two
   identical smoke queries must yield ONE plan fingerprint, two stored
   profiles, and zero obs.regression findings; a forced
   spark.tpu.compile.tier=operator flip must land on the SAME
   structural query key, a DIFFERENT fingerprint, and raise a
   deterministic-counter regression finding (severity error); and
   dev/perfcheck.py's comparator must flag the same delta against a
   baseline built from the healthy runs. Self-contained:
   `validate_trace.py --profile` with no trace path runs only this
   gate.

10. Serve gate (--serve) — multi-tenant serving (spark_tpu/serve/):
    the weighted fair scheduler must grant contended slots in exact
    2:1 proportion under a deterministic submit/release schedule;
    scheduler-level HBM admission must hold a query back until the
    in-flight reservation frees budget (and an over-budget plan must
    reject plan-time through check_memory_budget); a REAL concurrent
    load (8 cloned sessions, 2 pools) must complete with every
    query's attributed launch total summing exactly to the global
    KernelCache delta, zero `overlapped` profiles, and a
    contention-fairness ratio within 25% of the configured weights;
    and graceful drain must reject new queries with SERVER_DRAINING,
    finish in-flight work, and leave the admission ledger balanced
    (no leaked slots or HBM reservations) with the device ledger
    verifying clean. Self-contained: `validate_trace.py --serve`
    with no trace path runs only this gate.

11. Mesh whole-query gate (--mesh-whole) — on a virtual 8-device CPU
    mesh, a repartitioned join+agg under spark.tpu.compile.tier=
    mesh-whole must execute the ENTIRE sharded plan as ONE shard_map
    dispatch per step (exchanges as in-program all-to-alls, join and
    aggregate folded behind the collectives), agree with the whole and
    stage tiers, have its mesh_whole launch count — including a skew-
    driven quota-retry round — predicted EXACTLY by plan_lint, surface
    the tier decision on report and span, and leave the device ledger
    balanced. Self-contained: `validate_trace.py --mesh-whole` with no
    trace path runs only this gate.

12. Race gate (--race) — runtime lock-discipline validation: the
    8-session serve load and a 2-worker cluster chaos leg (transient
    block-fetch flap plus a deterministic transport-retry exercise) run
    under utils/lockwatch.py with every registered lock watched. Every
    instrumented guard must be HELD where the static race_lint model
    claims (zero guard violations, the RETRY_STATS counter actually
    exercised), the union of the statically inferred lock-nesting graph
    and the runtime-observed acquisition-order edges must stay acyclic
    (an observed order the static model missed that closes a cycle is a
    latent deadlock), the registered watch slots must all exist in the
    static lock inventory, attribution must stay scope-exact under
    watching, and disable() must restore raw locks (the structural
    zero-overhead-when-idle claim). Self-contained:
    `validate_trace.py --race` with no trace path runs only this gate.

13. Adaptive gate (--adaptive) — runtime-adaptive execution: a
    selective shuffled hash join must produce identical results with
    spark.tpu.adaptive.runtimeFilter on vs off, install at least one
    runtime join filter that prunes probe rows before the shuffle (the
    install event visible as an adaptive.runtime_filter span), degrade
    the launch model honestly (exact=False with a named runtimeFilter
    reason, zero unexplained EXPLAIN ANALYZE drift), and leave the
    device ledger balanced. Self-contained: `validate_trace.py
    --adaptive` with no trace path runs only this gate.

Usage: python dev/validate_trace.py [--cluster] [--live] [--mesh]
       [--encoded] [--adaptive] [--whole-query] [--mesh-whole]
       [--chaos] [--profile] [--serve] [--race] [<trace.json>]
"""

import json
import os
import sys

# runs as `python dev/validate_trace.py` — spark_tpu lives one level up
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)


def fail(msg: str) -> "NoReturn":  # noqa: F821
    print(f"validate_trace: FAIL — {msg}")
    sys.exit(1)


def _check_flows(events: list, complete: list) -> int:
    """Flow-event referential integrity: every flow id has exactly one
    "s" and one "f" endpoint, and each endpoint lands inside a complete
    span on its (pid, tid) track (Perfetto binds arrows to the enclosing
    slice — a dangling endpoint renders as an arrow from/to nowhere)."""
    fuzz = 1.0
    flows = [e for e in events if e.get("ph") in ("s", "t", "f")]
    by_id: dict = {}
    for e in flows:
        if "id" not in e:
            fail(f"flow event missing id: {e}")
        by_id.setdefault(e["id"], []).append(e)
    spans_by_track: dict = {}
    for e in complete:
        spans_by_track.setdefault((e["pid"], e["tid"]), []).append(e)
    for fid, evs in by_id.items():
        phs = sorted(e["ph"] for e in evs)
        if phs != ["f", "s"]:
            fail(f"flow id {fid} endpoints are {phs}, want one 's' + "
                 "one 'f' (broken arrow)")
        for e in evs:
            track = spans_by_track.get((e["pid"], e["tid"]), [])
            if not any(sp["ts"] - fuzz <= e["ts"] <= sp["ts"] + sp["dur"]
                       + fuzz for sp in track):
                fail(f"flow endpoint {e} does not land inside any span "
                     f"on track {(e['pid'], e['tid'])} — the flow id "
                     "does not resolve to an endpoint span")
    return len(by_id)


def validate_trace(path: str, cluster: bool = False) -> None:
    if not os.path.isfile(path):
        fail(f"trace file {path} does not exist")
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            fail(f"trace file is not valid JSON: {e}")
    events = doc.get("traceEvents")
    if not isinstance(events, list) or not events:
        fail("traceEvents missing or empty")
    complete = [e for e in events if e.get("ph") == "X"]
    if not complete:
        fail("no complete ('ph': 'X') span events")
    for e in complete:
        for k in ("name", "ts", "dur", "pid", "tid"):
            if k not in e:
                fail(f"span event missing field {k!r}: {e}")
        if e["dur"] < 0 or e["ts"] < 0:
            fail(f"negative ts/dur: {e}")

    # nesting: per tid, spans must obey stack discipline — any two spans
    # either nest or are disjoint (1 µs fuzz for float rounding)
    fuzz = 1.0
    by_tid: dict = {}
    for e in complete:
        by_tid.setdefault(e["tid"], []).append(e)
    for tid, evs in by_tid.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in evs:
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"] \
                    - fuzz:
                stack.pop()
            if stack:
                parent = stack[-1]
                if e["ts"] + e["dur"] > parent["ts"] + parent["dur"] + fuzz:
                    fail(f"span {e['name']!r} partially overlaps "
                         f"{parent['name']!r} on tid {tid} "
                         "(broken nesting)")
            stack.append(e)

    attributed = [e for e in complete
                  if (e.get("args") or {}).get("launches", 0) > 0]
    if not attributed:
        fail("no span carries kernel attribution (args.launches > 0) — "
             "the KernelCache→operator attribution scope is dead")

    n_flows = _check_flows(events, complete)
    if not n_flows:
        fail("no flow events — the query→stage→lane/worker flow "
             "linkage is dead (spans carry no resolvable flow ids)")

    worker_tracks = {m["args"]["name"] for m in events
                     if m.get("ph") == "M"
                     and m.get("name") == "thread_name"
                     and str(m.get("args", {}).get("name", ""))
                     .startswith("worker:")}
    if cluster and not worker_tracks:
        fail("--cluster: no worker-track span (thread name 'worker:…') — "
             "worker-side span shipping never crossed the process "
             "boundary")
    cats = {e.get("cat") for e in complete}
    print(f"validate_trace: trace OK — {len(complete)} spans, "
          f"{len(by_tid)} thread tracks ({len(worker_tracks)} worker), "
          f"{len(attributed)} with kernel attribution, {n_flows} flow "
          f"arrows, categories={sorted(c for c in cats if c)}")


def drift_gate(cluster: bool = False) -> None:
    """EXPLAIN ANALYZE a fused aggregation; severity-error drift findings
    (launch-model divergence) fail the gate. With --cluster the query
    runs under ClusterDAGScheduler: worker-shipped attribution must be
    non-empty and reconcile with the driver+worker measured total."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession

    conf = {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.fusion.minRows": "0",
    }
    if cluster:
        conf["spark.tpu.cluster.enabled"] = "true"
        conf["spark.tpu.cluster.workers"] = "2"
    session = TpuSession("trace-gate", conf)
    try:
        rng = np.random.default_rng(11)
        n = 4000
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 9, n),
            "v": rng.integers(-20, 80, n),
        })).createOrReplaceTempView("gate_t")
        if cluster:
            # the explicit repartition keeps shuffle map stages in the
            # plan (a single-partition partial agg never ships) — the
            # gate must exercise worker-side attribution, not just the
            # driver path
            import spark_tpu.api.functions as F

            df = (session.sql("select k, v from gate_t where v > 0")
                  .repartition(2).groupBy("k")
                  .agg(F.sum("v").alias("s"), F.count("k").alias("c")))
        else:
            df = session.sql(
                "select k, sum(v) s, count(*) c from gate_t where v > 0 "
                "group by k")
        report = df.query_execution.analyzed_report()
        errors = [f for f in report.findings if f["severity"] == "error"]
        if errors:
            print(report.render())
            fail("EXPLAIN ANALYZE reported unexplained drift: "
                 + "; ".join(f["msg"] for f in errors))
        if cluster:
            remote = session._metrics.snapshot()["counters"].get(
                "scheduler.stages_remote", 0)
            if remote < 1:
                fail("--cluster: gate query never shipped a map stage "
                     "to a worker process")
            attributed = sum(v for nd in report.nodes
                             for v in (nd.get("launches") or {}).values())
            measured = sum(report.measured.values())
            if not attributed:
                fail("--cluster: EXPLAIN ANALYZE per-operator metrics "
                     "empty — worker-side attribution never shipped")
            if attributed != measured:
                fail(f"--cluster: attributed launches ({attributed}) != "
                     f"measured driver+worker total ({measured}) — a "
                     "dispatch escaped cross-process attribution")
        print("validate_trace: drift gate OK — predicted "
              f"{sum(report.predicted.values())} == measured "
              f"{sum(report.measured.values())} launches, "
              f"{len(report.findings)} non-error findings"
              + (" [cluster]" if cluster else ""))
    finally:
        session.stop()


def resource_gate() -> None:
    """Device-resource accounting must balance at query end: the ledger
    verifies internally (non-negative balances, identity table ==
    counter, attribution <= global), the kernel cost table is non-empty
    with positive bytes accessed, and the gate query's HBM record shows
    a positive per-operator-attributed watermark that EXPLAIN ANALYZE's
    memory section reconciles against the plan analyzer's prediction."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.obs.resources import GLOBAL_LEDGER
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    session = TpuSession("resource-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.tpu.fusion.minRows": "0",
    })
    try:
        rng = np.random.default_rng(5)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 7, 3000),
            "v": rng.integers(-10, 90, 3000),
        })).createOrReplaceTempView("res_t")
        df = session.sql("select k, sum(v) s from res_t where v > 0 "
                         "group by k")
        report = df.query_execution.analyzed_report()
        issues = GLOBAL_LEDGER.verify()
        if issues:
            fail("resource gate: ledger failed verification — "
                 + "; ".join(issues))
        if not KC.cost_by_kind:
            fail("resource gate: kernel cost table empty — cost capture "
                 "never ran (spark.tpu.metrics.kernelCost path broken)")
        if not KC.bytes_total > 0:
            fail("resource gate: cumulative kernel bytes accessed is 0 — "
                 "neither XLA cost_analysis nor the metadata fallback "
                 "captured anything")
        mem = report.memory
        if not mem.get("measured_peak"):
            fail("resource gate: EXPLAIN ANALYZE memory section has no "
                 "measured HBM watermark for the gate query")
        if not mem.get("predicted_peak"):
            fail("resource gate: plan analyzer produced no predicted "
                 "peak HBM for the gate query")
        if not any(st.get("measured") for st in mem.get("per_stage", ())):
            fail("resource gate: no per-operator HBM attribution reached "
                 "the memory section (scope propagation broken)")
        print("validate_trace: resource gate OK — ledger balanced "
              f"({GLOBAL_LEDGER.bytes} B live), "
              f"{len(KC.cost_by_kind)} kernel kinds costed "
              f"({KC.bytes_total:.0f} B accessed), query watermark "
              f"{mem['measured_peak']} B vs predicted {mem['predicted_peak']} B")
    finally:
        session.stop()


def live_gate() -> None:
    """Heartbeat-streamed telemetry must be operational, not post-mortem:
    run a deliberately slow map stage on a 2-worker cluster heartbeating
    every 0.1s, require ≥1 mid-stage obs delta on the driver BEFORE the
    task-return record lands, then require the live store to have
    converged to the task-return truth (every cluster task done and
    reconciled) with zero straggler findings on the healthy run."""
    import time

    import numpy as np
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu import TpuSession
    from spark_tpu.types import int64

    session = TpuSession("live-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 2,
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.cluster.enabled": "true",
        "spark.tpu.cluster.workers": "2",
        "spark.tpu.heartbeat.interval": "0.1",
    })
    try:
        rng = np.random.default_rng(23)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 8, 4000),
            "v": rng.integers(-20, 60, 4000),
        })).createOrReplaceTempView("live_t")

        @F.udf(returnType=int64)
        def crawl(k):
            time.sleep(0.4)   # several 0.1s heartbeats per map batch
            return k * 2

        qids = []
        session.listener_bus.register(lambda ev: qids.append(ev.query_id))
        live = session.live_obs
        base = live.partials_seen
        (session.table("live_t").withColumn("kk", crawl("k"))
         .repartition(2).groupBy("k").agg(F.sum("v").alias("s"))).toArrow()
        session.listener_bus.wait_empty()
        if live.partials_seen <= base:
            fail("--live: no mid-stage heartbeat obs delta reached the "
                 "driver before task return")
        if not qids:
            fail("--live: query event never fired (no query id to check)")
        progress = live.query_progress(qids[-1])
        if progress is None:
            fail("--live: live store has no record of the gate query")
        streamed = 0
        for stage, st in progress["stages"].items():
            if stage == "local":
                continue
            if st["tasks_done"] != st["tasks_total"]:
                fail(f"--live: stage {stage} never closed in the live "
                     f"store ({st['tasks_done']}/{st['tasks_total']})")
            for task, t in st["tasks"].items():
                if t["partials"] > 0:
                    streamed += 1
                    if t["reconciled"] is not True:
                        fail(f"--live: task {task} of stage {stage} "
                             "streamed partials that do NOT reconcile "
                             "with its final task-return record")
        if streamed < 1:
            fail("--live: no cluster task streamed a mid-stage partial "
                 "for the gate query")
        stragglers = [f for f in progress["findings"]
                      if f.get("kind") == "obs.straggler"]
        if stragglers:
            fail("--live: healthy run raised straggler findings: "
                 + "; ".join(f["msg"] for f in stragglers))
        print(f"validate_trace: live gate OK — {live.partials_seen - base} "
              f"heartbeat deltas, {streamed} task(s) streamed partials "
              "and reconciled, 0 stragglers")
    finally:
        session.stop()


def mesh_gate() -> None:
    """Mesh SPMD stage gate (--mesh, virtual 8-device CPU mesh): a
    power-of-two fused repartition+agg must execute its shuffle stage as
    mesh_stage dispatches predicted EXACTLY by plan_lint (one per step
    plus quota retries), EXPLAIN ANALYZE must show zero unexplained
    drift, per-operator attribution must equal the measured total (no
    dispatch escapes the operator scope under shard_map), span nesting
    must hold on the exported trace, and the device ledger must stay
    balanced after the donated send buffers release."""
    import jax

    if len(jax.devices()) < 8:
        fail("--mesh: needs 8 virtual devices (run with JAX_PLATFORMS="
             "cpu XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    import json as _json
    import tempfile

    import numpy as np
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu import TpuSession
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    session = TpuSession("mesh-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 8,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.trace.enabled": "true",
        "spark.tpu.ui.operatorMetrics": "true",
    })
    try:
        rng = np.random.default_rng(29)
        n = 6000
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 11, n),
            "v": rng.integers(-20, 80, n),
        })).createOrReplaceTempView("mesh_t")

        def q():
            return (session.sql("select k, v * 2 as v2 from mesh_t "
                                "where v > 0")
                    .repartition(8, "k").groupBy("k")
                    .agg(F.sum("v2").alias("s")))

        report = q().query_execution.analyzed_report()
        errors = [f for f in report.findings if f["severity"] == "error"]
        if errors:
            print(report.render())
            fail("--mesh: EXPLAIN ANALYZE reported unexplained drift "
                 "under shard_map: " + "; ".join(f["msg"] for f in errors))
        if report.measured.get("mesh_stage", 0) < 1:
            fail("--mesh: gate query never dispatched a mesh stage "
                 f"program (measured {dict(report.measured)})")
        if report.predicted.get("mesh_stage") != \
                report.measured.get("mesh_stage"):
            fail("--mesh: plan_lint mesh_stage prediction "
                 f"{report.predicted.get('mesh_stage')} != measured "
                 f"{report.measured.get('mesh_stage')}")
        attributed = sum(v for nd in report.nodes
                         for v in (nd.get("launches") or {}).values())
        measured = sum(report.measured.values())
        if attributed != measured:
            fail(f"--mesh: attributed launches ({attributed}) != "
                 f"measured total ({measured}) — a shard_map dispatch "
                 "escaped operator attribution")
        # span nesting + attribution args hold on the exported trace
        from spark_tpu.obs.tracing import to_chrome_trace

        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            _json.dump(to_chrome_trace(session.tracer.spans(),
                                       process_name="mesh-gate"), f)
            path = f.name
        validate_trace(path)
        os.unlink(path)
        donated = [k for k in KC._cache
                   if k and k[0] == "mesh_stage" and k[-1] is True]
        if not donated:
            fail("--mesh: no mesh stage program compiled with donated "
                 "send buffers (donate_argnums)")
        from spark_tpu.obs.resources import GLOBAL_LEDGER

        issues = GLOBAL_LEDGER.verify()
        if issues:
            fail("--mesh: device ledger failed verification after the "
                 "donated stage: " + "; ".join(issues))
        print("validate_trace: mesh gate OK — "
              f"{report.measured.get('mesh_stage')} mesh_stage "
              f"dispatch(es) predicted exactly, {attributed} launches "
              "attributed, ledger balanced")
    finally:
        session.stop()


def encoded_gate() -> None:
    """Compressed-execution drift gate (--encoded): a dictionary-heavy
    string-keyed repartition + group-by must (1) produce byte-identical
    results encoded vs decoded (spark.tpu.encoding.enabled differential),
    (2) predict its launch counts EXACTLY on the encoded path — dense-on-
    codes aggregation with ZERO krange3 probes, fused string pids —
    fusion on AND off, and (3) show zero unexplained EXPLAIN ANALYZE
    drift. Self-contained: no trace path required."""
    import numpy as np
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu import TpuSession
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    session = TpuSession("encoded-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 5,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.ui.operatorMetrics": "true",
    })
    try:
        rng = np.random.default_rng(31)
        n = 6000
        session.createDataFrame(pa.table({
            "s": [None if i % 29 == 0 else f"cat{i % 23}"
                  for i in range(n)],
            "v": rng.integers(-20, 80, n),
        })).createOrReplaceTempView("enc_gate_t")

        def q():
            return (session.sql("select s, v from enc_gate_t where v > 0")
                    .repartition(5, "s").groupBy("s")
                    .agg(F.sum("v").alias("sv")))

        outs = {}
        for flag in ("true", "false"):
            session.conf.set("spark.tpu.encoding.enabled", flag)
            outs[flag] = (q().toPandas().sort_values("s", na_position="last")
                          .reset_index(drop=True))
        session.conf.unset("spark.tpu.encoding.enabled")
        if not outs["true"].equals(outs["false"]):
            fail("--encoded: encoded results differ from the decoded "
                 "oracle (dictionary-native kernels changed answers)")

        for fusion in ("true", "false"):
            session.conf.set("spark.tpu.fusion.enabled", fusion)
            report = q().query_execution.analysis_report()
            if not report.exact:
                fail(f"--encoded: plan not exactly predicted (fusion="
                     f"{fusion}): {report.inexact_reasons}")
            if report.predicted_launches.get("krange3"):
                fail("--encoded: dictionary grouping key predicted a "
                     "krange3 probe — the code-domain decision regressed")
            q().toArrow()  # warm
            before = dict(KC.launches_by_kind)
            q().toArrow()
            measured = {k: v - before.get(k, 0)
                        for k, v in KC.launches_by_kind.items()
                        if v != before.get(k, 0)}
            if report.predicted_launches != measured:
                fail(f"--encoded: predicted {report.predicted_launches} "
                     f"!= measured {measured} (fusion={fusion})")
            if measured.get("gagg"):
                fail("--encoded: string group-by took the sort path "
                     f"(fusion={fusion}): {measured} — dense-on-codes "
                     "regressed")
        session.conf.unset("spark.tpu.fusion.enabled")

        report = q().query_execution.analyzed_report()
        errors = [f for f in report.findings if f["severity"] == "error"]
        if errors:
            print(report.render())
            fail("--encoded: EXPLAIN ANALYZE reported unexplained drift "
                 "on the encoded path: "
                 + "; ".join(f["msg"] for f in errors))
        print("validate_trace: encoded gate OK — encoded == decoded, "
              f"{sum(report.measured.values())} launches predicted "
              "exactly fusion on/off, 0 krange3 probes on the "
              "dictionary key")
    finally:
        session.stop()


def adaptive_gate() -> None:
    """Runtime-adaptive execution gate (--adaptive): a selective shuffled
    hash join (2000-key probe ⋈ [5,6,7] build) must (1) produce results
    identical with spark.tpu.adaptive.runtimeFilter on vs off — the
    differential identity, (2) install at least one runtime join filter
    that prunes probe rows before the shuffle, with the install event
    visible in the trace (adaptive.runtime_filter span), (3) degrade the
    launch model HONESTLY (exact=False with a named runtimeFilter
    reason) and show zero unexplained EXPLAIN ANALYZE drift with the
    adaptive layer armed, and (4) leave the device ledger balanced.
    Self-contained: no trace path required."""
    import pyarrow as pa

    import spark_tpu.api.functions as F
    from spark_tpu import TpuSession
    from spark_tpu.obs.resources import GLOBAL_LEDGER

    session = TpuSession("adaptive-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 4,
        "spark.sql.autoBroadcastJoinThreshold": -1,
        "spark.tpu.ui.operatorMetrics": "true",
    })
    try:
        def q():
            a = session.createDataFrame(pa.table({
                "k": list(range(2000)),
                "v": list(range(2000))})).repartition(4)
            b = session.createDataFrame(pa.table({
                "k": [5, 6, 7], "w": [50, 60, 70]})).repartition(2)
            return (a.join(b, on="k").groupBy("k")
                    .agg(F.sum("v").alias("sv")).orderBy("k"))

        outs = {}
        for flag in ("false", "true"):
            session.conf.set("spark.tpu.adaptive.runtimeFilter", flag)
            outs[flag] = q().toArrow().to_pydict()
        if outs["true"] != outs["false"]:
            fail("--adaptive: results differ with the runtime filter on "
                 "vs off (probe pruning changed answers)")

        c = session._metrics.snapshot()["counters"]
        if not c.get("adaptive.runtime_filters_installed"):
            fail("--adaptive: no runtime filter installed on the "
                 "selective join (harvest/install path regressed)")
        if not c.get("adaptive.filter_rows_pruned"):
            fail("--adaptive: filter installed but zero probe rows "
                 "pruned (the exchange never applied it)")
        rf_spans = [s for s in session.tracer.spans()
                    if s and s[0] == "adaptive.runtime_filter"]
        if not rf_spans:
            fail("--adaptive: filter install not visible in the trace "
                 "(no adaptive.runtime_filter span)")

        # the launch model must degrade honestly, not silently: armed
        # adaptive execution is a named inexactness, and EXPLAIN ANALYZE
        # reconciliation must classify the drift rather than error
        report = q().query_execution.analysis_report()
        if report.exact:
            fail("--adaptive: plan_lint claims exact launch counts with "
                 "the runtime filter armed — the model is lying")
        if not any("runtimeFilter" in r for r in report.inexact_reasons):
            fail("--adaptive: inexactness lacks a named runtimeFilter "
                 f"reason: {report.inexact_reasons}")
        report = q().query_execution.analyzed_report()
        errors = [f for f in report.findings if f["severity"] == "error"]
        if errors:
            print(report.render())
            fail("--adaptive: EXPLAIN ANALYZE reported unexplained drift "
                 "with the adaptive layer armed: "
                 + "; ".join(f["msg"] for f in errors))
        session.conf.unset("spark.tpu.adaptive.runtimeFilter")

        issues = GLOBAL_LEDGER.verify()
        if issues:
            fail("--adaptive: device ledger failed verification after "
                 "the adaptive run — " + "; ".join(issues))
        print("validate_trace: adaptive gate OK — on == off, "
              f"{c.get('adaptive.filter_rows_pruned')} probe rows pruned "
              f"by {c.get('adaptive.runtime_filters_installed')} "
              "filter(s), drift classified, ledger balanced")
    finally:
        session.stop()


def whole_query_gate() -> None:
    """Whole-query compilation gate (--whole-query): a q3-shaped star
    join + group-by under spark.tpu.compile.tier=whole must (1) produce
    results identical to the per-stage and operator tiers, (2) execute
    as EXACTLY the predicted whole_query dispatch count per step (one
    plus any predicted join-capacity retries; zero per-stage kernels of
    any kind), (3) show zero unexplained EXPLAIN ANALYZE drift, and (4)
    surface the tier decision in the analysis report (tier + reason).
    Self-contained: no trace path required."""
    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    session = TpuSession("whole-query-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 5,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.ui.operatorMetrics": "true",
    })
    try:
        rng = np.random.default_rng(41)
        n, nd = 9000, 700
        session.createDataFrame(pa.table({
            "date_sk": rng.integers(0, nd, n),
            "item_sk": rng.integers(0, nd, n),
            "price": rng.integers(0, 1000, n),
        })).createOrReplaceTempView("wqg_fact")
        session.createDataFrame(pa.table({
            "d_date_sk": np.arange(nd, dtype=np.int64),
            "d_year": (1998 + np.arange(nd) // 366),
            "d_moy": (1 + np.arange(nd) % 12),
        })).createOrReplaceTempView("wqg_dates")
        session.createDataFrame(pa.table({
            "i_item_sk": np.arange(nd, dtype=np.int64),
            "i_brand_id": (np.arange(nd) % 37),
            "i_manufact_id": (np.arange(nd) % 50),
        })).createOrReplaceTempView("wqg_items")
        sql = ("select d_year, i_brand_id, sum(price) s from wqg_fact "
               "join wqg_dates on date_sk = d_date_sk "
               "join wqg_items on item_sk = i_item_sk "
               "where d_moy = 11 and i_manufact_id = 28 "
               "group by d_year, i_brand_id")

        def q():
            return session.sql(sql)

        outs = {}
        for tier in ("whole", "stage", "operator"):
            session.conf.set("spark.tpu.compile.tier", tier)
            outs[tier] = (q().toPandas()
                          .sort_values(["d_year", "i_brand_id"])
                          .reset_index(drop=True))
        for tier in ("stage", "operator"):
            if not outs["whole"].equals(outs[tier]):
                fail(f"--whole-query: whole-tier results differ from the "
                     f"{tier} tier (in-program lowering changed answers)")

        session.conf.set("spark.tpu.compile.tier", "whole")
        report = q().query_execution.analysis_report()
        if not report.exact:
            fail("--whole-query: whole tier not exactly predicted: "
                 f"{report.inexact_reasons}")
        if (report.tier or {}).get("tier") != "whole":
            fail("--whole-query: tier decision missing from the analysis "
                 f"report: {report.tier}")
        expected = report.predicted_launches
        if set(expected) != {"whole_query"}:
            fail(f"--whole-query: predicted kinds {expected} — per-stage "
                 "kernels leaked into the whole-query program")
        q().toArrow()  # warm
        before = dict(KC.launches_by_kind)
        q().toArrow()
        measured = {k: v - before.get(k, 0)
                    for k, v in KC.launches_by_kind.items()
                    if v != before.get(k, 0)}
        if measured != expected:
            fail(f"--whole-query: measured {measured} != predicted "
                 f"{expected} — the one-dispatch-per-step guarantee "
                 "regressed")

        # the tier decision rides the execution span (obs contract)
        tier_spans = [s for s in session.tracer.spans()
                      if s and s[0] == "whole_query.program"
                      and (s[6] or {}).get("tier") == "whole"]
        if not tier_spans:
            fail("--whole-query: tier decision not visible in spans "
                 "(no whole_query.program span with args.tier=whole)")

        report = q().query_execution.analyzed_report()
        errors = [f for f in report.findings if f["severity"] == "error"]
        if errors:
            print(report.render())
            fail("--whole-query: EXPLAIN ANALYZE reported unexplained "
                 "drift under the whole tier: "
                 + "; ".join(f["msg"] for f in errors))
        session.conf.unset("spark.tpu.compile.tier")
        print("validate_trace: whole-query gate OK — 3 tiers agree, "
              f"{sum(expected.values())} dispatch(es) per step predicted "
              "exactly, tier decision surfaced, zero drift")
    finally:
        session.stop()


def mesh_whole_gate() -> None:
    """Mesh whole-query gate (--mesh-whole, virtual 8-device CPU mesh):
    the ENTIRE sharded join+agg plan — leaves, in-program all-to-alls,
    join build+probe, partial and final aggregate — must execute as ONE
    shard_map dispatch per step under spark.tpu.compile.tier=mesh-whole,
    with (1) results identical to the whole and stage tiers, (2) the
    mesh_whole launch count predicted EXACTLY by plan_lint including a
    quota-doubling retry round on a skewed key, (3) the tier decision
    surfaced on the report and the execution span, and (4) the device
    ledger balanced. Self-contained: no trace path required."""
    import jax
    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.obs.resources import GLOBAL_LEDGER
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC

    if len(jax.devices()) < 4:
        fail("--mesh-whole: needs >=4 virtual devices (run with "
             "JAX_PLATFORMS=cpu "
             "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    session = TpuSession("mesh-whole-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 4,
        "spark.tpu.fusion.minRows": "0",
    })
    try:
        rng = np.random.default_rng(41)
        n, nd = 9000, 700
        session.createDataFrame(pa.table({
            "item_sk": rng.integers(0, nd, n),
            "price": rng.integers(0, 1000, n),
        })).createOrReplaceTempView("mwg_fact")
        session.createDataFrame(pa.table({
            "i_item_sk": np.arange(nd, dtype=np.int64),
            "i_brand_id": (np.arange(nd) % 37),
        })).createOrReplaceTempView("mwg_items")

        def q():
            return (session.sql(
                "select item_sk, price, i_brand_id from mwg_fact "
                "join mwg_items on item_sk = i_item_sk "
                "where price > 100")
                .repartition(4, "i_brand_id")
                .groupBy("i_brand_id").count())

        outs = {}
        for tier in ("mesh-whole", "whole", "stage"):
            session.conf.set("spark.tpu.compile.tier", tier)
            outs[tier] = (q().toPandas().sort_values("i_brand_id")
                          .reset_index(drop=True))
        for tier in ("whole", "stage"):
            if not outs["mesh-whole"].equals(outs[tier]):
                fail(f"--mesh-whole: mesh-tier results differ from the "
                     f"{tier} tier (sharded lowering changed answers)")

        session.conf.set("spark.tpu.compile.tier", "mesh-whole")
        report = q().query_execution.analysis_report()
        if not report.exact:
            fail("--mesh-whole: mesh tier not exactly predicted: "
                 f"{report.inexact_reasons}")
        if (report.tier or {}).get("tier") != "mesh-whole":
            fail("--mesh-whole: tier decision missing from the analysis "
                 f"report: {report.tier}")
        expected = report.predicted_launches
        if set(expected) != {"mesh_whole"}:
            fail(f"--mesh-whole: predicted kinds {expected} — per-stage "
                 "kernels leaked out of the single sharded program")
        q().toArrow()  # warm
        before = dict(KC.launches_by_kind)
        q().toArrow()
        measured = {k: v - before.get(k, 0)
                    for k, v in KC.launches_by_kind.items()
                    if v != before.get(k, 0)}
        if measured != expected:
            fail(f"--mesh-whole: measured {measured} != predicted "
                 f"{expected} — the one-dispatch-per-step guarantee "
                 "regressed")

        # skewed key: one destination shard overflows its exchange quota
        # — the in-program overflow scalar doubles it and the WHOLE
        # program re-dispatches, and the analyzer mirrors the round
        skew = np.zeros(4000, dtype=np.int64)
        skew[:32] = np.arange(32)
        session.createDataFrame(pa.table({
            "sk": skew, "sv": np.arange(4000),
        })).createOrReplaceTempView("mwg_skew")

        def qs():
            return (session.sql("select * from mwg_skew")
                    .repartition(4, "sk").groupBy("sk").count())

        rep_s = qs().query_execution.analysis_report()
        if rep_s.predicted_launches.get("mesh_whole", 0) < 2:
            fail("--mesh-whole: the analyzer never predicted the skew "
                 f"quota-retry round: {rep_s.predicted_launches}")
        qs().toArrow()  # warm (retry rounds recur per fresh execution)
        before = dict(KC.launches_by_kind)
        qs().toArrow()
        measured = {k: v - before.get(k, 0)
                    for k, v in KC.launches_by_kind.items()
                    if v != before.get(k, 0)}
        if measured != rep_s.predicted_launches:
            fail(f"--mesh-whole: skew retry measured {measured} != "
                 f"predicted {rep_s.predicted_launches}")

        tier_spans = [s for s in session.tracer.spans()
                      if s and s[0] == "whole_query.program"
                      and (s[6] or {}).get("tier") == "mesh-whole"]
        if not tier_spans:
            fail("--mesh-whole: tier decision not visible in spans (no "
                 "whole_query.program span with args.tier=mesh-whole)")
        bad = GLOBAL_LEDGER.verify()
        if bad:
            fail("--mesh-whole: device ledger failed verification after "
                 f"the mesh whole-query runs: {bad[:3]}")
        session.conf.unset("spark.tpu.compile.tier")
        print("validate_trace: mesh-whole gate OK — 3 tiers agree, "
              f"{sum(expected.values())} sharded dispatch(es) per step "
              "and the skew retry round predicted exactly, ledger "
              "balanced")
    finally:
        session.stop()


def chaos_gate() -> None:
    """Chaos gate (--chaos, self-contained, fixed seed): deterministic
    fault injection through the regular conf surface must always
    TERMINATE — every injected fault class ends in a correct query
    result or a CLASSIFIED error under a watchdog timeout, never a
    hang. Scenarios: (1) transient block-fetch flap absorbed by the
    bounded fetch retry with ZERO stage regenerations; (2) fetch-retry
    budget exhausted → FetchFailed regeneration still correct, and an
    unbounded failure stream terminates in StageRegenerationLimitError
    with zero leaked shuffle blocks on any worker; (3) transient
    worker-task fault retried on another executor with per-operator
    kernel attribution still equal to driver+worker measured totals
    AFTER the failover; (4) a whole-tier runtime dispatch fault
    degrading to the stage tier with identical results. The device
    ledger must verify balanced at the end."""
    import pickle
    import threading

    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.errors import StageRegenerationLimitError
    from spark_tpu.net.transport import RpcClient
    from spark_tpu.obs.resources import GLOBAL_LEDGER
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
    from spark_tpu.utils import faults

    def watchdog(name, fn, timeout_s=120.0):
        """Every injected fault must terminate — run the scenario under
        a hard wall-clock bound so a hang fails the gate instead of
        wedging CI."""
        out: dict = {}

        def run():
            try:
                out["result"] = fn()
            except BaseException as e:   # re-raised on the gate thread
                out["error"] = e

        t = threading.Thread(target=run, daemon=True, name=f"chaos-{name}")
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            fail(f"--chaos: scenario {name!r} HUNG past {timeout_s}s "
                 "(injected faults must terminate in a result or a "
                 "classified error)")
        if "error" in out:
            raise out["error"]
        return out.get("result")

    session = TpuSession("chaos-gate", {
        "spark.sql.shuffle.partitions": "2",
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.cluster.enabled": "true",
        "spark.tpu.cluster.workers": "2",
    })
    try:
        rng = np.random.default_rng(7)    # fixed seed end to end
        keys = rng.integers(0, 24, 5000)
        vals = rng.integers(-40, 90, 5000)
        session.createDataFrame(pa.table({"k": keys, "v": vals})) \
            .createOrReplaceTempView("cg_t")
        rows = sorted(zip(keys.tolist(), vals.tolist()))

        def set_faults(points):
            session.conf.set("spark.tpu.faults.enabled", "true")
            session.conf.set("spark.tpu.faults.seed", "7")
            session.conf.set("spark.tpu.faults.points", points)
            faults.configure(session.conf)

        def clear_faults():
            session.conf.set("spark.tpu.faults.enabled", "false")
            session.conf.unset("spark.tpu.faults.points")
            faults.configure(session.conf)

        def counters():
            return dict(session._metrics.snapshot()["counters"])

        def shuffle_q():
            return session.table("cg_t").repartition(2)

        def check_rows(df):
            got = sorted((r["k"], r["v"]) for r in df.collect())
            if got != rows:
                fail("--chaos: faulted query returned WRONG rows")

        def scenario_flap():
            set_faults("block.fetch=first:2")
            before = counters()
            check_rows(shuffle_q())
            after = counters()
            clear_faults()
            regens = after.get("scheduler.fetch_failures", 0) \
                - before.get("scheduler.fetch_failures", 0)
            if regens != 0:
                fail(f"--chaos: transient fetch flap cost {regens} stage "
                     "regeneration(s) — the bounded retry did not absorb")
            retries = after.get("shuffle.fetch_retries", 0) \
                - before.get("shuffle.fetch_retries", 0)
            if retries < 1:
                fail("--chaos: fetch flap injected but no retry recorded")

        def scenario_regen_and_cap():
            session.conf.set("spark.tpu.shuffle.fetch.maxRetries", "0")
            set_faults("block.fetch=first:1")
            check_rows(shuffle_q())          # regen path still correct
            session.conf.set("spark.tpu.scheduler.maxStageRegens", "1")
            session.conf.set("spark.tpu.excludeOnFailure.maxFailures",
                             "100")
            set_faults("block.fetch=first:1000")
            try:
                shuffle_q().toArrow()
                fail("--chaos: unbounded fetch failures did NOT raise "
                     "the classified regen-limit error")
            except StageRegenerationLimitError as e:
                if e.error_class != "STAGE_REGENERATION_LIMIT":
                    fail(f"--chaos: wrong error class {e.error_class}")
            finally:
                session.conf.unset("spark.tpu.shuffle.fetch.maxRetries")
                session.conf.unset("spark.tpu.scheduler.maxStageRegens")
                session.conf.unset(
                    "spark.tpu.excludeOnFailure.maxFailures")
                clear_faults()
                session._sql_cluster.health.reset()
            cluster = session._sql_cluster
            for w in cluster.alive_workers():
                with RpcClient(w.client.addr, cluster.authkey_hex) as c:
                    stats = pickle.loads(c.call("block_stats", timeout=10))
                if stats["blocks"]:
                    fail(f"--chaos: failed query leaked {stats['blocks']} "
                         f"shuffle block(s) on {w.executor_id}")

        def scenario_failover_attribution():
            check_rows(shuffle_q())          # warm
            set_faults("worker.task=once")
            before = KC.launches
            df = shuffle_q()
            check_rows(df)
            driver_delta = KC.launches - before
            clear_faults()
            session._sql_cluster.health.reset()
            ctx = df.query_execution._last_ctx
            worker = sum((ctx.worker_kernel_kinds or {}).values())
            graph = df.query_execution.plan_graph()
            attributed = sum(v for nd in graph
                             for v in (nd.get("launches") or {}).values())
            if attributed != driver_delta + worker:
                fail("--chaos: attribution total after failover "
                     f"({attributed}) != driver+worker measured "
                     f"({driver_delta}+{worker})")

        def scenario_tier_degrade():
            local = TpuSession("chaos-gate-local", {
                "spark.sql.shuffle.partitions": "2",
                "spark.tpu.batch.capacity": 1 << 12,
                "spark.sql.adaptive.enabled": "false",
                "spark.tpu.compile.tier": "whole",
            })
            try:
                local.createDataFrame(pa.table({"k": keys, "v": vals})) \
                    .createOrReplaceTempView("cg_t")
                import spark_tpu.api.functions as F

                def q():
                    return (local.table("cg_t").repartition(2)
                            .groupBy("k").agg(F.sum("v").alias("s")))

                healthy = {r["k"]: r["s"] for r in q().collect()}
                local.conf.set("spark.tpu.faults.enabled", "true")
                local.conf.set("spark.tpu.faults.points",
                               "kernel.dispatch=once@whole_query")
                faults.configure(local.conf)
                before = dict(local._metrics.snapshot()["counters"])
                degraded = {r["k"]: r["s"] for r in q().collect()}
                after = dict(local._metrics.snapshot()["counters"])
                if degraded != healthy:
                    fail("--chaos: tier-degraded run returned different "
                         "results from the whole-tier run")
                d = after.get("whole_query.runtime_degraded", 0) \
                    - before.get("whole_query.runtime_degraded", 0)
                if d != 1:
                    fail("--chaos: whole-tier dispatch fault did not "
                         f"degrade to the stage tier (counter delta {d})")
            finally:
                faults.reset()
                local.stop()

        watchdog("flap", scenario_flap)
        watchdog("regen+cap", scenario_regen_and_cap)
        watchdog("failover-attribution", scenario_failover_attribution)
        watchdog("tier-degrade", scenario_tier_degrade)
        issues = GLOBAL_LEDGER.verify()
        if issues:
            fail("--chaos: device ledger unbalanced after chaos run: "
                 + "; ".join(issues))
        print("validate_trace: chaos gate OK — flap absorbed with 0 "
              "regens, regen limit classified + state freed, failover "
              "attribution intact, whole→stage degrade identical, "
              "ledger balanced")
    finally:
        faults.reset()
        session.stop()


def profile_gate() -> None:
    """Query flight recorder gate (--profile, self-contained): the
    fingerprint/store/regression loop must hold end to end. Two
    identical runs ⇒ one fingerprint, two stored profiles, zero
    obs.regression findings (warm runs never regress against their own
    cold baseline); a forced tier flip ⇒ same structural query key,
    different fingerprint, and a severity-error deterministic-counter
    regression finding in both the close hook and the live store; and
    dev/perfcheck.py's comparator flags the same delta against a
    baseline built from the healthy profiles."""
    import tempfile

    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.obs.history import ProfileStore

    tmp = tempfile.mkdtemp(prefix="profile_gate_")
    session = TpuSession("profile-gate", {
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.obs.profileDir": tmp,
    })
    try:
        rng = np.random.default_rng(13)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 9, 4000),
            "v": rng.integers(-20, 80, 4000),
        })).createOrReplaceTempView("pg_t")

        def q():
            return session.sql("select k, sum(v) s from pg_t "
                               "where v > 0 group by k")

        first = q()
        first.toArrow()
        second = q()
        second.toArrow()
        qe = second.query_execution
        if qe._last_profile is None:
            fail("--profile: flight recorder never recorded a profile")
        store = ProfileStore(tmp)
        qk = qe._last_profile["query_key"]
        profs = store.profiles(qk)
        if len(profs) != 2:
            fail(f"--profile: expected 2 stored profiles for the query "
                 f"key, found {len(profs)}")
        fps = {p["fingerprint"] for p in profs}
        if len(fps) != 1:
            fail(f"--profile: identical runs produced {len(fps)} distinct "
                 f"fingerprints ({fps}) — canonicalization is unstable")
        if qe._last_regressions:
            fail("--profile: identical re-run raised regression findings: "
                 + "; ".join(f["msg"] for f in qe._last_regressions))
        # perfcheck comparator: healthy baseline vs itself must be clean
        import importlib.util as _ilu

        spec = _ilu.spec_from_file_location(
            "perfcheck", os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "perfcheck.py"))
        perfcheck = _ilu.module_from_spec(spec)
        spec.loader.exec_module(perfcheck)
        healthy = perfcheck.collect_profiles(tmp)
        regs, _notes = perfcheck.compare(healthy, {"queries": healthy})
        if regs:
            fail("--profile: perfcheck flagged a healthy run against its "
                 "own baseline: " + "; ".join(regs))
        # forced tier flip: same query key, new fingerprint, counter
        # drift detected as a severity-error finding
        session.conf.set("spark.tpu.compile.tier", "operator")
        flipped = q()
        flipped.toArrow()
        session.conf.unset("spark.tpu.compile.tier")
        fqe = flipped.query_execution
        fprof = fqe._last_profile
        if fprof["query_key"] != qk:
            fail("--profile: tier flip changed the structural query key — "
                 "regression detection lost its baseline")
        if fprof["fingerprint"] in fps:
            fail("--profile: tier flip did NOT change the full plan "
                 "fingerprint (compile-cache key is tier-blind)")
        errors = [f for f in fqe._last_regressions
                  if f["severity"] == "error"]
        if not errors:
            fail("--profile: forced tier flip raised no deterministic-"
                 f"counter regression (findings: {fqe._last_regressions})")
        live = session.live_obs.findings_for(
            fqe._last_ctx.query_id)
        if not any(f.get("kind") == "obs.regression" for f in live):
            fail("--profile: regression finding never reached the live "
                 "store (EXPLAIN ANALYZE/live status would miss it)")
        # the same delta must trip perfcheck's cross-commit comparator
        flipped_counters = perfcheck.collect_profiles(tmp)
        regs, _notes = perfcheck.compare(flipped_counters,
                                         {"queries": healthy})
        if not regs:
            fail("--profile: perfcheck comparator missed the tier-flip "
                 "counter delta")
        print("validate_trace: profile gate OK — 1 fingerprint / 2 "
              "profiles / 0 regressions on identical runs; tier flip "
              f"kept query key, changed fingerprint, raised {len(errors)} "
              f"error finding(s) and {len(regs)} perfcheck regression(s)")
    finally:
        session.stop()


# one persist-gate child leg: runs in a REAL subprocess (the warm
# restart must be a fresh process) against the shared cache dir passed
# as argv[1]. Prints one PERSIST json line the parent asserts on.
_PERSIST_LEG = r'''
import json, os, sys
import numpy as np, pyarrow as pa

cache = sys.argv[1]
from spark_tpu import TpuSession
from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
import spark_tpu.exec.persist_cache as pc

session = TpuSession("persist-gate", {
    "spark.tpu.cache.dir": cache,
    "spark.tpu.cache.result.enabled": "false",
    "spark.sql.shuffle.partitions": 2,
    "spark.tpu.batch.capacity": 1 << 12,
    "spark.tpu.fusion.minRows": "0",
    "spark.sql.adaptive.enabled": "false",
    "spark.tpu.obs.profileDir": os.path.join(cache, "profiles"),
})
rng = np.random.default_rng(21)
session.createDataFrame(pa.table({
    "k": rng.integers(0, 9, 4000), "v": rng.integers(-50, 90, 4000),
})).createOrReplaceTempView("pg")
session.createDataFrame(pa.table({
    "k": np.repeat(np.arange(9), 3), "tag": np.arange(27),
})).createOrReplaceTempView("pg_dim")

# leg 1 — compile-cache proof (result cache OFF so queries execute).
# The FIRST run is the one that compiles (and, warm, hits disk): its
# profile must carry the disk-hit attribution.
q = lambda: session.sql(
    "select k, sum(v) s, count(*) c from pg where v > 0 group by k")
df1 = q()
out1 = df1.toArrow()
fp = df1.query_execution.plan_fingerprint()["fingerprint"]
prof = df1.query_execution._last_profile or {}

# leg 2 — whole-tier capacity-retry seeding: the 3x-expanding join
# overflows its output bucket cold; a warm restart's manifest seed must
# collapse the retry (1 dispatch, 0 capacity retries)
session.conf.set("spark.tpu.compile.tier", "whole")
jq = lambda: session.sql(
    "select p.k, count(*) n from pg p join pg_dim d on p.k = d.k "
    "group by p.k")
jrep = jq().query_execution.analysis_report()
c0 = dict(session._metrics.snapshot()["counters"])
jout = jq().toArrow()
c1 = dict(session._metrics.snapshot()["counters"])
session.conf.unset("spark.tpu.compile.tier")
wq = {"predicted": jrep.predicted_launches.get("whole_query"),
      "exact": jrep.exact,
      "dispatches": c1.get("whole_query.dispatches", 0)
      - c0.get("whole_query.dispatches", 0),
      "retries": c1.get("whole_query.capacity_retries", 0)
      - c0.get("whole_query.capacity_retries", 0),
      "rows": jout.num_rows}

# leg 3 — result cache: populate, then the analyzer must predict the
# zero-launch hit path exactly and the repeat must launch nothing
session.conf.set("spark.tpu.cache.result.enabled", "true")
a1 = q().toArrow()
rep = q().query_execution.analysis_report()
l0 = KC.launches
a2 = q().toArrow()
counters = session._metrics.snapshot()["counters"]
print("PERSIST " + json.dumps({
    "fingerprint": fp,
    "compiles": KC.misses,
    "disk_hit_compiles": KC.disk_hit_compiles,
    "disk": pc.disk_counters(),
    "profile_compiles": prof.get("compiles"),
    "profile_disk_hit": prof.get("compiles_disk_hit"),
    "profile_counters": prof.get("counters") or {},
    "wq": wq,
    "rc_predicted": rep.predicted_launches,
    "rc_exact": rep.exact,
    "rc_repeat_launches": KC.launches - l0,
    "rc_hits": int(counters.get("result_cache.hit", 0)),
    "rc_equal": a1.equals(a2),
    "rows": out1.num_rows,
}), flush=True)
'''


def persist_gate() -> None:
    """Persistent-cache gate (--persist, self-contained): the warm-
    restart story must hold across two REAL processes sharing one
    spark.tpu.cache.dir. Cold leg: XLA disk misses populate the cache,
    the whole-tier join pays its capacity retry, the result cache
    populates and answers the repeat with zero launches (plan_lint
    predicting the hit path exactly). Warm leg (fresh process): the
    SAME fingerprints resolve (stability across processes), ZERO XLA
    disk misses with every engine compile disk-served (per-query
    profiles attribute disk-hit vs cold), the manifest seed collapses
    the whole-tier capacity retry to one dispatch (plan_lint mirroring
    the seeded prediction), and the result cache hits cross-process."""
    import subprocess
    import tempfile

    cache = tempfile.mkdtemp(prefix="persist_gate_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def leg(name: str) -> dict:
        proc = subprocess.run(
            [sys.executable, "-c", _PERSIST_LEG, cache],
            env=env, cwd=root, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, timeout=600)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("PERSIST ")]
        if proc.returncode != 0 or not lines:
            fail(f"--persist: {name} leg failed rc={proc.returncode}: "
                 f"{proc.stderr[-800:]}")
        return json.loads(lines[-1][len("PERSIST "):])

    cold = leg("cold")
    # cold-leg invariants: disk cache populated, retry paid, result
    # cache exact on the hit path
    if cold["disk"]["compile.disk_miss"] < 1:
        fail("--persist: cold leg recorded no XLA disk-cache misses — "
             "the persistent compile cache never engaged")
    if cold["wq"]["retries"] < 1 or cold["wq"]["dispatches"] < 2:
        fail(f"--persist: cold whole-tier join did not pay a capacity "
             f"retry ({cold['wq']}) — the warm-start seed has nothing "
             "to prove")
    if cold["wq"]["predicted"] != cold["wq"]["dispatches"] \
            or not cold["wq"]["exact"]:
        fail(f"--persist: cold whole-query prediction "
             f"{cold['wq']['predicted']} != measured dispatches "
             f"{cold['wq']['dispatches']}")
    for c in (cold,):
        if c["rc_predicted"] != {} or not c["rc_exact"]:
            fail(f"--persist: plan_lint did not predict the zero-launch "
                 f"result-cache hit path ({c['rc_predicted']})")
        if c["rc_repeat_launches"] != 0:
            fail(f"--persist: repeated query launched "
                 f"{c['rc_repeat_launches']} kernels through the result "
                 "cache")
        if not c["rc_equal"]:
            fail("--persist: result-cache answer differs from the "
                 "executed answer")
    warm = leg("warm")
    if warm["fingerprint"] != cold["fingerprint"]:
        fail("--persist: plan fingerprint is not stable across "
             f"processes ({cold['fingerprint']} vs "
             f"{warm['fingerprint']}) — every persistent key is dead")
    if warm["disk"]["compile.disk_miss"] != 0:
        fail(f"--persist: warm restart paid "
             f"{warm['disk']['compile.disk_miss']} TRUE cold XLA "
             "compile(s) — the persistent compile cache missed")
    if warm["disk"]["compile.disk_hit"] < 1:
        fail("--persist: warm restart recorded no XLA disk-cache hits")
    if warm["disk_hit_compiles"] < 1:
        fail("--persist: KernelCache attributed no disk-served compiles "
             "on the warm restart")
    if warm["profile_disk_hit"] is None \
            or warm["profile_disk_hit"] < 1 \
            or not any(k == "compile.disk_hit"
                       for k in warm["profile_counters"]):
        fail("--persist: the warm query profile does not attribute "
             f"disk-hit compiles ({warm['profile_disk_hit']}, "
             f"{sorted(warm['profile_counters'])})")
    if warm["wq"]["retries"] != 0 or warm["wq"]["dispatches"] != 1:
        fail(f"--persist: warm whole-tier join replayed the capacity "
             f"ladder ({warm['wq']}) — the manifest seed did not take")
    if warm["wq"]["predicted"] != 1 or not warm["wq"]["exact"]:
        fail(f"--persist: plan_lint did not mirror the seeded "
             f"whole-query attempt count ({warm['wq']['predicted']})")
    if warm["rc_hits"] < 1 or warm["rc_repeat_launches"] != 0 \
            or not warm["rc_equal"]:
        fail(f"--persist: cross-process result-cache hit failed "
             f"(hits={warm['rc_hits']}, "
             f"launches={warm['rc_repeat_launches']})")
    print("validate_trace: persist gate OK — fingerprints stable across "
          f"processes; warm restart: 0 true cold XLA compiles "
          f"({warm['disk']['compile.disk_hit']} disk hits, "
          f"{warm['disk_hit_compiles']} kernels attributed), capacity "
          "retry collapsed 2→1 dispatches via the manifest seed, "
          "repeated query answered with 0 launches (predicted exactly)")


def serve_gate() -> None:
    """Serving gate (--serve, self-contained): deterministic weighted
    fairness, HBM admission, a real concurrent load with scope-exact
    attribution, and graceful drain (see module docstring #10)."""
    import tempfile
    import threading
    import time

    from spark_tpu import TpuSession
    from spark_tpu.config import SQLConf
    from spark_tpu.errors import (
        AdmissionTimeout, ServerDraining,
    )
    from spark_tpu.obs.history import ProfileStore
    from spark_tpu.obs.resources import GLOBAL_LEDGER, MemoryBudgetExceeded
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
    from spark_tpu.serve import FairScheduler, QueryService
    from spark_tpu.serve.loadgen import run_serve_load

    # -- 1: deterministic weighted fairness (no timing, no threads) ------
    conf = SQLConf({"spark.tpu.scheduler.pools": "a:2,b:1",
                    "spark.tpu.serve.maxConcurrent": 1})
    sched = FairScheduler(conf)
    tickets = []
    for _ in range(12):
        tickets.append(sched.submit("a"))
        tickets.append(sched.submit("b"))
    for _ in range(len(tickets)):
        running = [t for t in tickets if t.granted and not t.released]
        if len(running) != 1:
            fail(f"--serve: maxConcurrent=1 but {len(running)} tickets "
                 "hold slots")
        sched.release(running[0])
    grants = sched.contended_grants()
    if grants.get("a", 0) + grants.get("b", 0) < 12:
        fail(f"--serve: too few contended grants to judge fairness "
             f"({grants})")
    ratio = sched.fairness_ratio()
    if ratio is None or ratio > 1.20:
        fail(f"--serve: deterministic stride fairness broken — "
             f"contended grants {grants} (weights 2:1), "
             f"normalized ratio {ratio}")
    if not sched.balanced():
        fail("--serve: scheduler ledger unbalanced after the "
             "deterministic schedule drained")

    # -- 2: HBM admission — reservation blocks, release unblocks ---------
    conf = SQLConf({"spark.tpu.memory.budget": 100})
    sched = FairScheduler(conf)
    big = sched.submit("default", hbm=70)
    sched.wait(big, timeout=1.0)
    small = sched.submit("default", hbm=50)
    try:
        sched.wait(small, timeout=0.05)
        fail("--serve: 50B reservation admitted next to 70B in-flight "
             "under a 100B budget")
    except AdmissionTimeout:
        pass
    small = sched.submit("default", hbm=50)
    sched.release(big)
    sched.wait(small, timeout=1.0)
    sched.release(small)
    if not sched.balanced():
        fail("--serve: HBM reservations leaked through the "
             "admit/timeout/release cycle")

    # -- 3: real concurrent load (8 cloned sessions, 2 pools 2:1) --------
    profile_dir = tempfile.mkdtemp(prefix="serve_gate_prof_")
    session = TpuSession("serve-gate", {
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.obs.profileDir": profile_dir,
        "spark.tpu.scheduler.pools": "dash:2,batch:1",
        "spark.tpu.serve.maxConcurrent": 2,
    })
    try:
        import numpy as np
        import pyarrow as pa

        rng = np.random.default_rng(5)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 16, 4000).astype(np.int64),
            "v": rng.integers(-50, 150, 4000).astype(np.int64),
        })).createOrReplaceTempView("serve_gate_t")
        service = QueryService(session)
        launches_before = KC.launches
        report = run_serve_load(
            service,
            ["select k, sum(v) s from serve_gate_t group by k",
             "select k, v from serve_gate_t where v > 0 "
             "order by v limit 16"],
            sessions=8, reps=3, pools=("dash", "batch"))
        if report["errors"]:
            fail(f"--serve: load queries failed: {report['errors']}")
        kc_delta = KC.launches - launches_before
        store = ProfileStore(profile_dir)
        attributed = 0
        overlapped = 0
        for qk in store.query_keys():
            for p in store.profiles(qk):
                attributed += int(p.get("launch_total", 0))
                if p.get("overlapped"):
                    overlapped += 1
        if overlapped:
            fail(f"--serve: {overlapped} profiles marked overlapped — "
                 "scope-exact per-query deltas regressed to the PR 12 "
                 "overlap guard")
        if attributed != kc_delta:
            fail(f"--serve: per-query attributed launch totals "
                 f"({attributed}) != global KernelCache delta "
                 f"({kc_delta}) — the query ledger leaks or double-"
                 "counts under concurrency")
        ratio = report["fairness_ratio"]
        grants = report["contended_grants"]
        total_contended = sum(grants.values()) if grants else 0
        # judge the live-load ratio only on a real contended sample —
        # with few contended grants the ±1 stride rounding dominates
        # (the deterministic schedule above is the exact 2:1 assertion)
        if len(grants) >= 2 and total_contended >= 12:
            if ratio is None or ratio > 1.25:
                fail(f"--serve: contention fairness ratio {ratio} "
                     f"outside 25% of the 2:1 weights ({grants})")
        # -- 4: over-budget plan rejects PLAN-TIME, never queues ---------
        s2 = service.open_session()
        s2.conf.set("spark.tpu.memory.budget", 1024)
        try:
            service.execute_sql(
                s2, "select k, sum(v) s from serve_gate_t group by k")
            fail("--serve: over-budget plan was admitted (expected "
                 "MemoryBudgetExceeded from the plan-time pre-flight)")
        except MemoryBudgetExceeded:
            pass
        # -- 5: graceful drain -------------------------------------------
        slow = service.scheduler.submit("dash")     # a held in-flight slot
        service.scheduler.wait(slow, timeout=1.0)
        done = {"v": None}

        def _drain():
            done["v"] = service.drain(timeout=10.0)

        th = threading.Thread(target=_drain, daemon=True)
        th.start()
        deadline = time.monotonic() + 2.0
        while not service.scheduler.draining \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        try:
            service.execute_sql(
                service.session, "select count(*) c from serve_gate_t")
            fail("--serve: draining server accepted a new query")
        except ServerDraining:
            pass
        service.scheduler.release(slow)             # in-flight finishes
        th.join(10.0)
        if done["v"] is not True:
            fail(f"--serve: drain did not quiesce ({done['v']})")
        if not service.scheduler.balanced():
            fail("--serve: admission ledger unbalanced after drain "
                 "(leaked slots or HBM reservations)")
        problems = GLOBAL_LEDGER.verify()
        if problems:
            fail(f"--serve: device ledger inconsistent after drain: "
                 f"{problems[:3]}")
    finally:
        session.stop()
    print("validate_trace: serve gate OK — stride fairness 2:1 "
          "(deterministic), HBM admission holds/releases reservations, "
          f"concurrent load attribution exact ({attributed} launches, "
          "0 overlapped profiles), over-budget plans reject plan-time, "
          "drain quiesced with a balanced ledger")


def race_gate() -> None:
    """Race gate (--race, self-contained): runtime validation of the
    static race_lint concurrency model (see module docstring #12). Runs
    the two real concurrent loads CI already trusts — the 8-session
    serve load and a 2-worker cluster leg with a transient block-fetch
    flap plus a deterministic transport-retry exercise — with
    utils/lockwatch.py watching every registered lock, then cross-checks
    the observations against the static model built by
    analysis/race_lint.py."""
    import tempfile
    import threading

    # Watch BEFORE any session exists so module-level registered locks
    # swap to proxies, and export the env var so spawned cluster workers
    # inherit watching through their environment.
    os.environ["SPARK_TPU_LOCKWATCH"] = "1"
    from spark_tpu.utils import faults, lockwatch
    lockwatch.enable()
    lockwatch.reset_observations()

    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.config import SQLConf
    from spark_tpu.net.transport import (
        RETRY_STATS, RetryPolicy, RpcClient, RpcServer,
    )
    from spark_tpu.obs.history import ProfileStore
    from spark_tpu.obs.resources import GLOBAL_LEDGER
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
    from spark_tpu.serve import QueryService
    from spark_tpu.serve.loadgen import run_serve_load

    def watchdog(name, fn, timeout_s=120.0):
        out: dict = {}

        def run():
            try:
                out["result"] = fn()
            except BaseException as e:   # re-raised on the gate thread
                out["error"] = e

        t = threading.Thread(target=run, daemon=True, name=f"race-{name}")
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            fail(f"--race: leg {name!r} HUNG past {timeout_s}s under "
                 "lockwatch — watching must never introduce a deadlock")
        if "error" in out:
            raise out["error"]
        return out.get("result")

    def leg_serve():
        """The serve-gate concurrent load (8 cloned sessions, 2 pools)
        run under watching; attribution must stay scope-exact, proving
        the proxies perturb nothing the obs layer measures."""
        profile_dir = tempfile.mkdtemp(prefix="race_gate_prof_")
        session = TpuSession("race-gate-serve", {
            "spark.sql.shuffle.partitions": 2,
            "spark.tpu.batch.capacity": 1 << 12,
            "spark.tpu.fusion.minRows": "0",
            "spark.tpu.obs.profileDir": profile_dir,
            "spark.tpu.scheduler.pools": "dash:2,batch:1",
            "spark.tpu.serve.maxConcurrent": 2,
        })
        try:
            rng = np.random.default_rng(11)
            session.createDataFrame(pa.table({
                "k": rng.integers(0, 16, 4000).astype(np.int64),
                "v": rng.integers(-50, 150, 4000).astype(np.int64),
            })).createOrReplaceTempView("race_gate_t")
            service = QueryService(session)
            before = KC.launches
            report = run_serve_load(
                service,
                ["select k, sum(v) s from race_gate_t group by k",
                 "select k, v from race_gate_t where v > 0 "
                 "order by v limit 16"],
                sessions=8, reps=2, pools=("dash", "batch"))
            if report["errors"]:
                fail(f"--race: serve load failed under lockwatch: "
                     f"{report['errors']}")
            kc_delta = KC.launches - before
            store = ProfileStore(profile_dir)
            attributed = sum(int(p.get("launch_total", 0))
                             for qk in store.query_keys()
                             for p in store.profiles(qk))
            if attributed != kc_delta:
                fail(f"--race: watched serve load attribution "
                     f"({attributed}) != KernelCache delta ({kc_delta}) "
                     "— lockwatch perturbed the obs scope machinery")
        finally:
            session.stop()

    def leg_cluster():
        """2-worker cluster chaos leg: a transient block-fetch flap must
        still return correct rows with watching live in driver AND
        workers (inherited env), then a deterministic rpc.call flap
        drives the RETRY_STATS locked-counter bump so its guard check
        fires on record. Returns each worker's own lockwatch
        observations (the lockwatch_edges RPC) so the cross-checks
        below cover executor processes, not just the driver."""
        session = TpuSession("race-gate-cluster", {
            "spark.sql.shuffle.partitions": "2",
            "spark.tpu.batch.capacity": 1 << 12,
            "spark.sql.adaptive.enabled": "false",
            "spark.tpu.cluster.enabled": "true",
            "spark.tpu.cluster.workers": "2",
        })
        worker_lw: dict = {}
        try:
            rng = np.random.default_rng(13)
            keys = rng.integers(0, 24, 4000)
            vals = rng.integers(-40, 90, 4000)
            session.createDataFrame(pa.table({"k": keys, "v": vals})) \
                .createOrReplaceTempView("rg_t")
            rows = sorted(zip(keys.tolist(), vals.tolist()))
            session.conf.set("spark.tpu.faults.enabled", "true")
            session.conf.set("spark.tpu.faults.seed", "13")
            session.conf.set("spark.tpu.faults.points",
                             "block.fetch=first:2")
            faults.configure(session.conf)
            got = sorted(
                (r["k"], r["v"]) for r in
                session.table("rg_t").repartition(2).collect())
            if got != rows:
                fail("--race: cluster flap query returned WRONG rows "
                     "under lockwatch")
            # pull each worker's lock observations BEFORE teardown —
            # the executor half of cross-check 2
            cluster = getattr(session, "_sql_cluster", None)
            if cluster is not None:
                worker_lw = cluster.lockwatch_edges()
        finally:
            faults.reset()
            session.stop()

        server = RpcServer("rg")
        server.register("echo", lambda p: p)
        addr = server.start()
        try:
            c = RpcClient(addr, "rg")
            faults.configure(SQLConf({
                "spark.tpu.faults.enabled": "true",
                "spark.tpu.faults.points": "rpc.call=first:1"}))
            before = RETRY_STATS["absorbed"]
            out = c.call("echo", b"y",
                         retry=RetryPolicy(attempts=3, base_ms=1.0,
                                           deadline_s=5.0))
            if out != b"y" or RETRY_STATS["absorbed"] <= before:
                fail("--race: transport retry exercise did not absorb "
                     "the injected flap")
            c.close()
        finally:
            faults.reset()
            server.stop()
        return worker_lw

    try:
        watchdog("serve-load", leg_serve)
        worker_lw = watchdog("cluster-chaos", leg_cluster) or {}

        # -- cross-check 1: every claimed guard was HELD where claimed --
        viol = lockwatch.violations()
        if viol:
            fail(f"--race: {len(viol)} guard check(s) found the claimed "
                 f"lock NOT held at a flagged mutation site, e.g. "
                 f"{viol[0]}")
        checks = lockwatch.guard_checks()
        if not any(site.startswith("net.transport.RETRY_STATS")
                   for site, _lock in checks):
            fail("--race: the RETRY_STATS guard was never exercised — "
                 "the retry leg did not drive the instrumented counter")
        acq = lockwatch.acquire_counts()
        if not acq:
            fail("--race: no watched-lock acquisitions recorded — "
                 "lockwatch was not live during the load")

        # -- cross-check 2: the static and runtime halves share one
        # lock namespace, and their union stays acyclic ----------------
        from spark_tpu.analysis import race_lint
        model = race_lint.build_model(
            [os.path.join(_ROOT, "spark_tpu")], repo_root=_ROOT)
        static_locks = set(model.locks)
        unknown = [n for n in lockwatch.registered_names()
                   if not n.startswith("counter.")
                   and n not in static_locks]
        if unknown:
            fail(f"--race: registered watch slots unknown to the static "
                 f"model: {unknown} — the two halves drifted apart")
        observed = set(lockwatch.order_edges())

        # -- cross-check 2b: the EXECUTOR processes, via the
        # lockwatch_edges RPC the cluster leg collected — workers must
        # have watched (inherited env), reported no guard violations,
        # registered only slots the static model knows, and their
        # acquisition edges fold into the same cycle check -------------
        if not worker_lw:
            fail("--race: no worker answered the lockwatch_edges RPC — "
                 "executor-side lock discipline went unchecked")
        for eid, wp in sorted(worker_lw.items()):
            if not wp.get("enabled"):
                fail(f"--race: worker {eid} ran with lockwatch OFF — "
                     "the env inheritance into executors broke")
            if wp.get("violations"):
                fail(f"--race: worker {eid} recorded guard violations: "
                     f"{wp['violations'][:2]}")
            unknown_w = [n for n in wp.get("names", ())
                         if not n.startswith("counter.")
                         and n not in static_locks]
            if unknown_w:
                fail(f"--race: worker {eid} registered watch slots "
                     f"unknown to the static model: {unknown_w}")
            observed |= {(a, b) for a, b, _n in wp.get("edges", ())}

        static_edges = {tuple(e) for e in model.lock_edges}
        cyc = lockwatch.find_cycle(observed | static_edges)
        if cyc:
            fail("--race: observed acquisition orders close a lock-order "
                 f"cycle the static model missed: {' -> '.join(cyc)}")

        problems = GLOBAL_LEDGER.verify()
        if problems:
            fail(f"--race: device ledger inconsistent after watched "
                 f"run: {problems[:3]}")
    finally:
        lockwatch.disable()
        os.environ.pop("SPARK_TPU_LOCKWATCH", None)

    # disable() must restore RAW locks in every registered slot — the
    # zero-overhead-when-idle claim is structural, so verify structure
    import threading as _threading
    raw_lock_type = type(_threading.Lock())
    if not isinstance(RETRY_STATS._lock, raw_lock_type):
        fail("--race: disable() left a WatchedLock proxy installed — "
             "idle runs would pay the watching overhead")
    print("validate_trace: race gate OK — serve load (8 sessions) and "
          "2-worker chaos leg ran watched with exact attribution, "
          f"{len(checks)} guard site(s) held where claimed, 0 guard "
          f"violations (driver + {len(worker_lw)} workers via the "
          f"lockwatch_edges RPC), {len(observed)} observed acquisition "
          "edge(s) union the static nesting graph acyclic, raw locks "
          "restored on disable")


def metrics_gate() -> None:
    """Metrics gate (--metrics, self-contained): the service metrics
    plane's acceptance identities under a real serve load —

      1. the new lockwatch slots are registered;
      2. structural zero overhead: the kernel-launch delta of the same
         query is IDENTICAL with export on and off;
      3. under a concurrent load with export on: the Prometheus scrape
         parses, the per-pool e2e histogram counts sum EXACTLY to the
         queries the service admitted, per-query attribution stays
         scope-exact, and the drain snapshot froze a non-empty ring;
      4. the static race model still matches its baseline (the new
         locks/threads are modeled, not baselined away).
    """
    import subprocess
    import tempfile

    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.obs import export as mx
    from spark_tpu.obs.history import ProfileStore
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
    from spark_tpu.serve import QueryService
    from spark_tpu.serve.loadgen import run_serve_load
    from spark_tpu.utils import lockwatch

    # -- 1: the metrics plane's locks are lockwatch-registered -----------
    names = set(lockwatch.registered_names())
    for slot in ("obs.export.MetricsRegistry._lock",
                 "obs.export._TS_LOCK"):
        if slot not in names:
            fail(f"--metrics: lock slot {slot!r} is not "
                 "lockwatch-registered — the metrics plane left the "
                 "runtime discipline net")

    # hermetic registry: earlier gates in the same process may have
    # bound sources over their (now-stopped) sessions
    mx.REGISTRY.reset()

    base = {
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.cache.result.enabled": "false",
    }

    # -- 2: zero overhead — launch delta export on == export off ---------
    session = TpuSession("metrics-gate-overhead", dict(base))
    try:
        rng = np.random.default_rng(17)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 16, 4000).astype(np.int64),
            "v": rng.integers(-50, 150, 4000).astype(np.int64),
        })).createOrReplaceTempView("mg_t")
        probe = "select k, sum(v) s from mg_t group by k"
        session.sql(probe).collect()            # compile warmup
        l0 = KC.launches
        session.sql(probe).collect()
        delta_off = KC.launches - l0
        session.conf.set("spark.tpu.metrics.export", "true")
        mx.configure(session.conf)
        mx.register_default_sources(session=session)
        l0 = KC.launches
        session.sql(probe).collect()
        delta_on = KC.launches - l0
        if delta_off <= 0:
            fail("--metrics: overhead probe launched nothing — the "
                 "comparison is vacuous")
        if delta_on != delta_off:
            fail(f"--metrics: export flipped the kernel-launch count "
                 f"({delta_off} off -> {delta_on} on) — the metrics "
                 "plane touched the device path")
    finally:
        session.stop()

    # -- 3: serve load with export on --------------------------------
    profile_dir = tempfile.mkdtemp(prefix="metrics_gate_prof_")
    session = TpuSession("metrics-gate-serve", {
        **base,
        "spark.tpu.obs.profileDir": profile_dir,
        "spark.tpu.scheduler.pools": "dash:2,batch:1",
        "spark.tpu.serve.maxConcurrent": 2,
        "spark.tpu.metrics.export": "true",
        "spark.tpu.metrics.tickInterval": "0.1",
    })
    try:
        rng = np.random.default_rng(19)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 16, 4000).astype(np.int64),
            "v": rng.integers(-50, 150, 4000).astype(np.int64),
        })).createOrReplaceTempView("mg_serve_t")
        queries = ["select k, sum(v) s from mg_serve_t group by k",
                   "select k, v from mg_serve_t where v > 0 "
                   "order by v limit 16"]
        service = QueryService(session)
        launches_before = KC.launches
        warmup = service.open_session()
        for q in queries:
            service.execute_sql(warmup, q)
        sessions_n, reps = 6, 2
        report = run_serve_load(service, queries, sessions=sessions_n,
                                reps=reps, pools=("dash", "batch"))
        if report["errors"]:
            fail(f"--metrics: load queries failed: {report['errors']}")
        # the acceptance identity: every admitted collect — warmup plus
        # the whole load — released through exactly one pool histogram
        expected = len(queries) * (1 + sessions_n * reps)
        try:
            parsed = mx.parse_prometheus(mx.render_prometheus())
        except ValueError as e:
            fail(f"--metrics: /metrics scrape does not parse: {e}")
        e2e_total = sum(
            v for (name, _lbl), v in parsed["samples"].items()
            if name == "spark_tpu_serve_pool_e2e_ms_count")
        if int(e2e_total) != expected:
            fail(f"--metrics: per-pool e2e histogram counts sum to "
                 f"{int(e2e_total)}, expected {expected} admitted "
                 "queries — the admission path leaks or double-counts "
                 "observations")
        if "spark_tpu_kernel_launches" not in parsed["types"]:
            fail("--metrics: scrape is missing the kernel.launches "
                 "series — default sources not wired")
        # attribution must stay scope-exact with the plane live
        kc_delta = KC.launches - launches_before
        store = ProfileStore(profile_dir)
        attributed = sum(int(p.get("launch_total", 0))
                         for qk in store.query_keys()
                         for p in store.profiles(qk))
        if attributed != kc_delta:
            fail(f"--metrics: attributed launches ({attributed}) != "
                 f"KernelCache delta ({kc_delta}) under the metrics "
                 "plane — export perturbed scope attribution")
        service.drain()
        snap = service.drain_snapshot or {}
        if not snap.get("series"):
            fail("--metrics: drain froze an EMPTY time-series ring — "
                 "the ticker never sampled")
    finally:
        session.stop()

    # -- 4: the static race model still matches its baseline ----------
    proc = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "dev", "racecheck.py"),
         "spark_tpu", "--baseline",
         os.path.join(_ROOT, "dev", "race_baseline.json")],
        cwd=_ROOT, capture_output=True, text=True, timeout=300)
    if proc.returncode != 0:
        fail("--metrics: racecheck regressed against its baseline — "
             "the metrics plane introduced unmodeled concurrency:\n"
             + proc.stdout[-800:] + proc.stderr[-400:])

    print("validate_trace: metrics gate OK — scrape parses, per-pool "
          f"e2e histogram counts == {expected} admitted queries, "
          f"attribution exact ({attributed} launches), launch delta "
          f"identical export on/off ({delta_on}), drain snapshot "
          f"{len(snap['series'])} series, racecheck baseline clean")


def bundles_gate() -> None:
    """Black-box gate (--bundles, self-contained): the diagnostic
    bundle layer's acceptance identities —

      1. structural hygiene: the capture layer's lock is
         lockwatch-registered, and with spark.tpu.obs.bundles off the
         module bool stays False (no registry, no scans);
      2. zero-overhead identity: the kernel-launch delta of the same
         query is IDENTICAL armed-but-untriggered vs off, and a healthy
         armed run captures ZERO bundles;
      3. chaos-seeded SLO breach on a 2-worker cluster ⇒ exactly one
         complete self-contained bundle: manifest + trace + plan
         reports + metrics scrape on disk, pulled worker diagnostic
         state (executor-labeled spans, fault-registry counts) inside,
         profile with embedded same-key history, and dev/diagnose.py
         renders the postmortem from the bundle directory alone;
      4. the retention ring prunes to its bound.
    """
    import json as _json
    import subprocess
    import tempfile

    import numpy as np
    import pyarrow as pa

    from spark_tpu import TpuSession
    from spark_tpu.obs import blackbox
    from spark_tpu.physical.compile import GLOBAL_KERNEL_CACHE as KC
    from spark_tpu.serve import QueryService
    from spark_tpu.utils import lockwatch

    # -- 1: structural hygiene -------------------------------------------
    if "obs.blackbox._LOCK" not in set(lockwatch.registered_names()):
        fail("--bundles: obs.blackbox._LOCK is not lockwatch-registered "
             "— the capture layer left the runtime discipline net")

    base = {
        "spark.sql.shuffle.partitions": 2,
        "spark.tpu.batch.capacity": 1 << 12,
        "spark.tpu.fusion.minRows": "0",
        "spark.tpu.cache.result.enabled": "false",
    }
    blackbox.reset()
    bundle_dir = tempfile.mkdtemp(prefix="bundles_gate_")

    # -- 2: zero overhead — launch delta armed == off, healthy ⇒ 0 -------
    session = TpuSession("bundles-gate-overhead", dict(base))
    try:
        if blackbox.ENABLED:
            fail("--bundles: capture layer armed with "
                 "spark.tpu.obs.bundles at its default (off)")
        rng = np.random.default_rng(23)
        session.createDataFrame(pa.table({
            "k": rng.integers(0, 16, 4000).astype(np.int64),
            "v": rng.integers(-50, 150, 4000).astype(np.int64),
        })).createOrReplaceTempView("bg_t")
        probe = "select k, sum(v) s from bg_t group by k"
        session.sql(probe).collect()            # compile warmup
        l0 = KC.launches
        session.sql(probe).collect()
        delta_off = KC.launches - l0
        session.conf.set("spark.tpu.obs.bundles", "true")
        session.conf.set("spark.tpu.obs.bundleDir", bundle_dir)
        blackbox.configure(session.conf)
        if not blackbox.ENABLED:
            fail("--bundles: configure() left the layer unarmed with "
                 "bundles on and a bundle dir set")
        l0 = KC.launches
        session.sql(probe).collect()
        delta_on = KC.launches - l0
        if delta_off <= 0:
            fail("--bundles: overhead probe launched nothing — the "
                 "comparison is vacuous")
        if delta_on != delta_off:
            fail(f"--bundles: arming flipped the kernel-launch count "
                 f"({delta_off} off -> {delta_on} armed) — capture is "
                 "not pull-on-anomaly")
        if blackbox.list_bundles(bundle_dir):
            fail("--bundles: a HEALTHY armed run captured a bundle — "
                 "the trigger predicate fires on non-anomalies")
    finally:
        session.stop()
        blackbox.reset()

    # -- 3: chaos-seeded SLO breach on a 2-worker cluster ----------------
    profile_dir = tempfile.mkdtemp(prefix="bundles_gate_prof_")
    session = TpuSession("bundles-gate-cluster", {
        **base,
        "spark.sql.adaptive.enabled": "false",
        "spark.tpu.cluster.enabled": "true",
        "spark.tpu.cluster.workers": "2",
        "spark.tpu.obs.bundles": "true",
        "spark.tpu.obs.bundleDir": bundle_dir,
        "spark.tpu.obs.profileDir": profile_dir,
        "spark.tpu.metrics.export": "true",
        "spark.tpu.serve.sloMs": "50",
        # deterministic breach: every worker stage task sleeps well past
        # the pool SLO (host-side sleep — results stay exact)
        "spark.tpu.faults.enabled": "true",
        "spark.tpu.faults.seed": "7",
        "spark.tpu.faults.points": "worker.task=always:sleep:0.2",
    })
    try:
        rng = np.random.default_rng(29)
        keys = rng.integers(0, 24, 5000).astype(np.int64)
        vals = rng.integers(-40, 90, 5000).astype(np.int64)
        session.createDataFrame(pa.table({"k": keys, "v": vals})) \
            .createOrReplaceTempView("bg_c")
        service = QueryService(session)
        # explicit repartition: the query MUST run worker map tasks (the
        # chaos gate's worker.task seam) for the pull leg to mean anything
        df = session.table("bg_c").repartition(2)
        table = service.collect(session, df)
        got = sorted(zip(table.column("k").to_pylist(),
                         table.column("v").to_pylist()))
        if got != sorted(zip(keys.tolist(), vals.tolist())):
            fail("--bundles: chaos-seeded query returned wrong rows — "
                 "the breach scenario corrupted results")
        entries = blackbox.list_bundles(bundle_dir)
        if len(entries) != 1:
            fail(f"--bundles: SLO breach captured {len(entries)} "
                 "bundle(s), expected exactly one")
        ent = entries[0]
        if ent.get("trigger_kind") != "obs.slo":
            fail(f"--bundles: bundle trigger is {ent.get('trigger_kind')!r},"
                 " expected obs.slo")
        bid = ent["id"]
        bdir = os.path.join(bundle_dir, f"bundle-{bid}")
        for fname in ("bundle.json", "trace.json", "explain_simple.txt",
                      "explain_analysis.txt", "explain_analyze.txt",
                      "metrics.prom"):
            if not os.path.isfile(os.path.join(bdir, fname)):
                fail(f"--bundles: bundle is missing {fname} — not "
                     "self-contained")
        with open(os.path.join(bdir, "bundle.json")) as f:
            manifest = _json.load(f)
        workers = manifest.get("workers") or {}
        if not workers:
            fail("--bundles: diagnostic_state pull landed NO worker "
                 "state in the bundle")
        ring_tasks = [t for w in workers.values()
                      for t in (w.get("tasks") or [])]
        if not ring_tasks:
            fail("--bundles: pulled worker rings are empty — "
                 "finish_stage_obs did not retain post-task state")
        if not any(t.get("spans") for t in ring_tasks):
            fail("--bundles: pulled worker rings carry no spans")
        if not any((w.get("faults") or {}).get("fired")
                   for w in workers.values()):
            fail("--bundles: no worker fault-registry state in the "
                 "bundle (the injected worker.task rule fired)")
        with open(os.path.join(bdir, "trace.json")) as f:
            trace = _json.load(f)
        procs = {e.get("args", {}).get("name")
                 for e in trace.get("traceEvents", [])
                 if e.get("name") == "process_name"}
        if not any(str(p).startswith("executor ") for p in procs):
            fail(f"--bundles: trace.json has no executor-labeled "
                 f"process track (got {sorted(map(str, procs))})")
        if manifest.get("profile") is None:
            fail("--bundles: bundle carries no query profile — the "
                 "flight recorder section is missing")
        # postmortem renders from the bundle dir alone, out of process
        proc = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "dev", "diagnose.py"),
             bundle_dir, bid],
            cwd=_ROOT, capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        if proc.returncode != 0:
            fail("--bundles: dev/diagnose.py failed on the bundle:\n"
                 + proc.stdout[-400:] + proc.stderr[-400:])
        for marker in ("Trigger timeline", "obs.slo",
                       "Per-executor straggler / HBM map"):
            if marker not in proc.stdout:
                fail(f"--bundles: postmortem report is missing "
                     f"{marker!r}")

        # -- 4: retention ring prunes to its bound -----------------------
        session.conf.set("spark.tpu.obs.bundle.ring", "2")
        blackbox.configure(session.conf)
        for _ in range(4):
            if session.capture_diagnostics(df) is None:
                fail("--bundles: explicit capture_diagnostics returned "
                     "no bundle id")
        left = blackbox.list_bundles(bundle_dir)
        dirs = [d for d in os.listdir(bundle_dir)
                if d.startswith("bundle-")]
        if len(left) > 2 or len(dirs) > 2:
            fail(f"--bundles: retention ring bound 2 violated "
                 f"({len(left)} index entries, {len(dirs)} dirs)")
    finally:
        session.stop()
        blackbox.reset()

    print("validate_trace: bundles gate OK — launch delta identical "
          f"armed/off ({delta_on}), healthy run zero bundles, SLO "
          "breach on the 2-worker cluster captured exactly one "
          f"self-contained bundle ({len(ring_tasks)} pulled worker "
          "task(s), executor trace tracks, fault-registry state), "
          "diagnose.py rendered it offline, retention ring pruned to "
          "bound")


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    cluster = "--cluster" in argv
    live = "--live" in argv
    mesh = "--mesh" in argv
    encoded = "--encoded" in argv
    adaptive = "--adaptive" in argv
    whole = "--whole-query" in argv
    mesh_whole = "--mesh-whole" in argv
    chaos = "--chaos" in argv
    profile = "--profile" in argv
    persist = "--persist" in argv
    serve = "--serve" in argv
    race = "--race" in argv
    metrics = "--metrics" in argv
    bundles = "--bundles" in argv
    argv = [a for a in argv if a not in ("--cluster", "--live", "--mesh",
                                         "--encoded", "--adaptive",
                                         "--whole-query",
                                         "--mesh-whole",
                                         "--chaos", "--profile",
                                         "--persist", "--serve",
                                         "--race", "--metrics",
                                         "--bundles")]
    if (mesh or encoded or adaptive or whole or mesh_whole or chaos
            or profile or persist or serve or race or metrics
            or bundles) and not argv:
        # self-contained legs: these gates generate and validate their
        # own state (dev/run_all.sh runs them without a trace file)
        if mesh:
            mesh_gate()
        if encoded:
            encoded_gate()
        if adaptive:
            adaptive_gate()
        if whole:
            whole_query_gate()
        if mesh_whole:
            mesh_whole_gate()
        if chaos:
            chaos_gate()
        if profile:
            profile_gate()
        if persist:
            persist_gate()
        if serve:
            serve_gate()
        if metrics:
            metrics_gate()
        if bundles:
            bundles_gate()
        if race:
            race_gate()
        print("validate_trace: PASS")
        return 0
    if len(argv) != 1:
        print(__doc__)
        return 2
    validate_trace(argv[0], cluster=cluster)
    drift_gate(cluster=cluster)
    resource_gate()
    if live:
        live_gate()
    if mesh:
        mesh_gate()
    if encoded:
        encoded_gate()
    if adaptive:
        adaptive_gate()
    if whole:
        whole_query_gate()
    if mesh_whole:
        mesh_whole_gate()
    if chaos:
        chaos_gate()
    if profile:
        profile_gate()
    if persist:
        persist_gate()
    if serve:
        serve_gate()
    if metrics:
        metrics_gate()
    if bundles:
        bundles_gate()
    if race:
        race_gate()
    print("validate_trace: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
