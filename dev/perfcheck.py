#!/usr/bin/env python
"""perfcheck: deterministic perf-regression gate over bench smoke profiles.

Wall-clock on the CI box is noise, but kernel launches by kind, compile
counts, and retry attempts are DETERMINISTIC — plan_lint predicts them
exactly, and the query flight recorder (spark_tpu/obs/history.py) now
persists them per plan fingerprint. This gate closes the loop across
commits:

  1. run `bench.py --smoke --profile` (tiny scales, forced CPU) with the
     flight recorder pointed at a scratch directory;
  2. collapse each query key's profiles to its STEADY-STATE deterministic
     counters (min launches per kind across runs — the warm run; max of
     the retry/fault counters — which must stay zero on a healthy run);
  3. diff against the committed `dev/perf_baseline.json` and exit
     non-zero on ANY counter increase, new launch kind, or vanished
     query key.

A legitimate engine change that shifts launch counts (a new fusion rule,
a tier-chooser change) must refresh the baseline CONSCIOUSLY:

  python dev/perfcheck.py --write-baseline

Exit codes: 0 clean, 1 regression (or missing baseline), 2 usage/bench
failure.
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

_HERE = os.path.dirname(os.path.abspath(__file__))
_ROOT = os.path.dirname(_HERE)
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

DEFAULT_BASELINE = os.path.join(_HERE, "perf_baseline.json")

# counters gated here (max across a key's profiles — healthy smoke runs
# must not retry); mirrors obs/history.DETERMINISTIC_COUNTERS
from spark_tpu.obs.history import DETERMINISTIC_COUNTERS, ProfileStore  # noqa: E402

# persistent-cache steady-state counters (exec/persist_cache.py): gated
# the same increase-only way — compile.disk_miss going up means the XLA
# disk cache stopped hitting for a known plan, result_cache.miss going
# up means a repeated query stopped answering from the result cache.
# With the caches off (the default bench --smoke run) both stay 0 and
# the gate is inert; a cache-enabled profile run locks them in.
PERSIST_COUNTERS = ("compile.disk_miss", "result_cache.miss")
GATED_COUNTERS = tuple(DETERMINISTIC_COUNTERS) + PERSIST_COUNTERS


def collect_profiles(profile_dir: str) -> dict:
    """Collapse a profile directory into the gate's shape:
    {query_key: {detail, launches (min per kind), compiles_steady (min),
    counters (max per deterministic counter), runs}}. Min-per-kind is
    the steady state — cold runs legitimately launch memo probes and
    compile; the WARM run is the deterministic quantity."""
    store = ProfileStore(profile_dir)
    out: dict = {}
    for qk in store.query_keys():
        # deltas are scope-exact (per-query kernel ledger, PR 15) —
        # every stored profile gates, including ones recorded under
        # concurrent load
        profs = store.profiles(qk)
        if not profs:
            continue
        launches: dict = {}
        for p in profs:
            for kind, n in (p.get("launches_by_kind") or {}).items():
                cur = launches.get(kind)
                launches[kind] = n if cur is None else min(cur, n)
        counters = {}
        for key in GATED_COUNTERS:
            v = max((p.get("counters") or {}).get(key, 0) for p in profs)
            if v:
                counters[key] = v
        out[qk] = {
            "detail": profs[-1].get("detail", "")[:120],
            "launches": {k: int(v) for k, v in sorted(launches.items())},
            "compiles_steady": int(min(p.get("compiles", 0)
                                       for p in profs)),
            "counters": counters,
            "runs": len(profs),
        }
    return out


def compare(fresh: dict, baseline: dict) -> tuple[list, list]:
    """Diff fresh steady-state counters against the committed baseline.
    Returns (regressions, notes): regressions fail the gate; notes are
    improvements/new queries that suggest a conscious baseline refresh."""
    regressions: list[str] = []
    notes: list[str] = []
    base_q = baseline.get("queries", {})
    for qk, b in sorted(base_q.items()):
        f = fresh.get(qk)
        tag = f"{qk} [{b.get('detail', '')[:60]}]"
        if f is None:
            regressions.append(
                f"{tag}: query key missing from the fresh run — the plan "
                "structure (or its fingerprinting) changed; if "
                "intentional, refresh with --write-baseline")
            continue
        kinds = set(b.get("launches", {})) | set(f.get("launches", {}))
        for kind in sorted(kinds):
            bv = b.get("launches", {}).get(kind, 0)
            fv = f.get("launches", {}).get(kind, 0)
            if fv > bv:
                regressions.append(
                    f"{tag}: steady-state launches '{kind}' {fv} > "
                    f"baseline {bv}")
            elif fv < bv:
                notes.append(
                    f"{tag}: launches '{kind}' improved {bv} -> {fv} "
                    "(refresh the baseline to lock it in)")
        bv = b.get("compiles_steady", 0)
        fv = f.get("compiles_steady", 0)
        if fv > bv:
            regressions.append(
                f"{tag}: steady-state compiles {fv} > baseline {bv} — a "
                "kernel cache key stopped hitting across runs")
        for key in GATED_COUNTERS:
            bv = b.get("counters", {}).get(key, 0)
            fv = f.get("counters", {}).get(key, 0)
            if fv > bv:
                regressions.append(
                    f"{tag}: counter {key} = {fv} > baseline {bv}")
    for qk in sorted(set(fresh) - set(base_q)):
        notes.append(f"{qk} [{fresh[qk].get('detail', '')[:60]}]: new "
                     "query key (not in baseline — add with "
                     "--write-baseline)")
    return regressions, notes


def run_bench_smoke(profile_dir: str) -> int:
    """Run the bench smoke configs with the flight recorder on, in a
    child process (bench.py owns its own session/device lifecycle)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARK_TPU_PROFILE_DIR"] = profile_dir
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    cmd = [sys.executable, os.path.join(_ROOT, "bench.py"),
           "--smoke", "--profile"]
    print(f"perfcheck: running {' '.join(cmd)}")
    proc = subprocess.run(cmd, env=env, cwd=_ROOT,
                          stdout=subprocess.PIPE, text=True)
    sys.stdout.write(proc.stdout[-2000:])
    return proc.returncode


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="perfcheck", description=__doc__)
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--write-baseline", action="store_true",
                    help="(re)write the committed baseline from this "
                         "run's profiles and exit 0")
    ap.add_argument("--profiles", default=None,
                    help="use an existing profile directory instead of "
                         "running bench --smoke --profile")
    args = ap.parse_args(argv)

    if args.profiles:
        profile_dir = args.profiles
    else:
        profile_dir = tempfile.mkdtemp(prefix="perfcheck_profiles_")
        rc = run_bench_smoke(profile_dir)
        if rc != 0:
            print(f"perfcheck: FAIL — bench smoke run exited {rc}")
            return 2
    fresh = collect_profiles(profile_dir)
    if not fresh:
        print(f"perfcheck: FAIL — no profiles recorded in {profile_dir}")
        return 2

    if args.write_baseline:
        doc = {"version": 1,
               "note": "steady-state deterministic counters of the bench "
                       "smoke configs, keyed by structural query key "
                       "(spark_tpu/obs/history.py); regenerate with "
                       "`python dev/perfcheck.py --write-baseline`",
               "queries": fresh}
        with open(args.baseline, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"perfcheck: baseline written to {args.baseline} "
              f"({len(fresh)} query keys)")
        return 0

    if not os.path.isfile(args.baseline):
        print(f"perfcheck: FAIL — no baseline at {args.baseline} (create "
              "one with --write-baseline)")
        return 1
    with open(args.baseline) as f:
        baseline = json.load(f)
    regressions, notes = compare(fresh, baseline)
    for n in notes:
        print(f"perfcheck: note — {n}")
    if regressions:
        for r in regressions:
            print(f"perfcheck: REGRESSION — {r}")
        print(f"perfcheck: FAIL — {len(regressions)} deterministic-counter "
              f"regression(s) vs {args.baseline}")
        return 1
    print(f"perfcheck: OK — {len(fresh)} query keys, steady-state "
          "launches/compiles/retries all within baseline")
    return 0


if __name__ == "__main__":
    sys.exit(main())
