from .graph import Graph  # noqa: F401
