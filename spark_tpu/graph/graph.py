"""Property graphs + Pregel.

Role of the reference's GraphX (graphx/.../Graph.scala, Pregel.scala,
lib/PageRank.scala, ConnectedComponents.scala, TriangleCount.scala).
TPU-native design: vertex ids remap to dense indices; a Pregel superstep is
one jitted array program — messages are edge-wise gathers reduced with
`segment_sum`-family ops onto destination vertices (no per-vertex actors,
no shuffle files). Host loop handles convergence; triangle counting uses a
dense adjacency matmul (MXU) for graphs that fit, with the edge-intersection
path as fallback.
"""

from __future__ import annotations

from typing import Callable

import numpy as np


def _jnp():
    import jax.numpy as jnp

    return jnp


class Graph:
    """vertices: array of external ids (any ints); edges: (src, dst) pairs."""

    def __init__(self, vertex_ids: np.ndarray, src: np.ndarray,
                 dst: np.ndarray, session=None):
        import jax.numpy as jnp

        self.vertex_ids = np.asarray(vertex_ids, dtype=np.int64)
        order = np.argsort(self.vertex_ids, kind="stable")
        self.vertex_ids = self.vertex_ids[order]
        self._index = {int(v): i for i, v in enumerate(self.vertex_ids)}
        self.n = len(self.vertex_ids)
        self.src = jnp.asarray(
            np.searchsorted(self.vertex_ids, np.asarray(src, np.int64)))
        self.dst = jnp.asarray(
            np.searchsorted(self.vertex_ids, np.asarray(dst, np.int64)))
        self.m = int(self.src.shape[0])
        self.session = session

    # --- constructors ------------------------------------------------------
    @staticmethod
    def from_dataframes(vertices_df, edges_df, session=None,
                        id_col: str = "id", src_col: str = "src",
                        dst_col: str = "dst") -> "Graph":
        v = vertices_df.select(id_col).toArrow().column(0).to_numpy(
            zero_copy_only=False)
        e = edges_df.select(src_col, dst_col).toArrow()
        return Graph(v, e.column(0).to_numpy(zero_copy_only=False),
                     e.column(1).to_numpy(zero_copy_only=False),
                     session or vertices_df.session)

    @staticmethod
    def from_edges(src, dst, session=None) -> "Graph":
        ids = np.unique(np.concatenate([np.asarray(src), np.asarray(dst)]))
        return Graph(ids, src, dst, session)

    # --- degrees -----------------------------------------------------------
    def out_degrees(self) -> np.ndarray:
        import jax

        jnp = _jnp()
        return np.asarray(jax.ops.segment_sum(
            jnp.ones(self.m, jnp.int64), self.src, num_segments=self.n))

    def in_degrees(self) -> np.ndarray:
        import jax

        jnp = _jnp()
        return np.asarray(jax.ops.segment_sum(
            jnp.ones(self.m, jnp.int64), self.dst, num_segments=self.n))

    def degrees(self) -> np.ndarray:
        return self.in_degrees() + self.out_degrees()

    # --- Pregel ------------------------------------------------------------
    def pregel(self, initial: np.ndarray,
               superstep: Callable,
               max_iterations: int = 20,
               tol: float = 0.0) -> np.ndarray:
        """superstep(state[n], src_idx[m], dst_idx[m]) -> new state[n].
        The callable is jitted once; iteration stops when max |Δ| ≤ tol."""
        import jax

        jnp = _jnp()
        step = jax.jit(lambda s: superstep(s, self.src, self.dst))
        state = jnp.asarray(initial)
        for _ in range(max_iterations):
            new_state = step(state)
            if tol > 0:
                delta = float(jnp.max(jnp.abs(
                    new_state.astype(jnp.float64)
                    - state.astype(jnp.float64))))
                state = new_state
                if delta <= tol:
                    break
            else:
                state = new_state
        return np.asarray(state)

    # --- algorithms --------------------------------------------------------
    def page_rank(self, num_iter: int = 20, reset_prob: float = 0.15,
                  tol: float = 1e-6) -> dict[int, float]:
        """Power iteration (reference: graphx/lib/PageRank.scala runUntilConvergence)."""
        import jax

        jnp = _jnp()
        outdeg = jnp.asarray(np.maximum(self.out_degrees(), 1).astype(np.float64))
        n = self.n

        def superstep(rank, src, dst):
            contrib = rank[src] / outdeg[src]
            msg = jax.ops.segment_sum(contrib, dst, num_segments=n)
            return reset_prob + (1 - reset_prob) * msg

        ranks = self.pregel(np.full(n, 1.0), superstep,
                            max_iterations=num_iter, tol=tol)
        return {int(v): float(r) for v, r in zip(self.vertex_ids, ranks)}

    def connected_components(self, max_iterations: int = 50) -> dict[int, int]:
        """Label propagation to the minimum reachable id
        (reference: graphx/lib/ConnectedComponents.scala)."""
        import jax

        jnp = _jnp()
        n = self.n
        init = jnp.asarray(self.vertex_ids)

        def superstep(labels, src, dst):
            big = jnp.iinfo(jnp.int64).max
            to_dst = jax.ops.segment_min(labels[src], dst, num_segments=n)
            to_src = jax.ops.segment_min(labels[dst], src, num_segments=n)
            return jnp.minimum(labels, jnp.minimum(
                jnp.where(to_dst == big, labels, to_dst),
                jnp.where(to_src == big, labels, to_src)))

        labels = self.pregel(np.asarray(init), superstep,
                             max_iterations=max_iterations, tol=0.5)
        return {int(v): int(c) for v, c in zip(self.vertex_ids, labels)}

    def triangle_count(self) -> dict[int, int]:
        """Per-vertex triangle counts via adjacency matmul (MXU path;
        reference: graphx/lib/TriangleCount.scala uses set intersections)."""
        jnp = _jnp()
        if self.n > 4096:
            return self._triangle_count_sparse()
        A = np.zeros((self.n, self.n), dtype=np.float32)
        s = np.asarray(self.src)
        d = np.asarray(self.dst)
        keep = s != d
        A[s[keep], d[keep]] = 1.0
        A[d[keep], s[keep]] = 1.0
        Ad = jnp.asarray(A)
        tri = jnp.diagonal(Ad @ Ad @ Ad) / 2.0
        return {int(v): int(round(float(t)))
                for v, t in zip(self.vertex_ids, np.asarray(tri))}

    def _triangle_count_sparse(self) -> dict[int, int]:
        adj: dict[int, set] = {}
        s = np.asarray(self.src)
        d = np.asarray(self.dst)
        for a, b in zip(s, d):
            if a == b:
                continue
            adj.setdefault(int(a), set()).add(int(b))
            adj.setdefault(int(b), set()).add(int(a))
        counts = np.zeros(self.n, dtype=np.int64)
        for a, nbrs in adj.items():
            for b in nbrs:
                if b > a:
                    common = nbrs & adj.get(b, set())
                    for c in common:
                        if c > b:
                            counts[a] += 1
                            counts[b] += 1
                            counts[c] += 1
        return {int(v): int(c) for v, c in zip(self.vertex_ids, counts)}

    def shortest_paths(self, landmarks: list[int],
                       max_iterations: int = 50) -> dict[int, list[float]]:
        """Hop-count shortest paths to landmark vertices
        (reference: graphx/lib/ShortestPaths.scala)."""
        import jax

        jnp = _jnp()
        n = self.n
        inf = np.float64(np.inf)
        init = np.full((n, len(landmarks)), inf)
        for j, lm in enumerate(landmarks):
            init[self._index[int(lm)], j] = 0.0

        def superstep(dist, src, dst):
            via_src = jax.ops.segment_min(dist[src] + 1.0, dst,
                                          num_segments=n)
            via_dst = jax.ops.segment_min(dist[dst] + 1.0, src,
                                          num_segments=n)
            return jnp.minimum(dist, jnp.minimum(via_src, via_dst))

        out = self.pregel(init, superstep, max_iterations=max_iterations,
                          tol=0.5)
        return {int(v): [float(x) for x in row]
                for v, row in zip(self.vertex_ids, out)}

    def to_dataframes(self, session):
        import pyarrow as pa

        v = session.createDataFrame(pa.table({"id": self.vertex_ids}))
        e = session.createDataFrame(pa.table({
            "src": self.vertex_ids[np.asarray(self.src)],
            "dst": self.vertex_ids[np.asarray(self.dst)]}))
        return v, e
