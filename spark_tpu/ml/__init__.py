from .base import Pipeline, PipelineModel, Estimator, Transformer, Model  # noqa: F401
from .features import (  # noqa: F401
    VectorAssembler, StandardScaler, MinMaxScaler, StringIndexer, Binarizer,
    Bucketizer, QuantileDiscretizer, OneHotEncoder, PCA,
)
from .regression import LinearRegression  # noqa: F401
from .classification import LogisticRegression, NaiveBayes  # noqa: F401
from .clustering import KMeans  # noqa: F401
from .evaluation import (  # noqa: F401
    RegressionEvaluator, BinaryClassificationEvaluator,
    MulticlassClassificationEvaluator,
)
from .tuning import (  # noqa: F401
    ParamGridBuilder, CrossValidator, TrainValidationSplit,
)
from .tree import (  # noqa: F401
    DecisionTreeClassifier, DecisionTreeRegressor,
    GBTClassifier, GBTRegressor,
    RandomForestClassifier, RandomForestRegressor,
)
from .recommendation import ALS, ALSModel  # noqa: F401
from .fpm import FPGrowth, FPGrowthModel  # noqa: F401
from .features import (  # noqa: F401
    Imputer, MaxAbsScaler, Normalizer, PolynomialExpansion, RobustScaler,
)
from .regression import IsotonicRegression  # noqa: F401
from .classification import (  # noqa: F401
    LinearSVC, MultilayerPerceptronClassifier,
)
from .clustering import BisectingKMeans, GaussianMixture  # noqa: F401
from .text import (  # noqa: F401
    CountVectorizer, HashingTF, IDF, NGram, RegexTokenizer,
    StopWordsRemover, Tokenizer,
)
