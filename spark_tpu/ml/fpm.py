"""Frequent pattern mining.

Role of the reference's ml/fpm/FPGrowth.scala (FP-tree + conditional
pattern bases) and AssociationRules. Host implementation over transaction
lists — an FP-tree with recursive conditional mining; association rules
derive from the frequent itemsets.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Optional

from .base import Estimator, Model


class _FPNode:
    __slots__ = ("item", "count", "parent", "children", "link")

    def __init__(self, item, parent):
        self.item = item
        self.count = 0
        self.parent = parent
        self.children: dict = {}
        self.link: Optional["_FPNode"] = None


def _build_fp_tree(transactions, min_count):
    counts = defaultdict(int)
    for t in transactions:
        for item in set(t):
            counts[item] += 1
    freq = {i: c for i, c in counts.items() if c >= min_count}
    order = {i: (-c, str(i)) for i, c in freq.items()}

    root = _FPNode(None, None)
    headers: dict = {}
    for t in transactions:
        items = sorted((i for i in set(t) if i in freq),
                       key=lambda i: order[i])
        node = root
        for item in items:
            child = node.children.get(item)
            if child is None:
                child = _FPNode(item, node)
                node.children[item] = child
                if item in headers:
                    child.link = headers[item]
                headers[item] = child
            child.count += 1
            node = child
    return root, headers, freq


def _mine(headers, freq, min_count, suffix, out):
    for item in sorted(freq, key=lambda i: freq[i]):
        itemset = suffix + [item]
        out[frozenset(itemset)] = freq[item]
        # conditional pattern base
        cond_transactions = []
        node = headers.get(item)
        while node is not None:
            path = []
            p = node.parent
            while p is not None and p.item is not None:
                path.append(p.item)
                p = p.parent
            for _ in range(node.count):
                cond_transactions.append(path)
            node = node.link
        if cond_transactions:
            _, h2, f2 = _build_fp_tree(cond_transactions, min_count)
            if f2:
                _mine(h2, f2, min_count, itemset, out)


class FPGrowth(Estimator):
    _params = {"itemsCol": "items", "minSupport": 0.3, "minConfidence": 0.8}

    def fit(self, df) -> "FPGrowthModel":
        col = self.getOrDefault("itemsCol")
        raw = df.select(col).toArrow().column(0).to_pylist()
        transactions = [t if isinstance(t, (list, tuple))
                        else str(t).split() for t in raw]
        n = len(transactions)
        min_count = max(1, int(self.getOrDefault("minSupport") * n))

        _, headers, freq = _build_fp_tree(transactions, min_count)
        itemsets: dict = {}
        _mine(headers, freq, min_count, [], itemsets)

        m = FPGrowthModel(itemsCol=col,
                          minConfidence=self.getOrDefault("minConfidence"))
        m.num_transactions = n
        m.freq_itemsets = itemsets
        return m


class FPGrowthModel(Model):
    _params = {"itemsCol": "items", "minConfidence": 0.8}

    def freqItemsets(self):
        """[(items, count)] sorted by count desc."""
        return sorted(((sorted(k), v) for k, v in self.freq_itemsets.items()),
                      key=lambda kv: (-kv[1], kv[0]))

    def associationRules(self):
        """[(antecedent, consequent, confidence, lift)]."""
        rules = []
        minc = self.getOrDefault("minConfidence")
        n = self.num_transactions
        for itemset, count in self.freq_itemsets.items():
            if len(itemset) < 2:
                continue
            for item in itemset:
                antecedent = itemset - {item}
                a_count = self.freq_itemsets.get(antecedent)
                if not a_count:
                    continue
                conf = count / a_count
                if conf >= minc:
                    c_support = self.freq_itemsets.get(
                        frozenset({item}), 0) / n
                    lift = conf / c_support if c_support else float("inf")
                    rules.append((sorted(antecedent), [item], conf, lift))
        return sorted(rules, key=lambda r: (-r[2], r[0]))

    def transform(self, df):
        """Predict consequents per row from matching rules (reference:
        FPGrowthModel.transform)."""
        import numpy as np
        import pyarrow as pa

        col = self.getOrDefault("itemsCol")
        raw = df.select(col).toArrow().column(0).to_pylist()
        rules = self.associationRules()
        preds = []
        for t in raw:
            items = set(t if isinstance(t, (list, tuple))
                        else str(t).split())
            out = []
            for ante, cons, _conf, _lift in rules:
                if set(ante) <= items and cons[0] not in items and \
                        cons[0] not in out:
                    out.append(cons[0])
            preds.append(" ".join(str(x) for x in out))
        table = df.toArrow().append_column(
            "prediction", pa.array(preds, pa.string()))
        return df.session.createDataFrame(table)
