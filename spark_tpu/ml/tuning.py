"""Hyper-parameter tuning (reference: ml/tuning/CrossValidator.scala,
ParamGridBuilder)."""

from __future__ import annotations

import numpy as np

from .base import Estimator, Model


class ParamGridBuilder:
    def __init__(self):
        self._grid: dict[str, list] = {}

    def addGrid(self, param: str, values) -> "ParamGridBuilder":
        self._grid[param] = list(values)
        return self

    def build(self) -> list[dict]:
        import itertools

        keys = list(self._grid)
        combos = itertools.product(*[self._grid[k] for k in keys])
        return [dict(zip(keys, c)) for c in combos]


class CrossValidator(Estimator):
    _params = {"estimator": None, "estimatorParamMaps": (),
               "evaluator": None, "numFolds": 3, "seed": 42,
               "parallelism": 1}

    def fit(self, df) -> "CrossValidatorModel":
        est = self.getOrDefault("estimator")
        grid = list(self.getOrDefault("estimatorParamMaps")) or [{}]
        ev = self.getOrDefault("evaluator")
        k = int(self.getOrDefault("numFolds"))
        par = max(1, int(self.getOrDefault("parallelism")))

        table = df.toArrow()
        n = table.num_rows
        rng = np.random.default_rng(self.getOrDefault("seed"))
        fold = rng.integers(0, k, n)

        session = df.session
        import pyarrow as pa

        # pre-split once: every (params, fold) task shares the k splits
        splits = []
        for f in range(k):
            train = session.createDataFrame(table.filter(pa.array(fold != f)))
            test = session.createDataFrame(table.filter(pa.array(fold == f)))
            train._ml_features = getattr(df, "_ml_features", None)
            test._ml_features = getattr(df, "_ml_features", None)
            splits.append((train, test))

        def one(task):
            params, (train, test) = task
            model = est.copy(params).fit(train)
            return ev.evaluate(model.transform(test))

        tasks = [(params, split) for params in grid for split in splits]
        if par > 1:
            # reference: CrossValidator.parallelism fits param maps
            # concurrently; each fit's device work is jit-compiled, so
            # host threads overlap the python/solve phases
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(par) as pool:
                scores = list(pool.map(one, tasks))
        else:
            scores = [one(t) for t in tasks]
        avg_metrics = [float(np.mean(scores[i * k:(i + 1) * k]))
                       for i in range(len(grid))]

        higher_better = ev.getOrDefault("metricName") not in (
            "rmse", "mse", "mae")
        best_i = int(np.argmax(avg_metrics) if higher_better
                     else np.argmin(avg_metrics))
        best_model = est.copy(grid[best_i]).fit(df)
        out = CrossValidatorModel()
        out.bestModel = best_model
        out.avgMetrics = avg_metrics
        return out


class CrossValidatorModel(Model):
    _params = {}

    def transform(self, df):
        return self.bestModel.transform(df)


class TrainValidationSplit(Estimator):
    _params = {"estimator": None, "estimatorParamMaps": (),
               "evaluator": None, "trainRatio": 0.75, "seed": 42}

    def fit(self, df):
        import pyarrow as pa

        est = self.getOrDefault("estimator")
        grid = list(self.getOrDefault("estimatorParamMaps")) or [{}]
        ev = self.getOrDefault("evaluator")
        table = df.toArrow()
        rng = np.random.default_rng(self.getOrDefault("seed"))
        is_train = rng.random(table.num_rows) < self.getOrDefault("trainRatio")
        session = df.session
        train = session.createDataFrame(table.filter(pa.array(is_train)))
        test = session.createDataFrame(table.filter(pa.array(~is_train)))
        train._ml_features = getattr(df, "_ml_features", None)
        test._ml_features = getattr(df, "_ml_features", None)
        scores = [ev.evaluate(est.copy(p).fit(train).transform(test))
                  for p in grid]
        higher_better = ev.getOrDefault("metricName") not in (
            "rmse", "mse", "mae")
        best_i = int(np.argmax(scores) if higher_better
                     else np.argmin(scores))
        out = CrossValidatorModel()
        out.bestModel = est.copy(grid[best_i]).fit(df)
        out.avgMetrics = scores
        return out
