"""ALS collaborative filtering.

Role of the reference's ml/recommendation/ALS.scala. TPU-native: the
alternating least-squares updates are BATCHED ridge solves — every user's
(k×k) normal-equation system is built with `segment_sum` over the rating
edges and solved with a batched `jnp.linalg.solve` (MXU path) — instead of
the reference's per-block Cholesky loops.
"""

from __future__ import annotations

import numpy as np

from .base import Estimator, Model, with_host_column


class ALS(Estimator):
    _params = {"userCol": "user", "itemCol": "item", "ratingCol": "rating",
               "rank": 8, "maxIter": 10, "regParam": 0.1, "seed": 42,
               "predictionCol": "prediction",
               "implicitPrefs": False, "alpha": 1.0}

    def fit(self, df) -> "ALSModel":
        import jax
        import jax.numpy as jnp

        users_raw = np.asarray(df.select(self.getOrDefault("userCol"))
                               .toArrow().column(0).to_numpy(
                                   zero_copy_only=False))
        items_raw = np.asarray(df.select(self.getOrDefault("itemCol"))
                               .toArrow().column(0).to_numpy(
                                   zero_copy_only=False))
        ratings = np.asarray(df.select(self.getOrDefault("ratingCol"))
                             .toArrow().column(0).to_numpy(
                                 zero_copy_only=False), dtype=np.float64)

        uids, u_idx = np.unique(users_raw, return_inverse=True)
        iids, i_idx = np.unique(items_raw, return_inverse=True)
        nu, ni = len(uids), len(iids)
        k = int(self.getOrDefault("rank"))
        lam = float(self.getOrDefault("regParam"))
        rng = np.random.default_rng(self.getOrDefault("seed"))

        ue = jnp.asarray(u_idx)
        ie = jnp.asarray(i_idx)
        r = jnp.asarray(ratings)

        implicit = bool(self.getOrDefault("implicitPrefs"))
        alpha = float(self.getOrDefault("alpha"))

        def make_solver(n_out: int):
            """Batched ridge solve: for each output row, A = Σ ff^T + λI,
            b = Σ rating·f over its edges (n_out is compile-time static).

            Implicit mode (reference: ALS.scala implicitPrefs; Hu,
            Koren & Volinsky 2008): confidence c = 1 + α·r over observed
            edges, preference p = 1; A = YᵀY + Σ (c−1)·ffᵀ + λI and
            b = Σ c·f — the global YᵀY term stands in for the full
            all-pairs sum, so the MXU does one [n,k]ᵀ[n,k] matmul
            instead of nu×ni pair work."""

            @jax.jit
            def solve(fixed, edge_fixed, edge_out):
                f = fixed[edge_fixed]                  # [m, k]
                if implicit:
                    cm1 = alpha * jnp.maximum(r, 0.0)  # confidence − 1
                    outer = cm1[:, None, None] * \
                        (f[:, :, None] * f[:, None, :])
                    A = fixed.T @ fixed + \
                        jax.ops.segment_sum(outer, edge_out,
                                            num_segments=n_out)
                    b = jax.ops.segment_sum((1.0 + cm1)[:, None] * f,
                                            edge_out, num_segments=n_out)
                else:
                    outer = f[:, :, None] * f[:, None, :]  # [m, k, k]
                    A = jax.ops.segment_sum(outer, edge_out,
                                            num_segments=n_out)
                    b = jax.ops.segment_sum(f * r[:, None], edge_out,
                                            num_segments=n_out)
                A = A + lam * jnp.eye(k)[None]
                return jnp.linalg.solve(A, b[..., None])[..., 0]

            return solve

        solve_users = make_solver(nu)
        solve_items = make_solver(ni)

        # ALS is non-convex: run a few restarts and keep the best training
        # error (the reference mitigates with its blocked solver init; a
        # restart is the simple robust answer at this scale)
        best = None
        for attempt in range(3):
            U = jnp.asarray(rng.normal(0, 0.1, (nu, k)))
            V = jnp.asarray(rng.normal(0, 0.1, (ni, k)))
            for _ in range(int(self.getOrDefault("maxIter"))):
                U = solve_users(V, ie, ue)
                V = solve_items(U, ue, ie)
            pred = (U[ue] * V[ie]).sum(1)
            if implicit:
                # implicit fits preference 1 with confidence weights
                c = 1.0 + alpha * jnp.maximum(r, 0.0)
                err = float(jnp.mean(c * (1.0 - pred) ** 2))
            else:
                err = float(jnp.mean(jnp.abs(pred - r)))
            if best is None or err < best[0]:
                best = (err, U, V)
            if err < 1e-3:
                break
        _, U, V = best

        m = ALSModel(userCol=self.getOrDefault("userCol"),
                     itemCol=self.getOrDefault("itemCol"),
                     predictionCol=self.getOrDefault("predictionCol"))
        m.user_ids = uids
        m.item_ids = iids
        m.user_factors = np.asarray(U)
        m.item_factors = np.asarray(V)
        return m


class ALSModel(Model):
    _params = {"userCol": "user", "itemCol": "item",
               "predictionCol": "prediction"}

    def transform(self, df):
        users = np.asarray(df.select(self.getOrDefault("userCol"))
                           .toArrow().column(0).to_numpy(
                               zero_copy_only=False))
        items = np.asarray(df.select(self.getOrDefault("itemCol"))
                           .toArrow().column(0).to_numpy(
                               zero_copy_only=False))
        u = np.searchsorted(self.user_ids, users)
        i = np.searchsorted(self.item_ids, items)
        u = np.clip(u, 0, len(self.user_ids) - 1)
        i = np.clip(i, 0, len(self.item_ids) - 1)
        known = (self.user_ids[u] == users) & (self.item_ids[i] == items)
        pred = (self.user_factors[u] * self.item_factors[i]).sum(axis=1)
        pred = np.where(known, pred, np.nan)
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)

    def recommend_for_user(self, user, n: int = 10):
        idx = np.searchsorted(self.user_ids, user)
        scores = self.item_factors @ self.user_factors[idx]
        top = np.argsort(-scores)[:n]
        return [(self.item_ids[t], float(scores[t])) for t in top]
