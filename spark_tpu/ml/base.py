"""ML pipeline abstractions.

Role of the reference's ml API (mllib/.../ml/Pipeline.scala, Estimator.scala,
Transformer.scala, param/params.scala). The compute design is TPU-first:
estimators pull feature columns into device matrices and train with jitted
full-batch gradient steps (the MXU matmul path) instead of the reference's
breeze/netlib row-iterator optimizers.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Sequence

import numpy as np
import pyarrow as pa


class Params:
    """Typed param map: subclasses declare defaults as class attrs in
    `_params`."""

    _params: dict[str, Any] = {}

    def __init__(self, **kwargs):
        self._values = dict(type(self)._params)
        for k, v in kwargs.items():
            self._set(k, v)

    def _set(self, k: str, v: Any):
        if k not in self._values:
            raise ValueError(
                f"{type(self).__name__} has no param {k!r}; "
                f"has {sorted(self._values)}")
        self._values[k] = v
        return self

    def getOrDefault(self, k: str):
        return self._values[k]

    def __getattr__(self, k):
        values = object.__getattribute__(self, "__dict__").get("_values")
        if values is not None and k in values:
            return values[k]
        raise AttributeError(k)

    def copy(self, extra: dict | None = None):
        c = copy.deepcopy(self)
        for k, v in (extra or {}).items():
            c._set(k, v)
        return c

    def set(self, **kwargs):
        for k, v in kwargs.items():
            self._set(k, v)
        return self


class Transformer(Params):
    def transform(self, df):
        raise NotImplementedError


class Estimator(Params):
    def fit(self, df) -> Transformer:
        raise NotImplementedError


class Model(Transformer):
    pass


class Pipeline(Estimator):
    _params = {"stages": ()}

    def fit(self, df) -> "PipelineModel":
        fitted = []
        cur = df
        stages = list(self.getOrDefault("stages"))
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < len(stages) - 1:
                    cur = model.transform(cur)
            else:
                fitted.append(stage)
                if i < len(stages) - 1:
                    cur = stage.transform(cur)
        return PipelineModel(stages=tuple(fitted))


class PipelineModel(Model):
    _params = {"stages": ()}

    def transform(self, df):
        cur = df
        for stage in self.getOrDefault("stages"):
            cur = stage.transform(cur)
        return cur


# ---------------------------------------------------------------------------
# feature-matrix plumbing
# ---------------------------------------------------------------------------

def resolve_feature_cols(df, features_col: str) -> list[str]:
    """A 'features vector' column is represented as recorded assembler
    metadata (TPU-first: features live as a [n, d] device matrix, not
    per-row vector objects — see VectorAssembler)."""
    meta = getattr(df, "_ml_features", None)
    if meta and features_col in meta:
        return meta[features_col]
    if features_col in df.columns:
        return [features_col]
    raise ValueError(
        f"features column {features_col!r} not found; run VectorAssembler "
        "or name real columns")


def extract_matrix(df, cols: Sequence[str]) -> np.ndarray:
    """[n, d] float matrix from scalar columns and/or fixed-width list
    columns (HashingTF/CountVectorizer vectors are list<double> — each
    contributes its width in columns)."""
    table = df.select(*cols).toArrow()
    blocks = []
    for c in table.column_names:
        col = table.column(c)
        if pa.types.is_list(col.type) or pa.types.is_large_list(col.type) \
                or pa.types.is_fixed_size_list(col.type):
            blocks.append(np.asarray(col.to_pylist(), dtype=np.float64))
        else:
            blocks.append(np.asarray(
                col.to_numpy(zero_copy_only=False),
                dtype=np.float64)[:, None])
    return np.concatenate(blocks, axis=1)


def extract_vector(df, col: str) -> np.ndarray:
    table = df.select(col).toArrow()
    return np.asarray(table.column(0).to_numpy(zero_copy_only=False),
                      dtype=np.float64)


def with_host_column(df, name: str, values: np.ndarray):
    """Append a host-computed column (prediction outputs)."""
    table = df.toArrow()
    arr = pa.array(values)
    if name in table.column_names:
        table = table.drop_columns([name])
    table = table.append_column(name, arr)
    out = df.session.createDataFrame(table)
    out._ml_features = getattr(df, "_ml_features", None)
    return out
