"""Evaluators (reference: ml/evaluation/*)."""

from __future__ import annotations

import numpy as np

from .base import Params, extract_vector


class RegressionEvaluator(Params):
    _params = {"labelCol": "label", "predictionCol": "prediction",
               "metricName": "rmse"}

    def evaluate(self, df) -> float:
        y = extract_vector(df, self.getOrDefault("labelCol"))
        p = extract_vector(df, self.getOrDefault("predictionCol"))
        m = self.getOrDefault("metricName")
        if m == "rmse":
            return float(np.sqrt(np.mean((y - p) ** 2)))
        if m == "mse":
            return float(np.mean((y - p) ** 2))
        if m == "mae":
            return float(np.mean(np.abs(y - p)))
        if m == "r2":
            ss_res = np.sum((y - p) ** 2)
            ss_tot = np.sum((y - y.mean()) ** 2)
            return float(1 - ss_res / ss_tot) if ss_tot else 0.0
        raise ValueError(m)


class BinaryClassificationEvaluator(Params):
    _params = {"labelCol": "label", "rawPredictionCol": "probability",
               "metricName": "areaUnderROC"}

    def evaluate(self, df) -> float:
        y = extract_vector(df, self.getOrDefault("labelCol"))
        s = extract_vector(df, self.getOrDefault("rawPredictionCol"))
        order = np.argsort(-s, kind="stable")
        y = y[order]
        pos = y.sum()
        neg = len(y) - pos
        if pos == 0 or neg == 0:
            return 0.5
        # AUC via rank statistic
        ranks = np.empty(len(s))
        ranks[np.argsort(-s, kind="stable")] = np.arange(1, len(s) + 1)
        pos_rank_sum = ranks[extract_vector(
            df, self.getOrDefault("labelCol")) == 1].sum()
        auc = (len(s) * pos + pos * (pos + 1) / 2 - pos_rank_sum) / (pos * neg)
        return float(auc)


class MulticlassClassificationEvaluator(Params):
    _params = {"labelCol": "label", "predictionCol": "prediction",
               "metricName": "accuracy"}

    def evaluate(self, df) -> float:
        y = extract_vector(df, self.getOrDefault("labelCol"))
        p = extract_vector(df, self.getOrDefault("predictionCol"))
        m = self.getOrDefault("metricName")
        if m == "accuracy":
            return float(np.mean(y == p))
        if m == "f1":
            classes = np.unique(np.concatenate([y, p]))
            f1s = []
            weights = []
            for c in classes:
                tp = np.sum((p == c) & (y == c))
                fp = np.sum((p == c) & (y != c))
                fn = np.sum((p != c) & (y == c))
                prec = tp / (tp + fp) if tp + fp else 0.0
                rec = tp / (tp + fn) if tp + fn else 0.0
                f1s.append(2 * prec * rec / (prec + rec)
                           if prec + rec else 0.0)
                weights.append(np.sum(y == c))
            return float(np.average(f1s, weights=weights))
        raise ValueError(m)
