"""Regression estimators.

Role of the reference's ml regression (ml/regression/LinearRegression.scala —
breeze LBFGS/WLS there). TPU-native: full-batch jitted gradient descent /
normal equations — the [n, d] feature matrix rides the MXU.
"""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, extract_matrix, extract_vector, resolve_feature_cols,
    with_host_column,
)


def _jax():
    import jax

    return jax


class LinearRegression(Estimator):
    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "regParam": 0.0,
               "elasticNetParam": 0.0, "maxIter": 100, "fitIntercept": True,
               "solver": "normal"}  # normal | gd

    def fit(self, df) -> "LinearRegressionModel":
        import jax.numpy as jnp

        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        if self.getOrDefault("fitIntercept"):
            X = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
        lam = float(self.getOrDefault("regParam"))

        if self.getOrDefault("solver") == "normal":
            Xd = jnp.asarray(X)
            yd = jnp.asarray(y)
            A = Xd.T @ Xd + lam * jnp.eye(X.shape[1])
            b = Xd.T @ yd
            w = np.asarray(jnp.linalg.solve(A, b))
        else:
            w = _gd_fit(X, y, lam, int(self.getOrDefault("maxIter")),
                        kind="linear")

        m = LinearRegressionModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"))
        if self.getOrDefault("fitIntercept"):
            m.coefficients = w[:-1]
            m.intercept = float(w[-1])
        else:
            m.coefficients = w
            m.intercept = 0.0
        m.cols = cols
        return m


class LinearRegressionModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        pred = X @ self.coefficients + self.intercept
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)


def _gd_fit(X: np.ndarray, y: np.ndarray, lam: float, iters: int,
            kind: str, lr: float | None = None) -> np.ndarray:
    """Jitted full-batch gradient descent (lax.scan over steps — one XLA
    program for the whole optimization)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n, d = X.shape
    Xd = jnp.asarray(X)
    yd = jnp.asarray(y)
    if lr is None:
        # 1/L with L ≈ largest eigenvalue bound of X^T X / n
        lr = float(n) / (np.linalg.norm(X, ord="fro") ** 2 + 1e-12)
        if kind == "logistic":
            lr *= 4.0

    def grad_fn(w):
        z = Xd @ w
        if kind == "linear":
            r = z - yd
            return (Xd.T @ r) / n + lam * w
        p = jax.nn.sigmoid(z)
        return (Xd.T @ (p - yd)) / n + lam * w

    @jax.jit
    def run(w0):
        def step(w, _):
            return w - lr * grad_fn(w), None

        w, _ = lax.scan(step, w0, None, length=iters)
        return w

    return np.asarray(run(jnp.zeros(d)))


class IsotonicRegression(Estimator):
    """Monotone fit via pool-adjacent-violators
    (ml/regression/IsotonicRegression.scala)."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "isotonic": True}

    def fit(self, df) -> "IsotonicRegressionModel":
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        x = extract_matrix(df, cols)[:, 0]
        y = extract_vector(df, self.getOrDefault("labelCol"))
        if not self.getOrDefault("isotonic"):
            y = -y
        order = np.argsort(x, kind="stable")
        xs, ys = x[order], y[order].astype(np.float64)
        # PAVA: merge adjacent violating blocks
        vals = list(ys)
        wts = [1.0] * len(ys)
        i = 0
        while i < len(vals) - 1:
            if vals[i] > vals[i + 1] + 1e-15:
                tot = vals[i] * wts[i] + vals[i + 1] * wts[i + 1]
                w = wts[i] + wts[i + 1]
                vals[i:i + 2] = [tot / w]
                wts[i:i + 2] = [w]
                if i > 0:
                    i -= 1
            else:
                i += 1
        fitted = np.repeat(np.asarray(vals),
                           np.asarray(wts, dtype=np.int64))
        if not self.getOrDefault("isotonic"):
            fitted = -fitted
        m = IsotonicRegressionModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"))
        m.boundaries = xs
        m.predictions = fitted
        m.cols = cols
        return m


class IsotonicRegressionModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        x = extract_matrix(df, self.cols)[:, 0]
        idx = np.clip(np.searchsorted(self.boundaries, x, side="right") - 1,
                      0, len(self.predictions) - 1)
        return with_host_column(df, self.getOrDefault("predictionCol"),
                                self.predictions[idx])
