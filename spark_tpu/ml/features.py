"""Feature transformers (reference: mllib ml/feature/*)."""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, Transformer, extract_matrix, resolve_feature_cols,
    with_host_column,
)


class VectorAssembler(Transformer):
    """Records which columns make the [n, d] feature matrix
    (reference: ml/feature/VectorAssembler.scala; see base.py on the
    matrix-not-vector-objects design)."""

    _params = {"inputCols": (), "outputCol": "features"}

    def transform(self, df):
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = list(
            self.getOrDefault("inputCols"))
        out = df._with(df.plan)
        out._ml_features = meta
        return out


class StandardScaler(Estimator):
    _params = {"inputCol": "features", "outputCol": "scaled",
               "withMean": True, "withStd": True}

    def fit(self, df) -> "StandardScalerModel":
        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        mean = X.mean(axis=0)
        std = X.std(axis=0)
        std[std == 0] = 1.0
        return StandardScalerModel(
            inputCol=self.getOrDefault("inputCol"),
            outputCol=self.getOrDefault("outputCol"),
            withMean=self.getOrDefault("withMean"),
            withStd=self.getOrDefault("withStd"),
        )._with_stats(cols, mean, std)


class StandardScalerModel(Model):
    _params = {"inputCol": "features", "outputCol": "scaled",
               "withMean": True, "withStd": True}

    def _with_stats(self, cols, mean, std):
        self.cols = cols
        self.mean = mean
        self.std = std
        return self

    def transform(self, df):
        import spark_tpu.api.functions as F

        out = df
        new_cols = []
        for i, c in enumerate(self.cols):
            name = f"{self.getOrDefault('outputCol')}_{c}"
            expr = F.col(c)
            if self.getOrDefault("withMean"):
                expr = expr - float(self.mean[i])
            if self.getOrDefault("withStd"):
                expr = expr / float(self.std[i])
            out = out.withColumn(name, expr)
            new_cols.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = new_cols
        out._ml_features = meta
        return out


class MinMaxScaler(Estimator):
    _params = {"inputCol": "features", "outputCol": "scaled"}

    def fit(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        mn, mx = X.min(axis=0), X.max(axis=0)
        rng = mx - mn
        rng[rng == 0] = 1.0
        m = MinMaxScalerModel(inputCol=self.getOrDefault("inputCol"),
                              outputCol=self.getOrDefault("outputCol"))
        m.cols, m.mn, m.rng = cols, mn, rng
        return m


class MinMaxScalerModel(Model):
    _params = {"inputCol": "features", "outputCol": "scaled"}

    def transform(self, df):
        import spark_tpu.api.functions as F

        out = df
        new_cols = []
        for i, c in enumerate(self.cols):
            name = f"{self.getOrDefault('outputCol')}_{c}"
            out = out.withColumn(
                name, (F.col(c) - float(self.mn[i])) / float(self.rng[i]))
            new_cols.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = new_cols
        out._ml_features = meta
        return out


class StringIndexer(Estimator):
    """Label encoding by descending frequency
    (reference: ml/feature/StringIndexer.scala)."""

    _params = {"inputCol": None, "outputCol": None}

    def fit(self, df):
        import spark_tpu.api.functions as F

        col = self.getOrDefault("inputCol")
        counts = (df.groupBy(col).agg(F.count("*").alias("c"))
                  .orderBy(F.col("c").desc(), F.col(col))
                  .toArrow().to_pydict())
        labels = [v for v in counts[col]]
        m = StringIndexerModel(inputCol=col,
                               outputCol=self.getOrDefault("outputCol"))
        m.labels = labels
        return m


class StringIndexerModel(Model):
    _params = {"inputCol": None, "outputCol": None}

    def transform(self, df):
        mapping = {v: float(i) for i, v in enumerate(self.labels)}
        vals = df.select(self.getOrDefault("inputCol")).toArrow() \
            .column(0).to_pylist()
        idx = np.array([mapping.get(v, -1.0) for v in vals])
        return with_host_column(df, self.getOrDefault("outputCol"), idx)


class Bucketizer(Transformer):
    """Continuous → bucket index by split points (reference:
    ml/feature/Bucketizer.scala) — a device searchsorted via SQL CASE."""

    _params = {"inputCol": None, "outputCol": None, "splits": ()}

    def transform(self, df):
        import spark_tpu.api.functions as F

        splits = list(self.getOrDefault("splits"))
        c = F.col(self.getOrDefault("inputCol"))
        expr = None
        for i in range(len(splits) - 1):
            cond = (c >= splits[i]) & (c < splits[i + 1]) \
                if i < len(splits) - 2 else \
                (c >= splits[i]) & (c <= splits[i + 1])
            expr = F.when(cond, float(i)) if expr is None \
                else expr.when(cond, float(i))
        return df.withColumn(self.getOrDefault("outputCol"),
                             expr.otherwise(None))


class QuantileDiscretizer(Estimator):
    """Fit quantile split points, then bucketize (reference:
    ml/feature/QuantileDiscretizer.scala)."""

    _params = {"inputCol": None, "outputCol": None, "numBuckets": 4}

    def fit(self, df) -> Bucketizer:
        nb = int(self.getOrDefault("numBuckets"))
        probs = [i / nb for i in range(1, nb)]
        qs = df.stat.approxQuantile(self.getOrDefault("inputCol"), probs)
        splits = [float("-inf")] + sorted(set(qs)) + [float("inf")]
        return Bucketizer(inputCol=self.getOrDefault("inputCol"),
                          outputCol=self.getOrDefault("outputCol"),
                          splits=tuple(splits))


class OneHotEncoder(Estimator):
    """Category index → indicator columns (reference:
    ml/feature/OneHotEncoder.scala; vectors are column groups here)."""

    _params = {"inputCol": None, "outputCol": None, "dropLast": True}

    def fit(self, df):
        vals = (df.select(self.getOrDefault("inputCol")).distinct()
                .toArrow().column(0).to_pylist())
        cats = sorted(v for v in vals if v is not None)
        if self.getOrDefault("dropLast") and len(cats) > 1:
            cats = cats[:-1]
        m = OneHotEncoderModel(inputCol=self.getOrDefault("inputCol"),
                               outputCol=self.getOrDefault("outputCol"),
                               dropLast=self.getOrDefault("dropLast"))
        m.categories = cats
        return m


class OneHotEncoderModel(Model):
    _params = {"inputCol": None, "outputCol": None, "dropLast": True}

    def transform(self, df):
        import spark_tpu.api.functions as F

        out = df
        names = []
        base = self.getOrDefault("outputCol")
        for c in self.categories:
            name = f"{base}_{c}"
            out = out.withColumn(
                name, F.when(F.col(self.getOrDefault("inputCol")) == c, 1.0)
                .otherwise(0.0))
            names.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[base] = names
        out._ml_features = meta
        return out


class PCA(Estimator):
    """Principal components via device SVD (reference: ml/feature/PCA.scala —
    the MXU-friendly path: one gram/SVD instead of row-iterated covariance)."""

    _params = {"inputCol": "features", "outputCol": "pca", "k": 2}

    def fit(self, df):
        import jax.numpy as jnp

        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        mean = X.mean(axis=0)
        Xc = jnp.asarray(X - mean)
        _, _, vt = jnp.linalg.svd(Xc, full_matrices=False)
        k = int(self.getOrDefault("k"))
        m = PCAModel(inputCol=self.getOrDefault("inputCol"),
                     outputCol=self.getOrDefault("outputCol"), k=k)
        m.cols = cols
        m.mean = mean
        m.components = np.asarray(vt)[:k]  # [k, d]
        return m


class PCAModel(Model):
    _params = {"inputCol": "features", "outputCol": "pca", "k": 2}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        Z = (X - self.mean) @ self.components.T
        out = df
        names = []
        for j in range(Z.shape[1]):
            name = f"{self.getOrDefault('outputCol')}_{j}"
            out = with_host_column(out, name, Z[:, j])
            names.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = names
        out._ml_features = meta
        return out


class Binarizer(Transformer):
    _params = {"inputCol": None, "outputCol": None, "threshold": 0.0}

    def transform(self, df):
        import spark_tpu.api.functions as F

        t = self.getOrDefault("threshold")
        return df.withColumn(
            self.getOrDefault("outputCol"),
            F.when(F.col(self.getOrDefault("inputCol")) > t, 1.0)
            .otherwise(0.0))


class Imputer(Estimator):
    """Fill missing values with mean/median per column
    (ml/feature/Imputer.scala)."""

    _params = {"inputCols": (), "outputCols": (), "strategy": "mean"}

    def fit(self, df) -> "ImputerModel":
        cols = list(self.getOrDefault("inputCols"))
        table = df.select(*cols).toArrow()
        fills = {}
        for c in cols:
            v = np.asarray(table.column(c).to_numpy(zero_copy_only=False),
                           dtype=np.float64)
            ok = v[~np.isnan(v)]
            if not len(ok):
                fills[c] = 0.0  # all-null column: nothing to estimate
            elif self.getOrDefault("strategy") == "median":
                fills[c] = float(np.median(ok))
            else:
                fills[c] = float(ok.mean())
        m = ImputerModel(inputCols=tuple(cols),
                         outputCols=tuple(self.getOrDefault("outputCols"))
                         or tuple(cols))
        m.fills = fills
        return m


class ImputerModel(Model):
    _params = {"inputCols": (), "outputCols": ()}

    def transform(self, df):
        import spark_tpu.api.functions as F

        out = df
        for src, dst in zip(self.getOrDefault("inputCols"),
                            self.getOrDefault("outputCols")):
            out = out.withColumn(
                dst, F.coalesce(F.col(src), F.lit(self.fills[src])))
        return out


class Normalizer(Transformer):
    """Row-wise p-norm scaling of the feature matrix
    (ml/feature/Normalizer.scala)."""

    _params = {"inputCol": "features", "outputCol": "normalized", "p": 2.0}

    def transform(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        p = float(self.getOrDefault("p"))
        norms = np.power(np.power(np.abs(X), p).sum(axis=1), 1.0 / p)
        norms[norms == 0] = 1.0
        Xn = X / norms[:, None]
        out = df
        names = []
        for i, c in enumerate(cols):
            name = f"{self.getOrDefault('outputCol')}_{c}"
            out = with_host_column(out, name, Xn[:, i])
            names.append(name)
        meta = dict(getattr(out, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = names
        out._ml_features = meta
        return out


class MaxAbsScaler(Estimator):
    _params = {"inputCol": "features", "outputCol": "scaled"}

    def fit(self, df) -> "MaxAbsScalerModel":
        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        scale = np.abs(X).max(axis=0)
        scale[scale == 0] = 1.0
        m = MaxAbsScalerModel(inputCol=self.getOrDefault("inputCol"),
                              outputCol=self.getOrDefault("outputCol"))
        m.cols, m.scale = cols, scale
        return m


class MaxAbsScalerModel(Model):
    _params = {"inputCol": "features", "outputCol": "scaled"}

    def transform(self, df):
        X = extract_matrix(df, self.cols) / self.scale[None, :]
        out = df
        names = []
        for i, c in enumerate(self.cols):
            name = f"{self.getOrDefault('outputCol')}_{c}"
            out = with_host_column(out, name, X[:, i])
            names.append(name)
        meta = dict(getattr(out, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = names
        out._ml_features = meta
        return out


class RobustScaler(Estimator):
    """Median/IQR scaling (ml/feature/RobustScaler.scala)."""

    _params = {"inputCol": "features", "outputCol": "scaled",
               "withCentering": True, "withScaling": True,
               "lower": 0.25, "upper": 0.75}

    def fit(self, df) -> "RobustScalerModel":
        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        med = np.median(X, axis=0)
        iqr = (np.quantile(X, self.getOrDefault("upper"), axis=0)
               - np.quantile(X, self.getOrDefault("lower"), axis=0))
        iqr[iqr == 0] = 1.0
        m = RobustScalerModel(
            inputCol=self.getOrDefault("inputCol"),
            outputCol=self.getOrDefault("outputCol"),
            withCentering=self.getOrDefault("withCentering"),
            withScaling=self.getOrDefault("withScaling"))
        m.cols, m.median, m.iqr = cols, med, iqr
        return m


class RobustScalerModel(Model):
    _params = {"inputCol": "features", "outputCol": "scaled",
               "withCentering": True, "withScaling": True}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        if self.getOrDefault("withCentering"):
            X = X - self.median[None, :]
        if self.getOrDefault("withScaling"):
            X = X / self.iqr[None, :]
        out = df
        names = []
        for i, c in enumerate(self.cols):
            name = f"{self.getOrDefault('outputCol')}_{c}"
            out = with_host_column(out, name, X[:, i])
            names.append(name)
        meta = dict(getattr(out, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = names
        out._ml_features = meta
        return out


class PolynomialExpansion(Transformer):
    """Degree-2/3 polynomial feature expansion
    (ml/feature/PolynomialExpansion.scala)."""

    _params = {"inputCol": "features", "outputCol": "poly", "degree": 2}

    def transform(self, df):
        import itertools

        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        degree = int(self.getOrDefault("degree"))
        out = df
        names = []
        idx = range(X.shape[1])
        for deg in range(1, degree + 1):
            for combo in itertools.combinations_with_replacement(idx, deg):
                name = f"{self.getOrDefault('outputCol')}_" + \
                    "_".join(str(i) for i in combo)
                v = np.prod(X[:, list(combo)], axis=1)
                out = with_host_column(out, name, v)
                names.append(name)
        meta = dict(getattr(out, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = names
        out._ml_features = meta
        return out
