"""Feature transformers (reference: mllib ml/feature/*)."""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, Transformer, extract_matrix, resolve_feature_cols,
    with_host_column,
)


class VectorAssembler(Transformer):
    """Records which columns make the [n, d] feature matrix
    (reference: ml/feature/VectorAssembler.scala; see base.py on the
    matrix-not-vector-objects design)."""

    _params = {"inputCols": (), "outputCol": "features"}

    def transform(self, df):
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = list(
            self.getOrDefault("inputCols"))
        out = df._with(df.plan)
        out._ml_features = meta
        return out


class StandardScaler(Estimator):
    _params = {"inputCol": "features", "outputCol": "scaled",
               "withMean": True, "withStd": True}

    def fit(self, df) -> "StandardScalerModel":
        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        mean = X.mean(axis=0)
        std = X.std(axis=0)
        std[std == 0] = 1.0
        return StandardScalerModel(
            inputCol=self.getOrDefault("inputCol"),
            outputCol=self.getOrDefault("outputCol"),
            withMean=self.getOrDefault("withMean"),
            withStd=self.getOrDefault("withStd"),
        )._with_stats(cols, mean, std)


class StandardScalerModel(Model):
    _params = {"inputCol": "features", "outputCol": "scaled",
               "withMean": True, "withStd": True}

    def _with_stats(self, cols, mean, std):
        self.cols = cols
        self.mean = mean
        self.std = std
        return self

    def transform(self, df):
        import spark_tpu.api.functions as F

        out = df
        new_cols = []
        for i, c in enumerate(self.cols):
            name = f"{self.getOrDefault('outputCol')}_{c}"
            expr = F.col(c)
            if self.getOrDefault("withMean"):
                expr = expr - float(self.mean[i])
            if self.getOrDefault("withStd"):
                expr = expr / float(self.std[i])
            out = out.withColumn(name, expr)
            new_cols.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = new_cols
        out._ml_features = meta
        return out


class MinMaxScaler(Estimator):
    _params = {"inputCol": "features", "outputCol": "scaled"}

    def fit(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        mn, mx = X.min(axis=0), X.max(axis=0)
        rng = mx - mn
        rng[rng == 0] = 1.0
        m = MinMaxScalerModel(inputCol=self.getOrDefault("inputCol"),
                              outputCol=self.getOrDefault("outputCol"))
        m.cols, m.mn, m.rng = cols, mn, rng
        return m


class MinMaxScalerModel(Model):
    _params = {"inputCol": "features", "outputCol": "scaled"}

    def transform(self, df):
        import spark_tpu.api.functions as F

        out = df
        new_cols = []
        for i, c in enumerate(self.cols):
            name = f"{self.getOrDefault('outputCol')}_{c}"
            out = out.withColumn(
                name, (F.col(c) - float(self.mn[i])) / float(self.rng[i]))
            new_cols.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = new_cols
        out._ml_features = meta
        return out


class StringIndexer(Estimator):
    """Label encoding by descending frequency
    (reference: ml/feature/StringIndexer.scala)."""

    _params = {"inputCol": None, "outputCol": None}

    def fit(self, df):
        import spark_tpu.api.functions as F

        col = self.getOrDefault("inputCol")
        counts = (df.groupBy(col).agg(F.count("*").alias("c"))
                  .orderBy(F.col("c").desc(), F.col(col))
                  .toArrow().to_pydict())
        labels = [v for v in counts[col]]
        m = StringIndexerModel(inputCol=col,
                               outputCol=self.getOrDefault("outputCol"))
        m.labels = labels
        return m


class StringIndexerModel(Model):
    _params = {"inputCol": None, "outputCol": None}

    def transform(self, df):
        mapping = {v: float(i) for i, v in enumerate(self.labels)}
        vals = df.select(self.getOrDefault("inputCol")).toArrow() \
            .column(0).to_pylist()
        idx = np.array([mapping.get(v, -1.0) for v in vals])
        return with_host_column(df, self.getOrDefault("outputCol"), idx)


class Bucketizer(Transformer):
    """Continuous → bucket index by split points (reference:
    ml/feature/Bucketizer.scala) — a device searchsorted via SQL CASE."""

    _params = {"inputCol": None, "outputCol": None, "splits": ()}

    def transform(self, df):
        import spark_tpu.api.functions as F

        splits = list(self.getOrDefault("splits"))
        c = F.col(self.getOrDefault("inputCol"))
        expr = None
        for i in range(len(splits) - 1):
            cond = (c >= splits[i]) & (c < splits[i + 1]) \
                if i < len(splits) - 2 else \
                (c >= splits[i]) & (c <= splits[i + 1])
            expr = F.when(cond, float(i)) if expr is None \
                else expr.when(cond, float(i))
        return df.withColumn(self.getOrDefault("outputCol"),
                             expr.otherwise(None))


class QuantileDiscretizer(Estimator):
    """Fit quantile split points, then bucketize (reference:
    ml/feature/QuantileDiscretizer.scala)."""

    _params = {"inputCol": None, "outputCol": None, "numBuckets": 4}

    def fit(self, df) -> Bucketizer:
        nb = int(self.getOrDefault("numBuckets"))
        probs = [i / nb for i in range(1, nb)]
        qs = df.stat.approxQuantile(self.getOrDefault("inputCol"), probs)
        splits = [float("-inf")] + sorted(set(qs)) + [float("inf")]
        return Bucketizer(inputCol=self.getOrDefault("inputCol"),
                          outputCol=self.getOrDefault("outputCol"),
                          splits=tuple(splits))


class OneHotEncoder(Estimator):
    """Category index → indicator columns (reference:
    ml/feature/OneHotEncoder.scala; vectors are column groups here)."""

    _params = {"inputCol": None, "outputCol": None, "dropLast": True}

    def fit(self, df):
        vals = (df.select(self.getOrDefault("inputCol")).distinct()
                .toArrow().column(0).to_pylist())
        cats = sorted(v for v in vals if v is not None)
        if self.getOrDefault("dropLast") and len(cats) > 1:
            cats = cats[:-1]
        m = OneHotEncoderModel(inputCol=self.getOrDefault("inputCol"),
                               outputCol=self.getOrDefault("outputCol"),
                               dropLast=self.getOrDefault("dropLast"))
        m.categories = cats
        return m


class OneHotEncoderModel(Model):
    _params = {"inputCol": None, "outputCol": None, "dropLast": True}

    def transform(self, df):
        import spark_tpu.api.functions as F

        out = df
        names = []
        base = self.getOrDefault("outputCol")
        for c in self.categories:
            name = f"{base}_{c}"
            out = out.withColumn(
                name, F.when(F.col(self.getOrDefault("inputCol")) == c, 1.0)
                .otherwise(0.0))
            names.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[base] = names
        out._ml_features = meta
        return out


class PCA(Estimator):
    """Principal components via device SVD (reference: ml/feature/PCA.scala —
    the MXU-friendly path: one gram/SVD instead of row-iterated covariance)."""

    _params = {"inputCol": "features", "outputCol": "pca", "k": 2}

    def fit(self, df):
        import jax.numpy as jnp

        cols = resolve_feature_cols(df, self.getOrDefault("inputCol"))
        X = extract_matrix(df, cols)
        mean = X.mean(axis=0)
        Xc = jnp.asarray(X - mean)
        _, _, vt = jnp.linalg.svd(Xc, full_matrices=False)
        k = int(self.getOrDefault("k"))
        m = PCAModel(inputCol=self.getOrDefault("inputCol"),
                     outputCol=self.getOrDefault("outputCol"), k=k)
        m.cols = cols
        m.mean = mean
        m.components = np.asarray(vt)[:k]  # [k, d]
        return m


class PCAModel(Model):
    _params = {"inputCol": "features", "outputCol": "pca", "k": 2}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        Z = (X - self.mean) @ self.components.T
        out = df
        names = []
        for j in range(Z.shape[1]):
            name = f"{self.getOrDefault('outputCol')}_{j}"
            out = with_host_column(out, name, Z[:, j])
            names.append(name)
        meta = dict(getattr(df, "_ml_features", None) or {})
        meta[self.getOrDefault("outputCol")] = names
        out._ml_features = meta
        return out


class Binarizer(Transformer):
    _params = {"inputCol": None, "outputCol": None, "threshold": 0.0}

    def transform(self, df):
        import spark_tpu.api.functions as F

        t = self.getOrDefault("threshold")
        return df.withColumn(
            self.getOrDefault("outputCol"),
            F.when(F.col(self.getOrDefault("inputCol")) > t, 1.0)
            .otherwise(0.0))
