"""Clustering (reference: ml/clustering/KMeans.scala)."""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, extract_matrix, resolve_feature_cols, with_host_column,
)


class KMeans(Estimator):
    """Lloyd's iterations as one jitted lax.scan — assignment is a [n, k]
    distance matmul (MXU), update is segment_sum."""

    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "k": 2, "maxIter": 20, "seed": 42}

    def fit(self, df) -> "KMeansModel":
        import jax
        import jax.numpy as jnp
        from jax import lax

        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        k = int(self.getOrDefault("k"))
        rng = np.random.default_rng(self.getOrDefault("seed"))
        init = X[rng.choice(len(X), size=k, replace=False)]

        Xd = jnp.asarray(X)

        @jax.jit
        def run(c0):
            def step(c, _):
                d2 = ((Xd[:, None, :] - c[None]) ** 2).sum(-1)
                assign = jnp.argmin(d2, axis=1)
                sums = jax.ops.segment_sum(Xd, assign, num_segments=k)
                cnts = jax.ops.segment_sum(jnp.ones(Xd.shape[0]), assign,
                                           num_segments=k)
                newc = jnp.where(cnts[:, None] > 0,
                                 sums / jnp.maximum(cnts[:, None], 1), c)
                return newc, None

            c, _ = lax.scan(step, c0, None,
                            length=int(self.getOrDefault("maxIter")))
            return c

        centers = np.asarray(run(jnp.asarray(init)))
        m = KMeansModel(featuresCol=self.getOrDefault("featuresCol"),
                        predictionCol=self.getOrDefault("predictionCol"),
                        k=k)
        m.cols = cols
        m.clusterCenters = centers
        return m


class KMeansModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "k": 2}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        d2 = ((X[:, None, :] - self.clusterCenters[None]) ** 2).sum(-1)
        pred = np.argmin(d2, axis=1).astype(np.float64)
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)
