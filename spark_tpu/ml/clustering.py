"""Clustering (reference: ml/clustering/KMeans.scala)."""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, extract_matrix, resolve_feature_cols, with_host_column,
)


class KMeans(Estimator):
    """Lloyd's iterations as one jitted lax.scan — assignment is a [n, k]
    distance matmul (MXU), update is segment_sum."""

    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "k": 2, "maxIter": 20, "seed": 42}

    def fit(self, df) -> "KMeansModel":
        import jax
        import jax.numpy as jnp
        from jax import lax

        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        k = int(self.getOrDefault("k"))
        rng = np.random.default_rng(self.getOrDefault("seed"))
        init = X[rng.choice(len(X), size=k, replace=False)]

        Xd = jnp.asarray(X)

        @jax.jit
        def run(c0):
            def step(c, _):
                d2 = ((Xd[:, None, :] - c[None]) ** 2).sum(-1)
                assign = jnp.argmin(d2, axis=1)
                sums = jax.ops.segment_sum(Xd, assign, num_segments=k)
                cnts = jax.ops.segment_sum(jnp.ones(Xd.shape[0]), assign,
                                           num_segments=k)
                newc = jnp.where(cnts[:, None] > 0,
                                 sums / jnp.maximum(cnts[:, None], 1), c)
                return newc, None

            c, _ = lax.scan(step, c0, None,
                            length=int(self.getOrDefault("maxIter")))
            return c

        centers = np.asarray(run(jnp.asarray(init)))
        m = KMeansModel(featuresCol=self.getOrDefault("featuresCol"),
                        predictionCol=self.getOrDefault("predictionCol"),
                        k=k)
        m.cols = cols
        m.clusterCenters = centers
        return m


class KMeansModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "k": 2}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        d2 = ((X[:, None, :] - self.clusterCenters[None]) ** 2).sum(-1)
        pred = np.argmin(d2, axis=1).astype(np.float64)
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)


class GaussianMixture(Estimator):
    """Diagonal-covariance GMM by EM, the whole loop one jitted lax.scan
    program (reference: ml/clustering/GaussianMixture.scala — its
    aggregation-tree E/M steps become batched device matmuls)."""

    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "probabilityCol": "probability", "k": 2, "maxIter": 100,
               "seed": 11, "tol": 1e-6}

    def fit(self, df) -> "GaussianMixtureModel":
        import jax
        import jax.numpy as jnp
        from jax import lax

        from .base import extract_matrix, resolve_feature_cols

        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        k = int(self.getOrDefault("k"))
        n, d = X.shape
        rng = np.random.default_rng(self.getOrDefault("seed"))
        mu0 = X[rng.choice(n, size=k, replace=False)]
        var0 = np.tile(X.var(axis=0) + 1e-6, (k, 1))
        w0 = np.full(k, 1.0 / k)
        Xd = jnp.asarray(X)

        @jax.jit
        def run(mu, var, w):
            def step(carry, _):
                mu, var, w = carry
                # E: log N(x | mu_j, diag var_j) for all pairs [n, k]
                diff2 = (Xd[:, None, :] - mu[None, :, :]) ** 2
                logp = (-0.5 * (diff2 / var[None]).sum(-1)
                        - 0.5 * jnp.log(2 * jnp.pi * var).sum(-1)[None]
                        + jnp.log(w)[None])
                r = jax.nn.softmax(logp, axis=1)          # [n, k]
                nk = r.sum(0) + 1e-12
                # M: weighted moments — MXU matmuls
                mu = (r.T @ Xd) / nk[:, None]
                ex2 = (r.T @ (Xd ** 2)) / nk[:, None]
                var = jnp.maximum(ex2 - mu ** 2, 1e-6)
                w = nk / nk.sum()
                return (mu, var, w), None

            (mu, var, w), _ = lax.scan(
                step, (mu, var, w), None,
                length=int(self.getOrDefault("maxIter")))
            return mu, var, w

        mu, var, w = run(jnp.asarray(mu0), jnp.asarray(var0),
                         jnp.asarray(w0))
        m = GaussianMixtureModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"),
            probabilityCol=self.getOrDefault("probabilityCol"),
            k=k)
        m.weights = np.asarray(w)
        m.means = np.asarray(mu)
        m.variances = np.asarray(var)
        m.cols = cols
        return m


class GaussianMixtureModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "probabilityCol": "probability", "k": 2}

    def _resp(self, X):
        diff2 = (X[:, None, :] - self.means[None]) ** 2
        logp = (-0.5 * (diff2 / self.variances[None]).sum(-1)
                - 0.5 * np.log(2 * np.pi * self.variances).sum(-1)[None]
                + np.log(self.weights)[None])
        logp -= logp.max(axis=1, keepdims=True)
        p = np.exp(logp)
        return p / p.sum(axis=1, keepdims=True)

    def transform(self, df):
        from .base import extract_matrix, with_host_column

        X = extract_matrix(df, self.cols)
        r = self._resp(X)
        out = with_host_column(df, self.getOrDefault("predictionCol"),
                               np.argmax(r, axis=1).astype(np.float64))
        return with_host_column(out, self.getOrDefault("probabilityCol"),
                                r.max(axis=1))


class BisectingKMeans(Estimator):
    """Top-down hierarchical k-means: repeatedly 2-means-split the
    largest cluster (reference: ml/clustering/BisectingKMeans.scala)."""

    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "k": 4, "maxIter": 20, "seed": 5}

    def fit(self, df) -> "KMeansModel":
        from .base import extract_matrix, resolve_feature_cols

        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        k = int(self.getOrDefault("k"))
        rng = np.random.default_rng(self.getOrDefault("seed"))
        assign = np.zeros(len(X), dtype=np.int64)
        centers = [X.mean(axis=0)]
        while len(centers) < k:
            sizes = np.bincount(assign, minlength=len(centers))
            target = int(np.argmax(sizes))
            idx = np.nonzero(assign == target)[0]
            if len(idx) < 2:
                break
            sub = X[idx]
            c = sub[rng.choice(len(sub), 2, replace=False)]
            for _ in range(int(self.getOrDefault("maxIter"))):
                d2 = ((sub[:, None] - c[None]) ** 2).sum(-1)
                lab = d2.argmin(1)
                for j in (0, 1):
                    if (lab == j).any():
                        c[j] = sub[lab == j].mean(axis=0)
            new_id = len(centers)
            centers[target] = c[0]
            centers.append(c[1])
            assign[idx[lab == 1]] = new_id
        m = KMeansModel(featuresCol=self.getOrDefault("featuresCol"),
                        predictionCol=self.getOrDefault("predictionCol"),
                        k=len(centers))
        m.clusterCenters = np.stack(centers)
        m.cols = cols
        return m
