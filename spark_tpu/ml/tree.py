"""Decision trees and random forests.

Role of the reference's tree family (ml/classification/DecisionTreeClassifier,
ml/regression/DecisionTreeRegressor, RandomForest*). Design: histogram-based
greedy splitting — per node, candidate thresholds come from feature
quantiles, impurity sums per bin are vectorized over numpy (the [n, d]
feature-matrix design of base.py); no per-row recursion.
"""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, extract_matrix, extract_vector, resolve_feature_cols,
    with_host_column,
)


class _Node:
    __slots__ = ("feature", "threshold", "left", "right", "value")

    def __init__(self, value):
        self.feature = -1
        self.threshold = 0.0
        self.left = None
        self.right = None
        self.value = value


def _build_tree(X: np.ndarray, y: np.ndarray, depth: int, max_depth: int,
                min_instances: int, impurity: str, n_bins: int,
                rng, feature_subset: float) -> _Node:
    n, d = X.shape
    if impurity == "variance":
        value = float(y.mean())
        node_imp = float(y.var())
    else:
        classes, counts = np.unique(y, return_counts=True)
        value = float(classes[np.argmax(counts)])
        p = counts / n
        node_imp = float(1.0 - (p * p).sum())  # gini
    node = _Node(value)
    if depth >= max_depth or n < 2 * min_instances or node_imp <= 1e-12:
        return node

    feats = np.arange(d)
    if feature_subset < 1.0:
        k = max(1, int(d * feature_subset))
        feats = rng.choice(d, size=k, replace=False)

    best = (0.0, -1, 0.0)  # (gain, feature, threshold)
    for f in feats:
        xs = X[:, f]
        qs = np.unique(np.quantile(xs, np.linspace(0.05, 0.95,
                                                   min(n_bins, n))))
        for t in qs:
            left = xs <= t
            nl = int(left.sum())
            if nl < min_instances or n - nl < min_instances:
                continue
            if impurity == "variance":
                imp = (nl * y[left].var() + (n - nl) * y[~left].var()) / n
            else:
                def gini(part):
                    _, c = np.unique(part, return_counts=True)
                    pp = c / len(part)
                    return 1.0 - (pp * pp).sum()

                imp = (nl * gini(y[left]) + (n - nl) * gini(y[~left])) / n
            gain = node_imp - imp
            if gain > best[0]:
                best = (gain, int(f), float(t))

    if best[1] < 0:
        return node
    node.feature, node.threshold = best[1], best[2]
    mask = X[:, node.feature] <= node.threshold
    node.left = _build_tree(X[mask], y[mask], depth + 1, max_depth,
                            min_instances, impurity, n_bins, rng,
                            feature_subset)
    node.right = _build_tree(X[~mask], y[~mask], depth + 1, max_depth,
                             min_instances, impurity, n_bins, rng,
                             feature_subset)
    return node


def _predict_tree(node: _Node, X: np.ndarray) -> np.ndarray:
    out = np.empty(len(X))

    def go(n: _Node, idx: np.ndarray):
        if n.left is None:
            out[idx] = n.value
            return
        mask = X[idx, n.feature] <= n.threshold
        go(n.left, idx[mask])
        go(n.right, idx[~mask])

    go(node, np.arange(len(X)))
    return out


class _TreeEstimator(Estimator):
    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "maxDepth": 5,
               "minInstancesPerNode": 1, "maxBins": 32, "numTrees": 1,
               "subsamplingRate": 1.0, "featureSubsetStrategy": 1.0,
               "seed": 42}
    _impurity = "gini"

    def fit(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        rng = np.random.default_rng(self.getOrDefault("seed"))
        trees = []
        n = len(X)
        for _ in range(int(self.getOrDefault("numTrees"))):
            if self.getOrDefault("subsamplingRate") < 1.0 or \
                    int(self.getOrDefault("numTrees")) > 1:
                idx = rng.choice(
                    n, size=max(1, int(n * self.getOrDefault(
                        "subsamplingRate"))), replace=True)
            else:
                idx = np.arange(n)
            trees.append(_build_tree(
                X[idx], y[idx], 0, int(self.getOrDefault("maxDepth")),
                int(self.getOrDefault("minInstancesPerNode")),
                self._impurity, int(self.getOrDefault("maxBins")), rng,
                float(self.getOrDefault("featureSubsetStrategy"))))
        m = _TreeModel(featuresCol=self.getOrDefault("featuresCol"),
                       predictionCol=self.getOrDefault("predictionCol"))
        m.cols = cols
        m.trees = trees
        m.is_regression = self._impurity == "variance"
        return m


class _TreeModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        preds = np.stack([_predict_tree(t, X) for t in self.trees])
        if self.is_regression:
            pred = preds.mean(axis=0)
        else:
            # majority vote
            pred = np.apply_along_axis(
                lambda v: np.bincount(v.astype(np.int64)).argmax(), 0,
                preds).astype(np.float64)
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)


class DecisionTreeClassifier(_TreeEstimator):
    _impurity = "gini"


class DecisionTreeRegressor(_TreeEstimator):
    _impurity = "variance"


class GBTRegressor(Estimator):
    """Gradient-boosted trees for regression (reference:
    ml/regression/GBTRegressor.scala): residual-fitting boosting over the
    histogram tree learner."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "maxDepth": 3,
               "maxIter": 20, "stepSize": 0.1, "maxBins": 32, "seed": 42}

    def fit(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        rng = np.random.default_rng(self.getOrDefault("seed"))
        lr = float(self.getOrDefault("stepSize"))
        base = float(y.mean())
        pred = np.full(len(y), base)
        trees = []
        for _ in range(int(self.getOrDefault("maxIter"))):
            residual = y - pred
            t = _build_tree(X, residual, 0,
                            int(self.getOrDefault("maxDepth")), 1,
                            "variance", int(self.getOrDefault("maxBins")),
                            rng, 1.0)
            trees.append(t)
            pred = pred + lr * _predict_tree(t, X)
        m = GBTRegressorModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"))
        m.cols = cols
        m.base = base
        m.lr = lr
        m.trees = trees
        return m


class GBTRegressorModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        pred = np.full(len(X), self.base)
        for t in self.trees:
            pred = pred + self.lr * _predict_tree(t, X)
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)


class GBTClassifier(Estimator):
    """Binary GBT classifier: logistic boosting on the half-gradient."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction",
               "probabilityCol": "probability", "maxDepth": 3,
               "maxIter": 20, "stepSize": 0.2, "maxBins": 32, "seed": 42}

    def fit(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        rng = np.random.default_rng(self.getOrDefault("seed"))
        lr = float(self.getOrDefault("stepSize"))
        f = np.zeros(len(y))
        trees = []
        for _ in range(int(self.getOrDefault("maxIter"))):
            p = 1.0 / (1.0 + np.exp(-np.clip(f, -50, 50)))
            grad = y - p  # negative gradient of logloss
            t = _build_tree(X, grad, 0,
                            int(self.getOrDefault("maxDepth")), 1,
                            "variance", int(self.getOrDefault("maxBins")),
                            rng, 1.0)
            trees.append(t)
            f = f + lr * _predict_tree(t, X)
        m = GBTClassifierModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"),
            probabilityCol=self.getOrDefault("probabilityCol"))
        m.cols = cols
        m.lr = lr
        m.trees = trees
        return m


class GBTClassifierModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "probabilityCol": "probability"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        f = np.zeros(len(X))
        for t in self.trees:
            f = f + self.lr * _predict_tree(t, X)
        p = 1.0 / (1.0 + np.exp(-np.clip(f, -50, 50)))
        out = with_host_column(df, self.getOrDefault("probabilityCol"), p)
        return with_host_column(out, self.getOrDefault("predictionCol"),
                                (p >= 0.5).astype(np.float64))


class RandomForestClassifier(_TreeEstimator):
    _impurity = "gini"
    _params = dict(_TreeEstimator._params, numTrees=20,
                   subsamplingRate=0.8, featureSubsetStrategy=0.6)


class RandomForestRegressor(_TreeEstimator):
    _impurity = "variance"
    _params = dict(_TreeEstimator._params, numTrees=20,
                   subsamplingRate=0.8, featureSubsetStrategy=0.6)
