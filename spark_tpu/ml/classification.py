"""Classification estimators (reference: ml/classification/
LogisticRegression.scala, NaiveBayes.scala)."""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, extract_matrix, extract_vector, resolve_feature_cols,
    with_host_column,
)
from .regression import _gd_fit


class LogisticRegression(Estimator):
    """Binary logistic regression via jitted full-batch GD (lax.scan)."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction",
               "probabilityCol": "probability", "regParam": 0.0,
               "maxIter": 200, "fitIntercept": True, "threshold": 0.5}

    def fit(self, df) -> "LogisticRegressionModel":
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        if self.getOrDefault("fitIntercept"):
            X = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
        w = _gd_fit(X, y, float(self.getOrDefault("regParam")),
                    int(self.getOrDefault("maxIter")), kind="logistic")
        m = LogisticRegressionModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"),
            probabilityCol=self.getOrDefault("probabilityCol"),
            threshold=self.getOrDefault("threshold"))
        if self.getOrDefault("fitIntercept"):
            m.coefficients = w[:-1]
            m.intercept = float(w[-1])
        else:
            m.coefficients = w
            m.intercept = 0.0
        m.cols = cols
        return m


class LogisticRegressionModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "probabilityCol": "probability", "threshold": 0.5}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        z = np.clip(X @ self.coefficients + self.intercept, -500, 500)
        p = 1.0 / (1.0 + np.exp(-z))
        out = with_host_column(df, self.getOrDefault("probabilityCol"), p)
        pred = (p >= self.getOrDefault("threshold")).astype(np.float64)
        return with_host_column(out, self.getOrDefault("predictionCol"), pred)


class NaiveBayes(Estimator):
    """Gaussian naive Bayes (the reference ships multinomial/bernoulli over
    term counts; Gaussian fits the columnar-numeric design)."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "smoothing": 1e-9}

    def fit(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        classes = np.unique(y)
        means, variances, priors = [], [], []
        for c in classes:
            Xi = X[y == c]
            means.append(Xi.mean(axis=0))
            variances.append(Xi.var(axis=0) + self.getOrDefault("smoothing"))
            priors.append(len(Xi) / len(X))
        m = NaiveBayesModel(featuresCol=self.getOrDefault("featuresCol"),
                            predictionCol=self.getOrDefault("predictionCol"))
        m.cols = cols
        m.classes = classes
        m.means = np.array(means)
        m.variances = np.array(variances)
        m.log_priors = np.log(np.array(priors))
        return m


class NaiveBayesModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        # log N(x | mu, var) per class, vectorized [n, k]
        ll = -0.5 * (((X[:, None, :] - self.means[None]) ** 2
                      / self.variances[None]).sum(-1)
                     + np.log(2 * np.pi * self.variances).sum(-1)[None])
        scores = ll + self.log_priors[None]
        pred = self.classes[np.argmax(scores, axis=1)].astype(np.float64)
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)
