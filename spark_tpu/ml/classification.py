"""Classification estimators (reference: ml/classification/
LogisticRegression.scala, NaiveBayes.scala)."""

from __future__ import annotations

import numpy as np

from .base import (
    Estimator, Model, extract_matrix, extract_vector, resolve_feature_cols,
    with_host_column,
)
from .regression import _gd_fit


class LogisticRegression(Estimator):
    """Binary logistic regression via jitted full-batch GD (lax.scan)."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction",
               "probabilityCol": "probability", "regParam": 0.0,
               "maxIter": 200, "fitIntercept": True, "threshold": 0.5}

    def fit(self, df) -> "LogisticRegressionModel":
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        if self.getOrDefault("fitIntercept"):
            X = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
        w = _gd_fit(X, y, float(self.getOrDefault("regParam")),
                    int(self.getOrDefault("maxIter")), kind="logistic")
        m = LogisticRegressionModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"),
            probabilityCol=self.getOrDefault("probabilityCol"),
            threshold=self.getOrDefault("threshold"))
        if self.getOrDefault("fitIntercept"):
            m.coefficients = w[:-1]
            m.intercept = float(w[-1])
        else:
            m.coefficients = w
            m.intercept = 0.0
        m.cols = cols
        return m


class LogisticRegressionModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction",
               "probabilityCol": "probability", "threshold": 0.5}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        z = np.clip(X @ self.coefficients + self.intercept, -500, 500)
        p = 1.0 / (1.0 + np.exp(-z))
        out = with_host_column(df, self.getOrDefault("probabilityCol"), p)
        pred = (p >= self.getOrDefault("threshold")).astype(np.float64)
        return with_host_column(out, self.getOrDefault("predictionCol"), pred)


class NaiveBayes(Estimator):
    """Gaussian naive Bayes (the reference ships multinomial/bernoulli over
    term counts; Gaussian fits the columnar-numeric design)."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "smoothing": 1e-9}

    def fit(self, df):
        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        classes = np.unique(y)
        means, variances, priors = [], [], []
        for c in classes:
            Xi = X[y == c]
            means.append(Xi.mean(axis=0))
            variances.append(Xi.var(axis=0) + self.getOrDefault("smoothing"))
            priors.append(len(Xi) / len(X))
        m = NaiveBayesModel(featuresCol=self.getOrDefault("featuresCol"),
                            predictionCol=self.getOrDefault("predictionCol"))
        m.cols = cols
        m.classes = classes
        m.means = np.array(means)
        m.variances = np.array(variances)
        m.log_priors = np.log(np.array(priors))
        return m


class NaiveBayesModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        # log N(x | mu, var) per class, vectorized [n, k]
        ll = -0.5 * (((X[:, None, :] - self.means[None]) ** 2
                      / self.variances[None]).sum(-1)
                     + np.log(2 * np.pi * self.variances).sum(-1)[None])
        scores = ll + self.log_priors[None]
        pred = self.classes[np.argmax(scores, axis=1)].astype(np.float64)
        return with_host_column(df, self.getOrDefault("predictionCol"), pred)


class LinearSVC(Estimator):
    """Linear SVM via jitted full-batch subgradient descent on the
    squared-hinge objective (reference: ml/classification/LinearSVC.scala
    — its OWLQN/breeze optimizer replaced by one XLA scan program)."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "regParam": 0.01,
               "maxIter": 200, "fitIntercept": True}

    def fit(self, df) -> "LinearSVCModel":
        import jax
        import jax.numpy as jnp
        from jax import lax

        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol"))
        if self.getOrDefault("fitIntercept"):
            X = np.concatenate([X, np.ones((X.shape[0], 1))], axis=1)
        n, d = X.shape
        Xd = jnp.asarray(X)
        yd = jnp.asarray(np.where(y > 0, 1.0, -1.0))
        lam = float(self.getOrDefault("regParam"))
        iters = int(self.getOrDefault("maxIter"))
        lr = float(n) / (np.linalg.norm(X, ord="fro") ** 2 + 1e-12)

        @jax.jit
        def run(w0):
            def step(w, _):
                margin = yd * (Xd @ w)
                viol = jnp.maximum(0.0, 1.0 - margin)  # squared hinge
                g = -(Xd.T @ (yd * viol)) * (2.0 / n) + lam * w
                return w - lr * g, None

            w, _ = lax.scan(step, w0, None, length=iters)
            return w

        w = np.asarray(run(jnp.zeros(d)))
        m = LinearSVCModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"))
        if self.getOrDefault("fitIntercept"):
            m.coefficients, m.intercept = w[:-1], float(w[-1])
        else:
            m.coefficients, m.intercept = w, 0.0
        m.cols = cols
        return m


class LinearSVCModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        pred = (X @ self.coefficients + self.intercept >= 0) \
            .astype(np.float64)
        return with_host_column(df, self.getOrDefault("predictionCol"),
                                pred)


class MultilayerPerceptronClassifier(Estimator):
    """Feed-forward network trained with jax.grad + full-batch Adam in
    one lax.scan program — the estimator whose compute maps to the MXU
    most directly (reference: ml/classification/
    MultilayerPerceptronClassifier.scala, its LBFGS replaced by Adam)."""

    _params = {"featuresCol": "features", "labelCol": "label",
               "predictionCol": "prediction", "layers": None,
               "maxIter": 300, "stepSize": 0.03, "seed": 7}

    def fit(self, df) -> "MultilayerPerceptronModel":
        import jax
        import jax.numpy as jnp
        from jax import lax

        cols = resolve_feature_cols(df, self.getOrDefault("featuresCol"))
        X = extract_matrix(df, cols)
        y = extract_vector(df, self.getOrDefault("labelCol")) \
            .astype(np.int64)
        layers = self.getOrDefault("layers") or \
            [X.shape[1], 16, int(y.max()) + 1]
        assert layers[0] == X.shape[1], "layers[0] must equal n_features"
        rng = np.random.default_rng(self.getOrDefault("seed"))
        params0 = []
        for i in range(len(layers) - 1):
            fan_in, fan_out = layers[i], layers[i + 1]
            params0.append((
                jnp.asarray(rng.normal(0, np.sqrt(2.0 / fan_in),
                                       (fan_in, fan_out))),
                jnp.zeros(fan_out)))
        Xd, yd = jnp.asarray(X), jnp.asarray(y)
        lr = float(self.getOrDefault("stepSize"))
        iters = int(self.getOrDefault("maxIter"))

        def forward(params, x):
            h = x
            for W, b in params[:-1]:
                h = jax.nn.relu(h @ W + b)
            W, b = params[-1]
            return h @ W + b

        def loss(params):
            logits = forward(params, Xd)
            return -jnp.mean(
                jax.nn.log_softmax(logits)[jnp.arange(len(yd)), yd])

        @jax.jit
        def run(p0):
            def step(carry, _):
                params, m, v, t = carry
                g = jax.grad(loss)(params)
                t = t + 1
                m = jax.tree.map(lambda a, b: 0.9 * a + 0.1 * b, m, g)
                v = jax.tree.map(lambda a, b: 0.999 * a + 0.001 * b ** 2,
                                 v, g)
                mh = jax.tree.map(lambda a: a / (1 - 0.9 ** t), m)
                vh = jax.tree.map(lambda a: a / (1 - 0.999 ** t), v)
                params = jax.tree.map(
                    lambda p, a, b: p - lr * a / (jnp.sqrt(b) + 1e-8),
                    params, mh, vh)
                return (params, m, v, t), None

            zeros = jax.tree.map(jnp.zeros_like, p0)
            (params, _, _, _), _ = lax.scan(
                step, (p0, zeros, zeros, 0.0), None, length=iters)
            return params

        params = run(params0)
        m = MultilayerPerceptronModel(
            featuresCol=self.getOrDefault("featuresCol"),
            predictionCol=self.getOrDefault("predictionCol"))
        m.params = [(np.asarray(W), np.asarray(b)) for W, b in params]
        m.cols = cols
        return m


class MultilayerPerceptronModel(Model):
    _params = {"featuresCol": "features", "predictionCol": "prediction"}

    def transform(self, df):
        X = extract_matrix(df, self.cols)
        h = X
        for W, b in self.params[:-1]:
            h = np.maximum(h @ W + b, 0.0)
        W, b = self.params[-1]
        pred = np.argmax(h @ W + b, axis=1).astype(np.float64)
        return with_host_column(df, self.getOrDefault("predictionCol"),
                                pred)
