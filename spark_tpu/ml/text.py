"""Text feature pipeline: Tokenizer → StopWordsRemover/NGram →
HashingTF/CountVectorizer → IDF.

Role of the reference's text features (mllib ml/feature/{Tokenizer,
RegexTokenizer, StopWordsRemover, NGram, HashingTF, CountVectorizer,
IDF}.scala). TPU-first shape: token lists are host columns
(list<string> — strings never land on the device), while the produced
term-frequency vectors are fixed-width list<double> columns that
`extract_matrix` expands straight into the [n, d] device matrix every
estimator trains on — so the classic `Tokenizer → HashingTF → IDF →
LogisticRegression` pipeline runs its training matmuls on the MXU.
"""

from __future__ import annotations

import re
import zlib

import numpy as np
import pyarrow as pa

from .base import Estimator, Model, Transformer

# a small default stop-word list (reference ships loadDefaultStopWords)
_DEFAULT_STOP_WORDS = frozenset("""
a an and are as at be by for from has he in is it its of on that the to
was were will with i you your this they but not or so if then than too
very can could should would do does did done no nor only own same s t
""".split())


def _doc_col(df, col: str) -> list:
    return df.select(col).toArrow().column(0).to_pylist()


def _with_list_column(df, name: str, values, value_type=pa.string()):
    table = df.toArrow()
    arr = pa.array(values, type=pa.list_(value_type))
    if name in table.column_names:
        table = table.drop_columns([name])
    out = df.session.createDataFrame(table.append_column(name, arr))
    out._ml_features = getattr(df, "_ml_features", None)
    return out


class Tokenizer(Transformer):
    """Lowercase whitespace tokenizer (ml/feature/Tokenizer.scala)."""

    _params = {"inputCol": "text", "outputCol": "tokens"}

    def transform(self, df):
        docs = _doc_col(df, self.getOrDefault("inputCol"))
        toks = [(d or "").lower().split() for d in docs]
        return _with_list_column(df, self.getOrDefault("outputCol"), toks)


class RegexTokenizer(Transformer):
    """Pattern-based tokenizer (ml/feature/RegexTokenizer.scala)."""

    _params = {"inputCol": "text", "outputCol": "tokens",
               "pattern": r"\s+", "gaps": True, "toLowercase": True,
               "minTokenLength": 1}

    def transform(self, df):
        pat = re.compile(self.getOrDefault("pattern"))
        gaps = self.getOrDefault("gaps")
        lower = self.getOrDefault("toLowercase")
        mlen = self.getOrDefault("minTokenLength")
        out = []
        for d in _doc_col(df, self.getOrDefault("inputCol")):
            s = (d or "")
            if lower:
                s = s.lower()
            toks = pat.split(s) if gaps else pat.findall(s)
            out.append([t for t in toks if len(t) >= mlen])
        return _with_list_column(df, self.getOrDefault("outputCol"), out)


class StopWordsRemover(Transformer):
    _params = {"inputCol": "tokens", "outputCol": "filtered",
               "stopWords": None, "caseSensitive": False}

    def transform(self, df):
        sw = self.getOrDefault("stopWords")
        cs = self.getOrDefault("caseSensitive")
        stop = set(sw) if sw is not None else set(_DEFAULT_STOP_WORDS)
        if not cs:
            stop = {w.lower() for w in stop}
        out = []
        for toks in _doc_col(df, self.getOrDefault("inputCol")):
            out.append([t for t in (toks or [])
                        if (t if cs else t.lower()) not in stop])
        return _with_list_column(df, self.getOrDefault("outputCol"), out)


class NGram(Transformer):
    _params = {"inputCol": "tokens", "outputCol": "ngrams", "n": 2}

    def transform(self, df):
        n = self.getOrDefault("n")
        out = []
        for toks in _doc_col(df, self.getOrDefault("inputCol")):
            toks = toks or []
            out.append([" ".join(toks[i:i + n])
                        for i in range(len(toks) - n + 1)])
        return _with_list_column(df, self.getOrDefault("outputCol"), out)


def _hash_bucket(term: str, num_features: int) -> int:
    # crc32: deterministic across processes (python hash() is salted)
    return zlib.crc32(term.encode()) % num_features


class HashingTF(Transformer):
    """Hashing-trick term frequencies → fixed-width list<double> column
    (ml/feature/HashingTF.scala)."""

    _params = {"inputCol": "tokens", "outputCol": "tf",
               "numFeatures": 256, "binary": False}

    def transform(self, df):
        d = self.getOrDefault("numFeatures")
        binary = self.getOrDefault("binary")
        vecs = []
        for toks in _doc_col(df, self.getOrDefault("inputCol")):
            v = np.zeros(d)
            for t in (toks or []):
                i = _hash_bucket(t, d)
                v[i] = 1.0 if binary else v[i] + 1.0
            vecs.append(v.tolist())
        return _with_list_column(df, self.getOrDefault("outputCol"), vecs,
                                 pa.float64())


class CountVectorizer(Estimator):
    """Vocabulary-based term counts (ml/feature/CountVectorizer.scala):
    vocab = top vocabSize terms by document frequency, minDF pruning."""

    _params = {"inputCol": "tokens", "outputCol": "tf",
               "vocabSize": 1 << 10, "minDF": 1.0}

    def fit(self, df) -> "CountVectorizerModel":
        docs = _doc_col(df, self.getOrDefault("inputCol"))
        n_docs = max(len(docs), 1)
        dfreq: dict[str, int] = {}
        for toks in docs:
            for t in set(toks or []):
                dfreq[t] = dfreq.get(t, 0) + 1
        min_df = self.getOrDefault("minDF")
        min_count = min_df if min_df >= 1.0 else min_df * n_docs
        terms = [(c, t) for t, c in dfreq.items() if c >= min_count]
        terms.sort(key=lambda x: (-x[0], x[1]))
        vocab = [t for _, t in terms[:self.getOrDefault("vocabSize")]]
        return CountVectorizerModel(
            inputCol=self.getOrDefault("inputCol"),
            outputCol=self.getOrDefault("outputCol"))._with_vocab(vocab)


class CountVectorizerModel(Model):
    _params = {"inputCol": "tokens", "outputCol": "tf"}

    def _with_vocab(self, vocab):
        self.vocabulary = list(vocab)
        self._index = {t: i for i, t in enumerate(vocab)}
        return self

    def transform(self, df):
        d = len(self.vocabulary)
        vecs = []
        for toks in _doc_col(df, self.getOrDefault("inputCol")):
            v = np.zeros(d)
            for t in (toks or []):
                i = self._index.get(t)
                if i is not None:
                    v[i] += 1.0
            vecs.append(v.tolist())
        return _with_list_column(df, self.getOrDefault("outputCol"), vecs,
                                 pa.float64())


class IDF(Estimator):
    """Inverse document frequency over TF vectors
    (ml/feature/IDF.scala): idf = log((n+1)/(df+1))."""

    _params = {"inputCol": "tf", "outputCol": "tfidf", "minDocFreq": 0}

    def fit(self, df) -> "IDFModel":
        tf = np.asarray(_doc_col(df, self.getOrDefault("inputCol")),
                        dtype=np.float64)
        n = tf.shape[0]
        dfreq = (tf > 0).sum(axis=0)
        idf = np.log((n + 1.0) / (dfreq + 1.0))
        idf[dfreq < self.getOrDefault("minDocFreq")] = 0.0
        return IDFModel(inputCol=self.getOrDefault("inputCol"),
                        outputCol=self.getOrDefault("outputCol")) \
            ._with_idf(idf)


class IDFModel(Model):
    _params = {"inputCol": "tf", "outputCol": "tfidf", "minDocFreq": 0}

    def _with_idf(self, idf):
        self.idf = idf
        return self

    def transform(self, df):
        tf = np.asarray(_doc_col(df, self.getOrDefault("inputCol")),
                        dtype=np.float64)
        out = (tf * self.idf[None, :]).tolist()
        return _with_list_column(df, self.getOrDefault("outputCol"), out,
                                 pa.float64())
