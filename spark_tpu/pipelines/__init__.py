"""Declarative pipelines (reference: sql/pipelines + python/pyspark/pipelines).

See graph.py for the execution model.
"""

from .graph import (  # noqa: F401
    Pipeline, PipelineError, append_flow, materialized_view, table,
    temporary_view,
)
