"""Declarative pipeline graph: datasets as decorated query functions.

Role of the reference's Declarative Pipelines layer (sql/pipelines —
graph/{DataflowGraph,GraphExecution,FlowExecution}.scala — and the
python decorator surface python/pyspark/pipelines/api.py:
materialized_view / table / temporary_view / append_flow). The model:

* a DATASET is declared by decorating a zero-arg query function; its
  body reads other datasets through `pipeline.read(name)` (or
  `spark.table(name)` after they materialize);
* dependencies are discovered DYNAMICALLY: running a flow that reads a
  not-yet-materialized dataset recursively materializes it first, with
  cycle detection (the reference resolves its graph topologically from
  declared inputs; dynamic discovery needs no separate declaration);
* `materialized_view` persists to the warehouse when one is configured
  (falling back to a session temp view), `temporary_view` never
  persists, `table` is a streaming-style target that APPEND FLOWS
  (`append_flow(target=...)`) feed incrementally — each run executes
  new flow output and unions it into the target, the reference's
  streaming-table/flow split.

    from spark_tpu.pipelines import Pipeline
    p = Pipeline(spark)

    @p.materialized_view()
    def customers():
        return spark.read.parquet("/data/customers")

    @p.materialized_view()
    def big_spenders():
        return p.read("customers").filter("spend > 100")

    p.run()   # materializes every dataset in dependency order
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class PipelineError(RuntimeError):
    pass


class _Dataset:
    def __init__(self, name: str, fn: Optional[Callable], kind: str,
                 comment: str = ""):
        self.name = name
        self.fn = fn
        self.kind = kind          # materialized_view | temporary_view | table
        self.comment = comment
        self.flows: list[tuple[str, Callable]] = []  # append flows


class Pipeline:
    """One dataflow graph bound to a session (DataflowGraph role)."""

    def __init__(self, session):
        self.session = session
        self._datasets: dict[str, _Dataset] = {}
        self._state: dict[str, str] = {}  # name → pending|running|done
        self._lock = threading.RLock()
        self.events: list[str] = []       # run log (ProgressReporter role)

    # -- declaration decorators -----------------------------------------
    def materialized_view(self, name: str | None = None, comment: str = ""):
        return self._decorate("materialized_view", name, comment)

    def temporary_view(self, name: str | None = None, comment: str = ""):
        return self._decorate("temporary_view", name, comment)

    def table(self, name: str | None = None, comment: str = ""):
        """A flow-fed target table: its own body (if any) seeds it; append
        flows add to it on every run (StreamingTable + append_flow)."""
        return self._decorate("table", name, comment)

    def _decorate(self, kind: str, name, comment):
        def deco(fn):
            dname = name or fn.__name__
            if dname in self._datasets:
                raise PipelineError(f"dataset {dname!r} defined twice")
            self._datasets[dname] = _Dataset(dname, fn, kind, comment)
            return fn

        return deco

    def append_flow(self, target: str, name: str | None = None):
        def deco(fn):
            ds = self._datasets.get(target)
            if ds is None or ds.kind != "table":
                raise PipelineError(
                    f"append_flow target {target!r} is not a declared table")
            ds.flows.append((name or fn.__name__, fn))
            return fn

        return deco

    # -- reads ----------------------------------------------------------
    def read(self, name: str):
        """Read a pipeline dataset from inside a flow body; materializes
        it first if needed (the dynamic dependency edge)."""
        if name in self._datasets:
            self._materialize(name)
        return self.session.table(name)

    # -- execution -------------------------------------------------------
    def run(self, full_refresh: bool = True) -> dict:
        """Materialize every dataset in dependency order; returns
        name → row count (GraphExecution role). Flow-fed tables are
        rebuilt from their flows on EVERY run; full_refresh=False keeps
        already-materialized views and only refreshes the tables (the
        streaming-table vs materialized-view refresh split)."""
        if full_refresh:
            self._state.clear()
        else:
            for name, ds in self._datasets.items():
                if ds.kind == "table":
                    self._state.pop(name, None)
        counts = {}
        for name in self._datasets:
            self._materialize(name)
        for name in self._datasets:
            counts[name] = self.session.table(name).count()
        return counts

    def _materialize(self, name: str) -> None:
        with self._lock:
            st = self._state.get(name)
            if st == "done":
                return
            if st == "running":
                raise PipelineError(
                    f"cycle detected through dataset {name!r}")
            self._state[name] = "running"
        try:
            ds = self._datasets[name]
            df = ds.fn() if ds.fn is not None else None
            if ds.kind == "table":
                parts = [] if df is None else [df.toArrow()]
                for fname, flow in ds.flows:
                    self.events.append(f"flow {fname} -> {name}")
                    parts.append(flow().toArrow())
                if not parts:
                    raise PipelineError(
                        f"table {name!r} has no body and no flows")
                import pyarrow as pa

                table = pa.concat_tables(parts,
                                         promote_options="permissive")
                self.session.createDataFrame(table) \
                    .createOrReplaceTempView(name)
            elif ds.kind == "materialized_view":
                table = df.toArrow()
                wh = self.session.catalog_.external
                if wh is not None:
                    wh.save_table(name, table, mode="overwrite")
                self.session.createDataFrame(table) \
                    .createOrReplaceTempView(name)
            else:  # temporary_view
                df.createOrReplaceTempView(name)
            self.events.append(f"materialized {ds.kind} {name}")
        except Exception:
            with self._lock:
                self._state[name] = "pending"
            raise
        with self._lock:
            self._state[name] = "done"


# -- module-level decorator surface (pyspark.pipelines.api shape) --------
_ACTIVE: list[Pipeline] = []


def _active() -> Pipeline:
    if not _ACTIVE:
        raise PipelineError(
            "no active Pipeline; use `with pipeline:` or the instance "
            "decorators (p.materialized_view()/p.table())")
    return _ACTIVE[-1]


def materialized_view(name: str | None = None, comment: str = ""):
    return _active().materialized_view(name, comment)


def temporary_view(name: str | None = None, comment: str = ""):
    return _active().temporary_view(name, comment)


def table(name: str | None = None, comment: str = ""):
    return _active().table(name, comment)


def append_flow(target: str, name: str | None = None):
    return _active().append_flow(target, name)


def _enter(self):
    _ACTIVE.append(self)
    return self


def _exit(self, *exc):
    _ACTIVE.pop()
    return False


Pipeline.__enter__ = _enter
Pipeline.__exit__ = _exit
