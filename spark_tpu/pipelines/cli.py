"""Pipelines CLI (reference: bin/spark-pipelines →
python/pyspark/pipelines/cli.py): run a python file that declares a
Pipeline; every Pipeline instance found in the module is executed."""

from __future__ import annotations

import argparse
import runpy
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="spark_tpu pipelines runner")
    p.add_argument("script", help="python file declaring Pipeline(s)")
    p.add_argument("--dry-run", action="store_true",
                   help="list datasets without materializing")
    args = p.parse_args(argv)

    from .graph import Pipeline

    ns = runpy.run_path(args.script)
    pipelines = [v for v in ns.values() if isinstance(v, Pipeline)]
    if not pipelines:
        print("no Pipeline instances found", file=sys.stderr)
        return 1
    for pl in pipelines:
        if args.dry_run:
            for name, ds in pl._datasets.items():
                print(f"{ds.kind:18s} {name}"
                      + (f"  ({len(ds.flows)} flows)" if ds.flows else ""))
            continue
        counts = pl.run()
        for name, n in counts.items():
            print(f"{name}: {n} rows")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
