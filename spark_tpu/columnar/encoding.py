"""Encoded-column metadata: run-length segments and dictionary-code domains.

The compressed-execution layer (ROADMAP direction 3, "GPU Acceleration of
SQL Analytics on Compressed Data" in PAPERS.md): columns carry cheap
host-side encoding metadata harvested while the data is still a numpy
array at ingest, and kernels pick encoding-native variants from it
without ever launching a probe or decoding a value:

  * `RunInfo` — run-length structure of an integral column (run count,
    sortedness, first/last value). A SORTED single grouping key reduces
    per run boundary (ops/grouping.group_rows_presorted) instead of
    paying the O(n log n) grouping sort — the RLE-aware segment reduce.
  * dictionary codes — a string column's int32 codes are a DENSE group
    domain [0, len(dict)): the dense-scatter aggregate keys directly on
    codes with the span known host-side (len(dictionary)), so encoded
    group-by columns never launch the krange3 range probe.
  * padded dictionary-hash luts (built on StringDict) — codes → stable
    value hashes as kernel aux inputs, so `eq_keys` works INSIDE a traced
    stage kernel and string join/exchange keys fuse.

Everything here is metadata: zero kernel launches, no device syncs.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

__all__ = ["RunInfo", "column_runs", "configure", "encoding_enabled",
           "runs_harvest_enabled"]

# process-wide switch rather than a per-call conf read: the RunInfo
# harvest sits on the batch-ingest hot path (every integral column of
# every tile) — flipped by configure(), the same pattern as
# obs/resources (TpuSession.__init__ + worker begin_stage_obs)
_ENCODING_ON = True


def configure(conf) -> None:
    """Apply a session/worker conf to the process-global encoding
    switch (spark.tpu.encoding.enabled). Dynamic conf flips after
    session start still govern the DECISION sites (which read the conf
    directly); this only gates the ingest-time metadata harvest."""
    global _ENCODING_ON

    from ..config import ENCODING_ENABLED

    # conf values are host data — never a device read
    _ENCODING_ON = bool(conf.get(ENCODING_ENABLED))  # tpulint: ignore[host-sync]


def runs_harvest_enabled() -> bool:
    return _ENCODING_ON


class RunInfo(NamedTuple):
    """Host-side run-length summary of one ingested column's live prefix.

    Computed on the host array at ingest (O(n) numpy, no device work) for
    integral/date columns without a validity plane. `is_sorted` licenses
    the run-boundary (sort-free) grouped aggregation: later mask-only
    filters never reorder rows, so sortedness survives every mask-based
    operator — only fresh kernel-output columns drop it."""

    n_runs: int
    is_sorted: bool
    first: int
    last: int


def column_runs(data: np.ndarray, n: int) -> RunInfo | None:
    """RunInfo over the first `n` (live) rows of a host integral array,
    or None for empty/degenerate inputs."""
    if n <= 0 or data.dtype.kind not in "iu":
        return None
    live = data[:n]
    if n == 1:
        # host numpy only — `live` is the ingest-time numpy plane
        return RunInfo(1, True, int(live[0]), int(live[0]))  # tpulint: ignore[host-sync]
    diff = np.diff(live)
    n_runs = int(np.count_nonzero(diff)) + 1  # tpulint: ignore[host-sync]
    is_sorted = bool((diff >= 0).all())  # tpulint: ignore[host-sync]
    return RunInfo(n_runs, is_sorted,
                   int(live[0]), int(live[-1]))  # tpulint: ignore[host-sync]


def encoding_enabled(conf) -> bool:
    """spark.tpu.encoding.enabled — the compressed-execution switch
    (off = the decode-at-boundary oracle)."""
    from ..config import ENCODING_ENABLED

    try:
        return bool(conf.get(ENCODING_ENABLED))  # tpulint: ignore[host-sync]
    except Exception:
        return True
