"""Batch invariant validation (debug mode).

Role of the reference's test-side sanitizers (SURVEY.md §5 'Race detection /
sanitizers': DebugFilesystem, shuffle checksums, ThreadAudit) for the
columnar layer: with spark.tpu.debug.validateBatches=true every operator
boundary checks batch invariants — shape agreement, dictionary code bounds,
validity/mask dtypes — catching kernel bugs at the operator that produced
them instead of rows downstream.
"""

from __future__ import annotations

import numpy as np

from ..errors import ExecutionError
from ..types import StringType
from .batch import ColumnarBatch


def validate_batch(batch: ColumnarBatch, site: str = "") -> None:
    cap = batch.capacity
    if len(batch.columns) != len(batch.schema.fields):
        raise ExecutionError(
            f"[{site}] column count {len(batch.columns)} != schema "
            f"{len(batch.schema.fields)}")
    mask = np.asarray(batch.row_mask)
    if mask.dtype != np.bool_ or mask.shape != (cap,):
        raise ExecutionError(f"[{site}] bad row mask {mask.dtype} {mask.shape}")
    for f, c in zip(batch.schema.fields, batch.columns):
        d = np.asarray(c.data)
        if d.shape != (cap,):
            raise ExecutionError(
                f"[{site}] column {f.name}: shape {d.shape} != cap {cap}")
        if d.dtype != f.dataType.device_dtype:
            raise ExecutionError(
                f"[{site}] column {f.name}: dtype {d.dtype} != "
                f"{f.dataType.device_dtype}")
        if c.validity is not None:
            v = np.asarray(c.validity)
            if v.dtype != np.bool_ or v.shape != (cap,):
                raise ExecutionError(
                    f"[{site}] column {f.name}: bad validity "
                    f"{v.dtype} {v.shape}")
        if isinstance(f.dataType, StringType):
            if c.dictionary is None:
                raise ExecutionError(
                    f"[{site}] string column {f.name} missing dictionary")
            live = d[mask]
            if c.validity is not None:
                live = d[mask & np.asarray(c.validity)]
            n = max(len(c.dictionary), 1)
            if live.size and (live.min() < 0 or live.max() >= n):
                raise ExecutionError(
                    f"[{site}] column {f.name}: code out of range "
                    f"[{live.min()}, {live.max()}] for dict size {n}")


def maybe_validate(parts, ctx, site: str):
    if str(ctx.conf.get("spark.tpu.debug.validateBatches", "false")) \
            .lower() != "true":
        return parts
    for p in parts:
        for b in p:
            validate_batch(b, site)
    return parts
