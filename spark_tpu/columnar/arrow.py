"""Arrow ⇄ ColumnarBatch interchange.

Role of the reference's ArrowConverters (sqlx/arrow/ArrowConverters.scala:216
toBatchIterator / :447 fromBatchIterator) — but Arrow is our *native* ingest
format rather than a sidecar: scans deliver pyarrow RecordBatches which are
dictionary-encoded, padded to a capacity bucket, and shipped to device HBM.
"""

from __future__ import annotations

from typing import Iterable, Iterator

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..types import (
    ArrayType,
    DataType,
    DecimalType,
    MapType,
    StringType,
    StructField,
    StructType,
    from_arrow_type,
)
from .batch import (
    Column, ColumnarBatch, StringDict, bucket_capacity, encode_values,
)

__all__ = ["schema_from_arrow", "table_to_batches", "batches_to_table",
           "record_batch_to_columnar"]


def schema_from_arrow(aschema: pa.Schema) -> StructType:
    return StructType([
        StructField(f.name, from_arrow_type(f.type), f.nullable)
        for f in aschema
    ])


def _chunked_to_numpy(arr: pa.ChunkedArray | pa.Array, dt: DataType):
    """→ (data ndarray in device dtype, validity ndarray|None, StringDict|None)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    validity = None
    if arr.null_count:
        validity = np.asarray(arr.is_valid())

    if isinstance(dt, StringType):
        if pa.types.is_dictionary(arr.type):
            darr = arr
        else:
            darr = pc.dictionary_encode(arr)
        if isinstance(darr, pa.ChunkedArray):
            darr = darr.combine_chunks()
        codes = np.asarray(darr.indices.fill_null(0)).astype(np.int32)
        values = darr.dictionary.to_pylist()
        sd = StringDict([v if v is not None else "" for v in values])
        return codes, validity, sd

    if isinstance(dt, DecimalType):
        # scaled int64
        scaled = pc.multiply(pc.cast(arr, pa.float64()), 10.0 ** dt.scale)
        data = np.rint(np.asarray(pc.cast(scaled, pa.float64()).fill_null(0))).astype(np.int64)
        return data, validity, None

    if isinstance(dt, (ArrayType, MapType, StructType)):
        # nested values dictionary-encode like strings: int32 codes on
        # device, python values (lists / dicts) host-side
        vals = arr.to_pylist()
        if isinstance(dt, MapType):
            # pyarrow maps materialize as lists of (k, v) pairs
            vals = [dict(v) if v is not None else None for v in vals]
        uniq, codes = encode_values(vals)
        empty = {} if isinstance(dt, (MapType, StructType)) else []
        return codes, validity, StringDict(uniq or [empty])

    at = arr.type
    if pa.types.is_date32(at):
        data = np.asarray(arr.fill_null(0)).astype("datetime64[D]").astype(np.int32)
        return data, validity, None
    if pa.types.is_timestamp(at):
        a = pc.cast(arr, pa.timestamp("us"))
        data = np.asarray(a.fill_null(0)).astype("datetime64[us]").astype(np.int64)
        return data, validity, None
    if pa.types.is_boolean(at):
        data = np.asarray(arr.fill_null(False)).astype(bool)
        return data, validity, None
    data = np.asarray(arr.fill_null(0)).astype(dt.device_dtype)
    return data, validity, None


def record_batch_to_columnar(rb: pa.RecordBatch | pa.Table,
                             schema: StructType | None = None,
                             capacity: int | None = None,
                             num_rows: int | None = None,
                             seed_ranges: dict | None = None,
                             dict_cache: dict | None = None,
                             dict_tokens: dict | None = None) -> ColumnarBatch:
    """Ingest one arrow slice into a device tile.

    `dict_cache`/`dict_tokens` (cluster shuffle reads): a {token →
    StringDict} intern table plus this slice's per-column dictionary
    tokens (shipped on the MapStatus). A token hit attaches the SAME
    StringDict object the previous block rebuilt — downstream
    concat/merge takes the identity fast path instead of re-merging
    equal dictionaries, with no host sync anywhere."""
    import jax.numpy as jnp

    if schema is None:
        schema = schema_from_arrow(rb.schema)
    # pyarrow reports unreliable num_rows for zero-column slices
    n = num_rows if num_rows is not None else rb.num_rows
    cap = capacity or bucket_capacity(max(n, 1))
    cols = []
    ranges = {}
    for i, f in enumerate(schema.fields):
        data, validity, sd = _chunked_to_numpy(rb.column(i), f.dataType)
        if sd is not None and dict_cache is not None \
                and dict_tokens is not None and i in dict_tokens:
            tok = dict_tokens[i]
            cached = dict_cache.get(tok)
            if cached is not None and len(cached) == len(sd):
                sd = cached  # identity remap: equal content, shared object
            else:
                dict_cache[tok] = sd
        pad = np.zeros(cap, dtype=f.dataType.device_dtype)
        pad[:n] = data[:cap]
        v = None
        if validity is not None:
            vm = np.zeros(cap, dtype=bool)
            vm[:n] = validity[:cap]
            v = jnp.asarray(vm)
        runs = None
        if validity is None and sd is None and pad.dtype.kind == "i":
            # run/sortedness metadata from the host copy (encoding.py):
            # licenses the sort-free run-boundary aggregate variant;
            # skipped entirely under the decoded oracle
            from .encoding import column_runs, runs_harvest_enabled

            if runs_harvest_enabled():
                runs = column_runs(pad, min(n, cap))
        col = Column(f.dataType, jnp.asarray(pad), v, sd, runs=runs)
        # key range from the HOST copy while we still have it: the dense
        # aggregate/join fast-path decision then never needs a device→host
        # sync (transfer-bound transports degrade permanently after one).
        # `seed_ranges` are precomputed upstream stats (shuffle reads: the
        # map side shipped them with the MapStatus) — possibly a SUPERSET
        # of this batch's range, which the dense decision handles soundly
        # and which keeps local and cluster decisions identical.
        if pad.dtype.kind == "i" and sd is None:
            if seed_ranges is not None and i in seed_ranges:
                ranges[i] = tuple(seed_ranges[i])
            else:
                live = data[:cap] if validity is None \
                    else data[:cap][validity[:cap]]
                if len(live):
                    ranges[i] = (int(live.min()), int(live.max()), True)
                else:
                    ranges[i] = (0, 0, False)
        elif sd is not None:
            # dictionary code span is known host-side — codes live in
            # [0, len(dict)): seed the dense-range memo so ANY dense
            # consumer of the code plane decides without a krange3 probe
            # (the dense-on-codes aggregate reads len(dict) directly and
            # never consults the memo, but this keeps the invariant for
            # every other range reader)
            any_live = n > 0 and (
                validity is None
                or bool(validity[:n].any()))  # tpulint: ignore[host-sync]
            ranges[i] = (0, max(len(sd) - 1, 0), any_live)
        cols.append(col)
    mask = np.zeros(cap, dtype=bool)
    mask[:n] = True
    mask_d = jnp.asarray(mask)
    out = ColumnarBatch(schema, cols, mask_d, num_rows=n)
    if ranges:
        # seed the process-global device-scalar memo keyed by the final
        # (data, validity, row_mask) identities — dense_range_stats hits it
        # without ever dispatching its range-probe kernel
        from ..utils.device_memo import seed_dense_range_memo

        for i, rng in ranges.items():
            seed_dense_range_memo(cols[i], mask_d, rng)
    return out


def table_to_batches(table: pa.Table, rows_per_batch: int,
                     schema: StructType | None = None) -> Iterator[ColumnarBatch]:
    """Slice an Arrow table into fixed-capacity ColumnarBatches."""
    if schema is None:
        schema = schema_from_arrow(table.schema)
    n = table.num_rows
    if n == 0:
        yield ColumnarBatch.empty(schema)
        return
    for start in range(0, n, rows_per_batch):
        chunk = table.slice(start, rows_per_batch)
        chunk_rows = min(rows_per_batch, n - start)
        # size the tile to the DATA (power-of-two bucket), not the maximum
        # tile: padding multiplies every downstream kernel's work
        yield record_batch_to_columnar(
            chunk, schema, capacity=bucket_capacity(chunk_rows),
            num_rows=chunk_rows)


def batches_to_table(batches: Iterable[ColumnarBatch]) -> pa.Table:
    tables = [b.to_arrow() for b in batches]
    tables = [t for t in tables if t.num_rows or len(tables) == 1]
    if not tables:
        raise ValueError("no batches")
    return pa.concat_tables(tables, promote_options="permissive")
