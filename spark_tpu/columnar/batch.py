"""Columnar batch substrate: fixed-capacity device tiles.

Role of the reference's vectorized layer — ColumnVector/ColumnarBatch
(sqlcatj/vectorized/{ColumnVector,ColumnarBatch}.java) and the writable
On/OffHeapColumnVector (sqlxj/vectorized/OffHeapColumnVector.java) — re-designed
for XLA:

  * Every batch has a STATIC power-of-two `capacity`; the number of live rows
    is carried as a boolean `row_mask` device array, so filters/joins never
    change array shapes (no XLA recompilation per cardinality; SURVEY.md §7
    'Hard parts' (1)).
  * A column is a device array in the type's device representation plus an
    optional validity (null) mask. Strings/binary are dictionary-encoded:
    int32 codes on device, UTF-8 values host-side in a StringDict (the
    reference keeps UTF8String bytes in UnsafeRow; on TPU bytes stay on host
    and comparisons ride hashes/ranks — SURVEY.md §2.5).
  * Selection is mask-based (the reference's selection-vector idea); host
    materialization compacts.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace
from functools import cached_property
from typing import Any, Iterable, Sequence

import numpy as np

from ..types import (
    ArrayType,
    BooleanType,
    DataType,
    DecimalType,
    MapType,
    NullType,
    StringType,
    StructField,
    StructType,
    dict_encoded,
    from_arrow_type,
    to_arrow_type,
)

__all__ = ["StringDict", "Column", "ColumnarBatch", "bucket_capacity", "EMPTY_DICT"]


def bucket_capacity(n: int, minimum: int = 1 << 10) -> int:
    """Round row count up to a power-of-two capacity bucket so jitted kernels
    are reused across batches (bounded recompile cache; the reference instead
    re-JITs Janino code per plan — codegen/CodeGenerator.scala:1557)."""
    cap = minimum
    while cap < n:
        cap <<= 1
    return cap


def _hash_str(s: str) -> int:
    """Deterministic 64-bit hash of a UTF-8 string (signed int64).

    Per-dictionary-entry only — row-level hashing happens on device via code
    lookup. (Native murmur3 path lives in native/; this is the fallback.)
    """
    d = hashlib.blake2b(s.encode("utf-8"), digest_size=8).digest()
    return int.from_bytes(d, "little", signed=True)


class StringDict:
    """Host-side dictionary for a string column: unique UTF-8 values.

    Device-side derivatives (lazily cached):
      * hashes: int64[n_values] stable hash per value — the cross-dictionary
        equality domain used by joins/group-bys over string keys.
      * ranks:  int32[n_values] lexicographic rank — the ORDER BY key domain.
    """

    __slots__ = ("values", "_index", "_hashes", "_ranks", "_device_hashes",
                 "_device_ranks", "_hash_luts", "_token")

    def __init__(self, values: Sequence[str]):
        self.values: list[str] = list(values)
        self._index: dict[str, int] | None = None
        self._hashes: np.ndarray | None = None
        self._ranks: np.ndarray | None = None
        self._device_hashes = None
        self._device_ranks = None
        self._hash_luts: dict | None = None  # pow2 bucket -> device lut
        self._token: str | None = None

    def __len__(self) -> int:
        return len(self.values)

    # The lazy caches below (_index/_device_hashes/_device_ranks/
    # _hash_luts/_token) are pure functions of `values`, which is frozen
    # at construction: par_map lanes racing on first touch recompute the
    # SAME value and last-writer-wins is safe (wasted work, never a
    # wrong answer). A lock here would serialize jnp.asarray uploads.
    @property
    def index(self) -> dict[str, int]:
        if self._index is None:
            # race-lint: ignore[shared-mutation] — idempotent lazy memo
            self._index = {v: i for i, v in enumerate(self.values)}
        return self._index

    def code_of(self, value: str) -> int | None:
        return self.index.get(value)

    @property
    def hashes(self) -> np.ndarray:
        if self._hashes is None:
            try:
                from ..utils.native import hash_strings
                self._hashes = hash_strings(self.values)
            except Exception:
                self._hashes = np.array(
                    [_hash_str(v) for v in self.values], dtype=np.int64)
        return self._hashes

    @property
    def ranks(self) -> np.ndarray:
        if self._ranks is None:
            order = np.argsort(np.array(self.values, dtype=object), kind="stable")
            r = np.empty(len(self.values), dtype=np.int32)
            r[order] = np.arange(len(self.values), dtype=np.int32)
            self._ranks = r
        return self._ranks

    def device_hashes(self):
        if self._device_hashes is None:
            import jax.numpy as jnp

            h = self.hashes if len(self.values) else np.zeros(1, np.int64)
            # race-lint: ignore[shared-mutation] — idempotent lazy memo
            self._device_hashes = jnp.asarray(h)
        return self._device_hashes

    def device_ranks(self):
        if self._device_ranks is None:
            import jax.numpy as jnp

            r = self.ranks if len(self.values) else np.zeros(1, np.int32)
            # race-lint: ignore[shared-mutation] — idempotent lazy memo
            self._device_ranks = jnp.asarray(r)
        return self._device_ranks

    def device_hash_lut(self, minimum: int = 8):
        """Padded codes→value-hash lut as a kernel AUX input: the eq-key
        domain (joins/exchanges on string keys) computed INSIDE a traced
        stage kernel via one `take`. Padded to a power-of-two bucket so
        the kernel cache key depends on the BUCKET, not the exact
        dictionary size — dictionaries that drift a few entries between
        batches reuse one compiled kernel. Pad entries are zeros: live
        valid codes are always < len(values), so padding is never read
        by a row that matters. Cached per bucket on the dictionary."""
        import jax.numpy as jnp

        n = max(len(self.values), 1)
        bucket = bucket_capacity(n, minimum=minimum)
        if self._hash_luts is None:
            # race-lint: ignore[shared-mutation] — idempotent lazy memo
            self._hash_luts = {}
        lut = self._hash_luts.get(bucket)
        if lut is None:
            h = np.zeros(bucket, dtype=np.int64)
            if len(self.values):
                h[: len(self.values)] = self.hashes
            # race-lint: ignore[shared-mutation] — idempotent lazy memo
            lut = self._hash_luts[bucket] = jnp.asarray(h)
        return lut

    def token(self) -> str:
        """Stable content fingerprint — the dictionary IDENTITY shipped on
        MapStatus/shuffle payloads so reduce sides recognize equal
        dictionaries across map tasks and remap by reference instead of
        re-merging (cluster shuffle ships codes + ONE dictionary per map
        task; equal tokens rebuild to one shared StringDict object)."""
        if self._token is None:
            h = hashlib.blake2b(digest_size=16)
            h.update(str(len(self.values)).encode())
            for v in self.values:
                s = v if isinstance(v, str) else repr(canon_value(v))
                h.update(s.encode("utf-8", "surrogatepass"))
                h.update(b"\x00")
            # race-lint: ignore[shared-mutation] — idempotent lazy memo
            self._token = h.hexdigest()
        return self._token

    def device_rank_to_code(self):
        """Inverse of ranks: rank → dictionary code (string MIN/MAX
        aggregation: reduce in rank space, map the winner back to a code)."""
        import jax.numpy as jnp

        r = self.ranks if len(self.values) else np.zeros(1, np.int32)
        inv = np.empty(len(r), dtype=np.int32)
        inv[r] = np.arange(len(r), dtype=np.int32)
        return jnp.asarray(inv)

    def map_values(self, fn) -> "StringDict":
        """Apply a host string→string function to every dictionary entry —
        how upper/lower/substr/concat-literal execute in O(|dict|) instead of
        O(rows) (no reference analog; enabled by dictionary encoding)."""
        return StringDict([fn(v) for v in self.values])

    @staticmethod
    def merged(a: "StringDict", b: "StringDict"):
        """Union two dictionaries; returns (merged, recode_a, recode_b) where
        recode_x maps old codes → merged codes."""
        md, (ra, rb) = merge_string_dicts([a, b])
        return md, ra, rb


def canon_value(v):
    """Hashable canonical form for a dictionary value. Dict items are
    SORTED: maps are unordered (two insertion orders are the same map);
    for structs the field order is schema-fixed so sorting is harmless."""
    if isinstance(v, dict):
        return tuple(sorted((k, canon_value(x)) for k, x in v.items()))
    if isinstance(v, (list, tuple, np.ndarray)):
        return tuple(canon_value(x) for x in v)
    return v


def encode_values(values, codes: np.ndarray | None = None):
    """Dictionary-encode a sequence of python values (None → code 0,
    caller tracks validity separately). Returns (unique values, codes)."""
    n = len(values)
    if codes is None:
        codes = np.zeros(n, np.int32)
    uniq: list = []
    index: dict = {}
    for i, v in enumerate(values):
        if v is None:
            continue
        k = canon_value(v)
        j = index.get(k)
        if j is None:
            j = len(uniq)
            uniq.append(v)
            index[k] = j
        codes[i] = j
    return uniq, codes


def merge_string_dicts(dicts: Sequence["StringDict"]):
    """Union several dictionaries; returns (merged StringDict,
    [recode int32 array per dict]). Uses the C++ open-addressing merge
    (native/sparktpu_native.cpp spark_tpu_merge_dicts) when built; nested
    values (lists/dicts) take the canonical-key python path. Dictionaries
    are type-homogeneous per column, so the first value decides the path."""
    all_str = all(isinstance(d.values[0], str)
                  for d in dicts if d.values)
    if all_str:
        try:
            from ..utils.native import merge_dicts

            merged_vals, recodes = merge_dicts([d.values for d in dicts])
            recodes = [r if len(r) else np.zeros(1, np.int32)
                       for r in recodes]
            return StringDict(merged_vals), recodes
        except Exception:
            pass
    merged: list = []
    idx: dict = {}
    recodes = []
    for d in dicts:
        # empty dictionaries contribute nothing; their (all-masked/invalid)
        # rows keep code 0, which decoding treats as the type default. Never
        # pad with "" here — for map/array/struct dictionaries a stray str
        # corrupts decoding (v.items() on "").
        lut = np.zeros(max(len(d.values), 1), dtype=np.int32)
        for i, v in enumerate(d.values):
            k = canon_value(v)
            j = idx.get(k)
            if j is None:
                j = len(merged)
                merged.append(v)
                idx[k] = j
            lut[i] = j
        recodes.append(lut)
    return StringDict(merged), recodes


EMPTY_DICT = StringDict([])


@dataclass(frozen=True)
class Column:
    """One column of a batch: device data + optional validity mask.

    data: device array [capacity] in dtype.device_dtype
    validity: device bool array [capacity] or None (= no nulls)
    dictionary: StringDict for string-like columns
    runs: host-side RunInfo (columnar/encoding.py) harvested at ingest —
        run-length/sortedness metadata licensing encoding-native kernel
        variants; dropped whenever the data plane is replaced
    """

    dtype: DataType
    data: Any
    validity: Any = None
    dictionary: StringDict | None = None
    runs: Any = None

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, StringType)

    def with_data(self, data, validity="__keep__") -> "Column":
        v = self.validity if validity == "__keep__" else validity
        # fresh data plane: ingest-time run metadata no longer describes it
        return replace(self, data=data, validity=v, runs=None)

    # --- device key domains ----------------------------------------------
    def eq_keys(self):
        """Device array usable as an equality-comparison key (joins, group-by,
        distinct). Strings map codes → stable 64-bit value hashes so columns
        with different dictionaries compare correctly."""
        if self.is_string:
            import jax.numpy as jnp

            codes = jnp.clip(self.data, 0, max(len(self.dictionary) - 1, 0))
            return jnp.take(self.dictionary.device_hashes(), codes)
        if isinstance(self.dtype, BooleanType):
            return self.data.astype(np.int32)
        return self.data

    def sort_keys(self):
        """Device array whose numeric order == SQL ORDER BY order."""
        if self.is_string:
            import jax.numpy as jnp

            codes = jnp.clip(self.data, 0, max(len(self.dictionary) - 1, 0))
            return jnp.take(self.dictionary.device_ranks(), codes)
        if isinstance(self.dtype, BooleanType):
            return self.data.astype(np.int32)
        return self.data

    # --- host materialization --------------------------------------------
    def to_numpy(self, selection: np.ndarray | None = None) -> np.ndarray:
        """Materialize (optionally selecting rows) into a host array of
        Python-level values (strings decoded, decimals scaled)."""
        data = np.asarray(self.data)
        valid = None if self.validity is None else np.asarray(self.validity)
        if selection is not None:
            data = data[selection]
            valid = valid[selection] if valid is not None else None
        if self.is_string or isinstance(self.dtype,
                                        (ArrayType, MapType, StructType)):
            # explicit fill: np.array() would make ragged equal-length
            # lists into a 2-D array
            vals = np.empty(len(self.dictionary.values) + 1, dtype=object)
            for i, v in enumerate(self.dictionary.values):
                vals[i] = v
            vals[-1] = [] if isinstance(self.dtype, ArrayType) else \
                {} if isinstance(self.dtype, (MapType, StructType)) else ""
            codes = np.clip(data, 0, len(self.dictionary.values))
            out = vals[codes] if len(self.dictionary) else \
                vals[np.full(len(data), -1)]
            out = np.asarray(out, dtype=object)
        elif isinstance(self.dtype, DecimalType):
            out = data.astype(np.float64) / (10 ** self.dtype.scale)
        else:
            out = data
        if valid is not None:
            out = np.asarray(out, dtype=object) if out.dtype != object else out
            out = out.copy()
            out[~valid] = None
        return out


class ColumnarBatch:
    """A fixed-capacity tile of rows (SURVEY.md §7 step 1).

    columns are positional; `schema` names them. `row_mask` marks live rows.
    `num_rows` is the host-known live count when available (None after a
    device-side filter until counted)."""

    # __weakref__: the device-resource ledger (obs/resources.py) arms a
    # weakref finalizer per batch to release its HBM charge on GC
    __slots__ = ("schema", "columns", "row_mask", "_num_rows", "_stats",
                 "__weakref__")

    def __init__(self, schema: StructType, columns: Sequence[Column], row_mask,
                 num_rows: int | None = None):
        assert len(schema.fields) == len(columns), (len(schema.fields), len(columns))
        self.schema = schema
        self.columns = list(columns)
        self.row_mask = row_mask
        self._num_rows = num_rows
        self._stats = None  # lazy per-batch kernel-result cache (dense agg
        # range etc.) so repeated executions over a cached batch skip the
        # host round-trip of re-syncing the same scalars
        # HBM ledger registration: charge this tile's device planes to
        # the current query/operator scope (array-identity refcounted, so
        # rewraps over shared columns charge once; shape/dtype metadata
        # only — zero launches, no sync)
        from ..obs.resources import GLOBAL_LEDGER, ledger_enabled

        if ledger_enabled():
            GLOBAL_LEDGER.register_batch(self)

    @property
    def capacity(self) -> int:
        return int(self.row_mask.shape[0])

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    def column(self, i: int) -> Column:
        return self.columns[i]

    def column_by_name(self, name: str) -> Column:
        for f, c in zip(self.schema.fields, self.columns):
            if f.name == name:
                return c
        raise KeyError(name)

    def num_rows(self) -> int:
        """Live row count; syncs with device if unknown."""
        if self._num_rows is None:
            self._num_rows = int(np.asarray(self.row_mask).sum())
        return self._num_rows

    def device_nbytes(self) -> int:
        """Device bytes this tile holds (column data + validity planes +
        row mask) — the block store's device-pin accounting unit."""
        total = self.row_mask.size * 1
        for c in self.columns:
            data = getattr(c, "data", None)
            if data is not None:
                total += data.size * data.dtype.itemsize
            valid = getattr(c, "validity", None)
            if valid is not None:
                total += valid.size * 1
        return int(total)

    def with_columns(self, schema: StructType, columns: Sequence[Column],
                     row_mask=None, num_rows: int | None = None) -> "ColumnarBatch":
        return ColumnarBatch(
            schema, columns,
            self.row_mask if row_mask is None else row_mask,
            num_rows if row_mask is not None else (num_rows or self._num_rows))

    # --- construction ------------------------------------------------------
    @staticmethod
    def from_numpy(schema: StructType, arrays: Sequence[np.ndarray],
                   dictionaries: Sequence[StringDict | None] | None = None,
                   validities: Sequence[np.ndarray | None] | None = None,
                   capacity: int | None = None) -> "ColumnarBatch":
        import jax.numpy as jnp

        n = int(arrays[0].shape[0]) if arrays else 0
        cap = capacity or bucket_capacity(max(n, 1))
        cols = []
        dictionaries = dictionaries or [None] * len(arrays)
        validities = validities or [None] * len(arrays)
        for f, arr, d, v in zip(schema.fields, arrays, dictionaries, validities):
            dd = f.dataType.device_dtype
            pad = np.zeros(cap, dtype=dd)
            pad[:n] = np.asarray(arr, dtype=dd)[:cap]
            vv = None
            if v is not None:
                vm = np.zeros(cap, dtype=bool)
                vm[:n] = v[:cap]
                vv = jnp.asarray(vm)
            runs = None
            if v is None and not dict_encoded(f.dataType) \
                    and pad.dtype.kind == "i":
                # run/sortedness metadata while the plane is still host
                # numpy (columnar/encoding.py): licenses the sort-free
                # run-boundary aggregate downstream, zero device work;
                # skipped entirely under the decoded oracle
                from .encoding import column_runs, runs_harvest_enabled

                if runs_harvest_enabled():
                    runs = column_runs(pad, min(n, cap))
            cols.append(Column(f.dataType, jnp.asarray(pad), vv,
                               d if dict_encoded(f.dataType) else None,
                               runs=runs))
        mask = np.zeros(cap, dtype=bool)
        mask[:n] = True
        return ColumnarBatch(schema, cols, jnp.asarray(mask), num_rows=n)

    @staticmethod
    def empty(schema: StructType, capacity: int = 1 << 10) -> "ColumnarBatch":
        return ColumnarBatch.from_numpy(
            schema,
            [np.zeros(0, dtype=f.dataType.device_dtype) for f in schema.fields],
            dictionaries=[EMPTY_DICT if dict_encoded(f.dataType) else None
                          for f in schema.fields],
            capacity=capacity)

    # --- host materialization ---------------------------------------------
    def selection_indices(self) -> np.ndarray:
        mask = np.asarray(self.row_mask)
        return np.nonzero(mask)[0]

    def to_pydict(self) -> dict[str, np.ndarray]:
        sel = self.selection_indices()
        return {f.name: c.to_numpy(sel)
                for f, c in zip(self.schema.fields, self.columns)}

    def to_arrow(self, encoded: bool = False):
        """Arrow materialization. `encoded=True` keeps StringType columns
        DICTIONARY-ENCODED (int32 codes + the dictionary values, i.e.
        pa.DictionaryArray) instead of decoding every row — the cluster
        shuffle wire format: codes cross the IPC boundary and the reduce
        side rebuilds code columns without re-encoding (compressed
        execution; the decoded path remains the user-facing collect
        format and the encoding-off oracle)."""
        import pyarrow as pa

        sel = self.selection_indices()
        arrays = []
        for f, c in zip(self.schema.fields, self.columns):
            if encoded and isinstance(f.dataType, StringType):
                sd = c.dictionary or EMPTY_DICT
                codes = np.asarray(c.data)[sel]  # tpulint: ignore[host-sync]
                codes = np.clip(codes, 0, max(len(sd) - 1, 0)) \
                    .astype(np.int32)
                mask = None
                if c.validity is not None:
                    mask = ~np.asarray(c.validity)[sel]  # tpulint: ignore[host-sync]
                arrays.append(pa.DictionaryArray.from_arrays(
                    pa.array(codes, mask=mask),
                    pa.array(list(sd.values) or [""], type=pa.string())))
                continue
            vals = c.to_numpy(sel)
            at = to_arrow_type(f.dataType)
            if isinstance(f.dataType, NullType):
                arrays.append(pa.nulls(len(sel)))
            elif isinstance(f.dataType, DecimalType):
                # vals are floats; rebuild exact decimals from scaled ints
                raw = np.asarray(c.data)[sel]
                valid = (np.asarray(c.validity)[sel]
                         if c.validity is not None else None)
                import decimal as _d

                scale = f.dataType.scale
                py = [None if (valid is not None and not valid[i])
                      else _d.Decimal(int(raw[i])).scaleb(-scale)
                      for i in range(len(raw))]
                arrays.append(pa.array(py, type=at))
            elif isinstance(f.dataType, MapType):
                arrays.append(pa.array(
                    [None if v is None else list(v.items())
                     for v in vals], type=at))
            elif isinstance(f.dataType, (StringType, ArrayType, StructType)):
                arrays.append(pa.array(list(vals), type=at))
            else:
                mask = None
                if c.validity is not None:
                    mask = ~np.asarray(c.validity)[sel]
                if vals.dtype == object and (
                        str(at) == "date32[day]"
                        or str(at).startswith("timestamp")):
                    # host lists can carry None for masked slots (e.g. a
                    # date column read from ORC) — zero-fill, the mask
                    # already marks them null
                    vals = np.asarray([0 if v is None else v
                                       for v in vals])
                if f.dataType.device_dtype == np.dtype(np.int32) and str(at) == "date32[day]":
                    arrays.append(pa.array(np.asarray(vals, np.int32), type=at, mask=mask))
                elif str(at).startswith("timestamp"):
                    arrays.append(pa.array(np.asarray(vals, np.int64), type=at, mask=mask))
                else:
                    vals2 = np.asarray([v if v is not None else 0 for v in vals]) \
                        if vals.dtype == object else vals
                    arrays.append(pa.array(vals2, type=at, mask=mask))
        return pa.table(arrays, names=self.schema.names)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"ColumnarBatch(cap={self.capacity}, rows={self._num_rows}, "
                f"schema={self.schema.simple_string()})")
