"""Batch-level structural operations: concat, gather, compact, dict unification.

These are the host-orchestrated (but device-executed) glue between kernels —
the role the reference's UnsafeRow copy/serialize plumbing plays between
Tungsten operators.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..types import StringType, StructType, dict_encoded
from .batch import (Column, ColumnarBatch, EMPTY_DICT, StringDict,
                    bucket_capacity)


def _jnp():
    import jax.numpy as jnp

    return jnp


def _device_of(arr):
    try:
        devs = arr.devices()
        if len(devs) == 1:
            return next(iter(devs))
    except Exception:
        pass
    return None


def align_devices(arrays: list, target=None) -> list:
    """Move arrays to one shared device when they are committed to different
    ones (cross-partition combine of mesh-exchange outputs — the gather role
    of a fetch in the reference's shuffle read). No-op on one device.

    With `target`, every array lands on that device (so a batch's data,
    validity, and mask planes agree even when each list is single-device)."""
    devs = {d for a in arrays if a is not None
            for d in [_device_of(a)] if d is not None}
    if target is None:
        if len(devs) <= 1:
            return arrays
        target = sorted(devs, key=lambda d: d.id)[0]
    elif devs <= {target}:
        return arrays
    import jax

    return [a if a is None or _device_of(a) == target
            else jax.device_put(a, target) for a in arrays]


def batch_to_device(batch: ColumnarBatch, device) -> ColumnarBatch:
    """Commit every array of a batch to `device` (broadcast-side alignment
    for joins against mesh-resident partitions)."""
    import jax

    cols = [Column(c.dtype, jax.device_put(c.data, device),
                   None if c.validity is None
                   else jax.device_put(c.validity, device), c.dictionary)
            for c in batch.columns]
    return ColumnarBatch(batch.schema, cols,
                         jax.device_put(batch.row_mask, device),
                         num_rows=batch._num_rows)


def unify_string_columns(cols: Sequence[Column]) -> tuple[StringDict, list]:
    """Merge the dictionaries of string columns; returns (merged dict,
    per-column recoded code arrays). The dictionary union runs in the native
    C++ merge when built (utils/native.py)."""
    from .batch import merge_string_dicts

    jnp = _jnp()
    dicts = [c.dictionary or EMPTY_DICT for c in cols]
    # fast path: all columns share one dictionary object (common after a
    # scan of one partition) — no recode needed
    if all(d is dicts[0] for d in dicts):
        return dicts[0], [c.data for c in cols]
    merged, luts = merge_string_dicts(dicts)
    recoded = []
    for c, lut in zip(cols, luts):
        lut_d = jnp.asarray(lut)
        recoded.append(jnp.take(lut_d, jnp.clip(c.data, 0, len(lut) - 1)))
    return merged, recoded


def concat_batches(batches: Sequence[ColumnarBatch],
                   schema: StructType | None = None) -> ColumnarBatch:
    """Concatenate batches (same schema) into one larger-capacity batch.
    String columns get a unified dictionary."""
    jnp = _jnp()
    assert batches
    if len(batches) == 1:
        return batches[0]
    schema = schema or batches[0].schema
    total_cap = sum(b.capacity for b in batches)
    cap = bucket_capacity(total_cap)
    ncols = len(schema.fields)

    # one coherent device for every plane of the result (mesh partitions
    # live on different devices; validity fills are created on the default
    # one) — without a single target, a column's data and validity can end
    # up committed apart and the next jitted kernel rejects the pair
    all_devs = {d for b in batches
                for a in [b.row_mask] + [c.data for c in b.columns]
                for d in [_device_of(a)] if d is not None}
    # always pin a target: even a single-device partition needs its
    # uncommitted validity fills pulled onto that device, not the default one
    target = sorted(all_devs, key=lambda d: d.id)[0] if all_devs else None

    cols: list[Column] = []
    for i, f in enumerate(schema.fields):
        parts = [b.columns[i] for b in batches]
        if dict_encoded(f.dataType):
            sd, datas = unify_string_columns(parts)
        else:
            sd = None
            datas = [p.data for p in parts]
        datas = align_devices(datas, target)
        data = jnp.concatenate(datas)
        if data.shape[0] < cap:
            data = jnp.concatenate(
                [data, jnp.zeros(cap - data.shape[0], dtype=data.dtype)])
        any_valid = any(p.validity is not None for p in parts)
        validity = None
        if any_valid:
            vs = [p.validity if p.validity is not None
                  else jnp.ones(p.data.shape[0], dtype=bool) for p in parts]
            validity = jnp.concatenate(align_devices(vs, target))
            if validity.shape[0] < cap:
                validity = jnp.concatenate(
                    [validity, jnp.zeros(cap - validity.shape[0], dtype=bool)])
        cols.append(Column(f.dataType, data, validity, sd))

    masks = align_devices([b.row_mask for b in batches], target)
    mask = jnp.concatenate(masks)
    if mask.shape[0] < cap:
        mask = jnp.concatenate([mask, jnp.zeros(cap - mask.shape[0], dtype=bool)])
    nrows = None
    if all(b._num_rows is not None for b in batches):
        nrows = sum(b._num_rows for b in batches)
    return ColumnarBatch(schema, cols, mask, num_rows=nrows)


def gather_batch(batch: ColumnarBatch, indices, out_mask,
                 schema: StructType | None = None,
                 extra_invalid=None) -> ColumnarBatch:
    """Row-gather a batch by device `indices` (int32[out_cap]) with live-row
    `out_mask`. `extra_invalid`: bool[out_cap] marking rows whose gathered
    values must read as NULL (outer-join null extension)."""
    jnp = _jnp()
    schema = schema or batch.schema
    cols = []
    for f, c in zip(schema.fields, batch.columns):
        data = jnp.take(c.data, indices)
        validity = None if c.validity is None else jnp.take(c.validity, indices)
        if extra_invalid is not None:
            base = validity if validity is not None \
                else jnp.ones(indices.shape[0], dtype=bool)
            validity = base & ~extra_invalid
        cols.append(Column(f.dataType, data, validity, c.dictionary))
    return ColumnarBatch(schema, cols, out_mask, num_rows=None)


def compact_batch(batch: ColumnarBatch, target_capacity: int | None = None
                  ) -> ColumnarBatch:
    """Drop dead rows: permute live rows to the front and slice to a smaller
    capacity bucket. Host-syncs the live count."""
    jnp = _jnp()
    n = batch.num_rows()
    cap = target_capacity or bucket_capacity(max(n, 1))
    if cap >= batch.capacity:
        return batch
    perm = jnp.argsort(~batch.row_mask, stable=True)[:cap].astype(jnp.int32)
    cols = []
    for c in batch.columns:
        data = jnp.take(c.data, perm)
        validity = None if c.validity is None else jnp.take(c.validity, perm)
        cols.append(Column(c.dtype, data, validity, c.dictionary))
    mask = jnp.arange(cap) < n
    return ColumnarBatch(batch.schema, cols, mask, num_rows=n)


def slice_to_numpy(batch: ColumnarBatch) -> dict:
    """Pull a batch to host as raw representation (codes stay codes).
    Returns {"schema", "columns": [(data, validity, dict)], "mask"}."""
    cols = []
    for c in batch.columns:
        cols.append((np.asarray(c.data),
                     None if c.validity is None else np.asarray(c.validity),
                     c.dictionary))
    return {"schema": batch.schema, "columns": cols,
            "mask": np.asarray(batch.row_mask)}
