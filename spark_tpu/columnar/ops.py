"""Batch-level structural operations: concat, gather, compact, dict unification.

These are the host-orchestrated (but device-executed) glue between kernels —
the role the reference's UnsafeRow copy/serialize plumbing plays between
Tungsten operators.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ..types import StringType, StructType
from .batch import Column, ColumnarBatch, StringDict, bucket_capacity


def _jnp():
    import jax.numpy as jnp

    return jnp


def unify_string_columns(cols: Sequence[Column]) -> tuple[StringDict, list]:
    """Merge the dictionaries of string columns; returns (merged dict,
    per-column recoded code arrays). The dictionary union runs in the native
    C++ merge when built (utils/native.py)."""
    from .batch import merge_string_dicts

    jnp = _jnp()
    dicts = [c.dictionary or StringDict([""]) for c in cols]
    # fast path: all columns share one dictionary object (common after a
    # scan of one partition) — no recode needed
    if all(d is dicts[0] for d in dicts):
        return dicts[0], [c.data for c in cols]
    merged, luts = merge_string_dicts(dicts)
    recoded = []
    for c, lut in zip(cols, luts):
        lut_d = jnp.asarray(lut)
        recoded.append(jnp.take(lut_d, jnp.clip(c.data, 0, len(lut) - 1)))
    return merged, recoded


def concat_batches(batches: Sequence[ColumnarBatch],
                   schema: StructType | None = None) -> ColumnarBatch:
    """Concatenate batches (same schema) into one larger-capacity batch.
    String columns get a unified dictionary."""
    jnp = _jnp()
    assert batches
    if len(batches) == 1:
        return batches[0]
    schema = schema or batches[0].schema
    total_cap = sum(b.capacity for b in batches)
    cap = bucket_capacity(total_cap)
    ncols = len(schema.fields)

    cols: list[Column] = []
    for i, f in enumerate(schema.fields):
        parts = [b.columns[i] for b in batches]
        if isinstance(f.dataType, StringType):
            sd, datas = unify_string_columns(parts)
        else:
            sd = None
            datas = [p.data for p in parts]
        data = jnp.concatenate(datas)
        if data.shape[0] < cap:
            data = jnp.concatenate(
                [data, jnp.zeros(cap - data.shape[0], dtype=data.dtype)])
        any_valid = any(p.validity is not None for p in parts)
        validity = None
        if any_valid:
            vs = [p.validity if p.validity is not None
                  else jnp.ones(p.data.shape[0], dtype=bool) for p in parts]
            validity = jnp.concatenate(vs)
            if validity.shape[0] < cap:
                validity = jnp.concatenate(
                    [validity, jnp.zeros(cap - validity.shape[0], dtype=bool)])
        cols.append(Column(f.dataType, data, validity, sd))

    masks = [b.row_mask for b in batches]
    mask = jnp.concatenate(masks)
    if mask.shape[0] < cap:
        mask = jnp.concatenate([mask, jnp.zeros(cap - mask.shape[0], dtype=bool)])
    nrows = None
    if all(b._num_rows is not None for b in batches):
        nrows = sum(b._num_rows for b in batches)
    return ColumnarBatch(schema, cols, mask, num_rows=nrows)


def gather_batch(batch: ColumnarBatch, indices, out_mask,
                 schema: StructType | None = None,
                 extra_invalid=None) -> ColumnarBatch:
    """Row-gather a batch by device `indices` (int32[out_cap]) with live-row
    `out_mask`. `extra_invalid`: bool[out_cap] marking rows whose gathered
    values must read as NULL (outer-join null extension)."""
    jnp = _jnp()
    schema = schema or batch.schema
    cols = []
    for f, c in zip(schema.fields, batch.columns):
        data = jnp.take(c.data, indices)
        validity = None if c.validity is None else jnp.take(c.validity, indices)
        if extra_invalid is not None:
            base = validity if validity is not None \
                else jnp.ones(indices.shape[0], dtype=bool)
            validity = base & ~extra_invalid
        cols.append(Column(f.dataType, data, validity, c.dictionary))
    return ColumnarBatch(schema, cols, out_mask, num_rows=None)


def compact_batch(batch: ColumnarBatch, target_capacity: int | None = None
                  ) -> ColumnarBatch:
    """Drop dead rows: permute live rows to the front and slice to a smaller
    capacity bucket. Host-syncs the live count."""
    jnp = _jnp()
    n = batch.num_rows()
    cap = target_capacity or bucket_capacity(max(n, 1))
    if cap >= batch.capacity:
        return batch
    perm = jnp.argsort(~batch.row_mask, stable=True)[:cap].astype(jnp.int32)
    cols = []
    for c in batch.columns:
        data = jnp.take(c.data, perm)
        validity = None if c.validity is None else jnp.take(c.validity, perm)
        cols.append(Column(c.dtype, data, validity, c.dictionary))
    mask = jnp.arange(cap) < n
    return ColumnarBatch(batch.schema, cols, mask, num_rows=n)


def slice_to_numpy(batch: ColumnarBatch) -> dict:
    """Pull a batch to host as raw representation (codes stay codes).
    Returns {"schema", "columns": [(data, validity, dict)], "mask"}."""
    cols = []
    for c in batch.columns:
        cols.append((np.asarray(c.data),
                     None if c.validity is None else np.asarray(c.validity),
                     c.dictionary))
    return {"schema": batch.schema, "columns": cols,
            "mask": np.asarray(batch.row_mask)}
