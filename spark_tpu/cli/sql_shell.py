"""Interactive SQL shell.

Role of the reference's bin/spark-sql (SparkSQLCLIDriver,
sql/hive-thriftserver/.../SparkSQLCLIDriver.scala): a line REPL over a
session — multi-line statements terminated by ';', EXPLAIN/SET/SHOW pass
straight through the SQL surface, table output rendered fixed-width.

Usage: python -m spark_tpu.cli.sql_shell [--conf K=V ...] [-e "SQL"]
       [-f script.sql]
"""

from __future__ import annotations

import argparse
import sys
import time


def render_table(table, max_rows: int = 100) -> str:
    cols = table.column_names
    data = [c.to_pylist() for c in table.columns]
    n = min(table.num_rows, max_rows)
    rows = [[("NULL" if v is None else str(v)) for v in
             (data[c][i] for c in range(len(cols)))]
            for i in range(n)]
    widths = [max(len(cols[c]), *(len(r[c]) for r in rows)) if rows
              else len(cols[c]) for c in range(len(cols))]
    sep = "+" + "+".join("-" * (w + 2) for w in widths) + "+"
    out = [sep,
           "|" + "|".join(f" {cols[c]:<{widths[c]}} "
                          for c in range(len(cols))) + "|",
           sep]
    for r in rows:
        out.append("|" + "|".join(f" {r[c]:<{widths[c]}} "
                                  for c in range(len(cols))) + "|")
    out.append(sep)
    if table.num_rows > max_rows:
        out.append(f"(showing {max_rows} of {table.num_rows} rows)")
    return "\n".join(out)


def run_statement(spark, stmt: str, out=sys.stdout) -> None:
    t0 = time.perf_counter()
    df = spark.sql(stmt)
    if not hasattr(df, "toArrow"):  # command with no result set
        print("OK", file=out)
        return
    table = df.toArrow()
    dt = time.perf_counter() - t0
    print(render_table(table), file=out)
    print(f"{table.num_rows} row(s) in {dt:.3f}s", file=out)


def main(argv: list[str] | None = None) -> int:
    from .submit import parse_conf

    p = argparse.ArgumentParser(prog="sparktpu-sql")
    p.add_argument("--conf", action="append", default=[], metavar="K=V")
    p.add_argument("-e", dest="query", default=None,
                   help="run a single statement and exit")
    p.add_argument("-f", dest="file", default=None,
                   help="run statements from a file and exit")
    args = p.parse_args(argv)

    from ..api.session import TpuSession

    spark = TpuSession("sql-shell", parse_conf(args.conf))
    try:
        if args.query is not None:
            run_statement(spark, args.query)
            return 0
        if args.file is not None:
            with open(args.file) as f:
                text = f.read()
            for stmt in [s.strip() for s in text.split(";") if s.strip()]:
                run_statement(spark, stmt)
            return 0

        print("sparktpu-sql shell — statements end with ';', "
              "exit with 'quit;' or Ctrl-D")
        buf: list[str] = []
        while True:
            try:
                line = input("sql> " if not buf else "   > ")
            except EOFError:
                print()
                break
            buf.append(line)
            if line.rstrip().endswith(";"):
                stmt = "\n".join(buf).rstrip().rstrip(";").strip()
                buf = []
                if stmt.lower() in ("quit", "exit"):
                    break
                if not stmt:
                    continue
                try:
                    run_statement(spark, stmt)
                except Exception as e:  # shell survives bad statements
                    print(f"Error: {e}", file=sys.stderr)
        return 0
    finally:
        spark.stop()


if __name__ == "__main__":
    sys.exit(main())
