"""CLI tooling (role of the reference's bin/ + launcher/ layer,
SURVEY.md §1 layer 14: spark-submit, spark-shell, spark-sql)."""
