"""Interactive Python shell with a prepared session.

Role of the reference's bin/pyspark (python/pyspark/shell.py): drops into
an interactive interpreter with `spark` (session) and `F` (functions)
bound, banner included.

Usage: python -m spark_tpu.cli.shell [--conf K=V ...]
"""

from __future__ import annotations

import argparse
import code
import sys


BANNER = r"""
   ____              __    ______
  / __/__  ___ _____/ /__ /_  __/__  __ __
 _\ \/ _ \/ _ `/ __/  '_/  / / / _ \/ // /
/___/ .__/\_,_/_/ /_/\_\  /_/ / .__/\_,_/
   /_/                       /_/

TPU-native analytics engine — `spark` session ready, functions as `F`.
"""


def main(argv: list[str] | None = None) -> int:
    from .submit import parse_conf

    p = argparse.ArgumentParser(prog="sparktpu-shell")
    p.add_argument("--conf", action="append", default=[], metavar="K=V")
    args = p.parse_args(argv)

    from .. import api
    from ..api.session import TpuSession
    import spark_tpu.api.functions as F

    spark = TpuSession("shell", parse_conf(args.conf))
    ns = {"spark": spark, "F": F, "functions": F}
    try:
        code.interact(banner=BANNER, local=ns)
    finally:
        spark.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
