"""Application launcher.

Role of the reference's SparkSubmit (core/deploy/SparkSubmit.scala:1096 main
→ runMain → user main()) and the launcher process API (launcher/): parses
--conf/--name/--master style arguments, builds the session configuration,
exposes it to the app via environment, and runs the user script in-process
with a prepared `spark` session available through
`spark_tpu.cli.submit.get_session()` (or the app builds its own — the conf
is inherited via SPARKTPU_CONF_JSON, the SparkSubmitArguments precedence
model: CLI > conf file > defaults).

Usage: python -m spark_tpu.cli.submit [options] <app.py> [app args...]
"""

from __future__ import annotations

import argparse
import json
import os
import runpy
import sys

_SESSION = None


def get_session():
    """The session prepared by the launcher (lazily created so plain
    `python app.py` also works)."""
    global _SESSION
    if _SESSION is None:
        from ..api.session import TpuSession

        conf = json.loads(os.environ.get("SPARKTPU_CONF_JSON", "{}"))
        _SESSION = TpuSession(os.environ.get("SPARKTPU_APP_NAME", "app"),
                              conf)
    return _SESSION


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sparktpu-submit",
        description="Run an application against the TPU engine")
    p.add_argument("--name", default="app", help="application name")
    p.add_argument("--conf", action="append", default=[],
                   metavar="K=V", help="session config entry (repeatable)")
    p.add_argument("--properties-file", default=None,
                   help="newline-delimited k=v defaults (lowest precedence)")
    p.add_argument("--master", default="local",
                   help="local | local-cluster[N] (process workers) | "
                        "grpc://host:port (standalone master daemon)")
    p.add_argument("--num-executors", type=int, default=None,
                   help="executors to request from a standalone master")
    p.add_argument("app", help="python application file")
    p.add_argument("app_args", nargs=argparse.REMAINDER,
                   help="arguments passed to the application")
    return p


def parse_conf(pairs: list[str]) -> dict:
    out = {}
    for kv in pairs:
        if "=" not in kv:
            raise SystemExit(f"--conf expects K=V, got {kv!r}")
        k, v = kv.split("=", 1)
        out[k.strip()] = v.strip()
    return out


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    conf: dict = {}
    if args.properties_file:
        with open(args.properties_file) as f:
            for line in f:
                line = line.strip()
                if line and not line.startswith("#") and "=" in line:
                    k, v = line.split("=", 1)
                    conf[k.strip()] = v.strip()
    conf.update(parse_conf(args.conf))
    if args.master.startswith("local-cluster"):
        conf.setdefault("spark.tpu.cluster.enabled", "true")
        inner = args.master[len("local-cluster"):].strip("[]")
        if inner:
            conf.setdefault("spark.tpu.cluster.workers", inner.split(",")[0])
    elif args.master.startswith(("grpc://", "spark://")):
        conf.setdefault("spark.tpu.master", args.master)
        if args.num_executors:
            conf.setdefault("spark.executor.instances",
                            str(args.num_executors))

    os.environ["SPARKTPU_CONF_JSON"] = json.dumps(conf)
    os.environ["SPARKTPU_APP_NAME"] = args.name
    sys.argv = [args.app] + list(args.app_args)
    runpy.run_path(args.app, run_name="__main__")
    if _SESSION is not None:
        _SESSION.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
