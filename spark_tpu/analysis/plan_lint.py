"""Plan/trace-level static analyzer: launches-per-batch, fusion boundaries,
recompile and overflow hazards.

Role of the reference's EXPLAIN CODEGEN / debugCodegen surface
(sqlx/execution/debug/package.scala — which operators got whole-stage
codegen and why the rest fell back) extended with the numbers that matter
on a TPU: for every stage of the optimized physical plan, how many XLA
dispatches one warm execution performs, per batch, and why.

The analyzer performs an ABSTRACT interpretation of the physical plan:

  * a layout model — partitions × fixed-capacity batches — propagated from
    the scans (local relations expose exact row counts; capacity-bucket
    math mirrors columnar/batch.bucket_capacity);
  * an identity model — whether a batch's device arrays are the SAME
    objects across repeated executions (device-cached scans) or fresh per
    run: the memoized device-scalar reads (utils/
    device_memo.memo_device_scalars) launch their probe kernel only on fresh arrays;
  * a value model — for columns that trace to local arrow data through
    mask-only operators and literal predicates, exact host statistics
    (span / uniqueness / match cardinality) that decide the value-dependent
    branches: dense-scatter vs sorted-segment aggregation, dense vs sorted
    join build, probe-capacity retries.

Where a branch cannot be decided statically the report degrades honestly:
``exact`` flips to False and the reason is listed. On the fusion
differential suite (single-partition local relations, broadcast joins)
predictions are EXACT and tests/test_plan_analysis.py asserts them against
the measured KernelCache launch counters, fusion on and off.

Kernel-kind legend (KernelCache key tags): pipeline, fused_agg, uagg/dagg/
gagg, ragg (sorted-run RLE segment reduce — no grouping sort),
krange3 (dense-range scalar probe), fused_limit, limit, sort,
join_build/join_probe, fused_probe, djoin_build/djoin_probe,
fused_djoin_probe, shuffle_pids/shuffle_hash/shuffle_rr/shuffle_range,
fused_shuffle (exchange map side fused with its pipeline), mesh_stage
(whole shuffle stage as ONE shard_map dispatch — pipeline + partition ids
+ ICI all-to-all; quota retries re-dispatch), sample.
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..columnar.batch import bucket_capacity
from ..config import (
    ADAPTIVE_ENABLED, ADAPTIVE_READMISSION, ADAPTIVE_RUNTIME_FILTER,
    ADVISORY_PARTITION_BYTES, AGG_BLOCK_ROWS, BATCH_CAPACITY,
    BLOOM_JOIN_FILTER, COALESCE_PARTITIONS_ENABLED, ENCODING_ENABLED,
    FUSION_DENSE_KEYS, FUSION_ENABLED, FUSION_EXCHANGE, FUSION_MESH,
    FUSION_MIN_ROWS, MESH_ENABLED, MINMAX_JOIN_FILTER, SQLConf,
)
from ..expr.expressions import (
    Alias, AttributeReference, EqualTo, GreaterThan, GreaterThanOrEqual, In,
    IsNotNull, LessThan, LessThanOrEqual, Literal, NotEqualTo,
)
from ..types import DateType, IntegralType, StringType, dict_encoded

__all__ = ["AnalysisReport", "analyze_plan"]

_EMPTY_CAP = 1 << 10   # ColumnarBatch.empty capacity
_DENSE_AGG_LIMIT = 1 << 23
_DENSE_JOIN_LIMIT = 1 << 23
_TRACE_MAX_ROWS = 1 << 22  # don't drag huge host columns into the analyzer


# ---------------------------------------------------------------------------
# models
# ---------------------------------------------------------------------------

@dataclass
class _Batch:
    rows: Optional[int]      # live-row upper bound (None = unknown)
    cap: Optional[int]       # device tile capacity (None = unknown)
    stable: bool             # same device arrays across executions
    # shuffle-built tiles carry map-side column stats: the dense-range
    # memo is seeded at build/ingest time, so the krange3 probe never
    # fires even though the arrays are fresh every run. True = every
    # integral column is seeded (the pre-annotation legacy model);
    # a frozenset holds the expr ids the exchange actually accumulates
    # (ShuffleExchangeExec.stat_cols — plan-reachable dense candidates)
    seeded: "bool | frozenset" = False
    # RunInfo-bearing tile: host-ingested (columnar/arrow ingest or
    # shuffle rebuild — True: every column carries run metadata) or a
    # pipeline output whose PASS-THROUGH columns inherited the input
    # tile's RunInfo (a frozenset of the expr ids that kept it; mask-only
    # filters never reorder rows, so ingest sortedness survives them).
    # Any other fresh kernel output drops it (Column.with_data).
    ingest: "bool | frozenset" = False

    def probe_free_for(self, expr_id) -> bool:
        """No krange3 dispatch when THIS column's range is consulted:
        stable arrays hit the memo from a prior run; seeded tiles were
        pre-populated for that column at build time."""
        if self.stable or self.seeded is True:
            return True
        return isinstance(self.seeded, frozenset) and expr_id in self.seeded

    def runs_for(self, expr_id) -> bool:
        """Column carries ingest RunInfo on this tile (the sorted-run
        ragg trigger): whole-tile ingest metadata, or run metadata a
        pass-through pipeline output inherited."""
        if self.ingest is True:
            return True
        return isinstance(self.ingest, frozenset) and expr_id in self.ingest


@dataclass
class _Trace:
    """Host value model: per-attribute raw columns traced to local data."""
    cols: dict            # expr_id -> (np values, np validity | None)
    live: np.ndarray      # row mask after the traced filter chain
    consecutive: bool = True   # rows still slice into batches in order
    # encoding model: expr_id -> tuple of dictionary values in DICT ORDER
    # for columns whose runtime dictionary covers more than the traced
    # rows (join gathers keep the FULL build dictionary; agg/shuffle
    # outputs keep merged input dictionaries). Absent entries derive the
    # domain from the value slice itself — the appearance-order distinct
    # pyarrow's dictionary_encode produces at ingest — but only while
    # `dict_derivable` holds (row subsets break the derivation: the
    # runtime dictionary still covers the DROPPED rows' values)
    dict_domains: dict = field(default_factory=dict)
    dict_derivable: bool = True

    def stats(self, expr_id):
        """(values_under_live_and_valid,) or None."""
        ent = self.cols.get(expr_id)
        if ent is None:
            return None
        vals, valid = ent
        m = self.live if valid is None else (self.live & valid)
        return vals[m]

    def compacted(self) -> "_Trace":
        """Live rows only (the shape of a shuffle/aggregate OUTPUT, where
        masked rows were dropped on the way through the host buffers)."""
        m = self.live
        cols = {k: (v[m], None if val is None else val[m])
                for k, (v, val) in self.cols.items()}
        return _Trace(cols, np.ones(int(m.sum()), bool), self.consecutive,
                      dict(self.dict_domains), False)

    def select(self, sel: np.ndarray, consecutive: bool) -> "_Trace":
        """Row subset (over an already-compacted trace)."""
        cols = {k: (v[sel], None if val is None else val[sel])
                for k, (v, val) in self.cols.items()}
        return _Trace(cols, np.ones(len(sel), bool), consecutive,
                      dict(self.dict_domains), False)


@dataclass
class _Flow:
    parts: list                       # list[list[_Batch]]
    trace: Optional[_Trace] = None
    counted: bool = True              # batch counts are known exactly
    # per-partition traces for multi-partition flows (post-exchange /
    # post-aggregate); when None, `trace` describes the whole flow (the
    # single-partition case every traced scan starts from)
    ptraces: Optional[list] = None

    @property
    def total_batches(self):
        return sum(len(p) for p in self.parts)

    def part_trace(self, i: int) -> Optional[_Trace]:
        if self.ptraces is not None:
            return self.ptraces[i] if i < len(self.ptraces) else None
        return self.trace

    def all_part_traces(self) -> Optional[list]:
        """Per-partition traces covering EVERY partition, or None."""
        if self.ptraces is not None:
            if len(self.ptraces) == len(self.parts) and \
                    all(t is not None for t in self.ptraces):
                return list(self.ptraces)
            return None
        if self.trace is not None and len(self.parts) == 1:
            return [self.trace]
        return None


# ---------------------------------------------------------------------------
# host mirror of the device hash partitioner (ops/hashing.py)
# ---------------------------------------------------------------------------

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)
_GOLDEN = np.uint64(0x9E3779B97F4A7C15)


def _np_mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer over uint64 lanes — bit-exact numpy mirror of
    ops/hashing.mix64 (uint64 arithmetic wraps modulo 2^64 on both; the
    errstate silences the 0-d scalar path's overflow warning — wrapping
    IS the hash)."""
    with np.errstate(over="ignore"):
        x = x ^ (x >> np.uint64(30))
        x = x * _M1
        x = x ^ (x >> np.uint64(27))
        x = x * _M2
        x = x ^ (x >> np.uint64(31))
    return x


def _np_eq_lane(vals: np.ndarray, valid) -> np.ndarray:
    """Host mirror of Column.eq_keys as a uint64 hash lane: numeric
    columns cast to int64; STRING columns map each value to its stable
    dictionary hash (the same StringDict.hashes the runtime lut holds,
    native or blake2b — codes → value hashes is exactly what the padded
    dict-hash aux table computes inside the trace). Null rows get lane 0:
    hash_columns replaces them with the null tag regardless."""
    vals = np.asarray(vals)
    if vals.dtype != object:
        return vals.astype(np.int64).view(np.uint64)
    from ..columnar.batch import StringDict

    if not len(vals):
        return np.zeros(0, np.uint64)
    # vectorized: hash each distinct value once, scatter by inverse index
    # (traced string columns hold only str — nulls are "" placeholders).
    # Invalid rows' lanes are irrelevant: the hash mirror replaces them
    # with the null tag.
    uniq, inv = np.unique(vals, return_inverse=True)
    hashes = StringDict([str(u) for u in uniq]).hashes
    return hashes[inv].view(np.uint64)


def _np_hash_pids(cols: list, num_out: int, seed: int = 42) -> np.ndarray:
    """Partition ids of traced key columns — the host-side hash of traced
    keys that lets multi-stage shuffle plans predict exactly. Mirrors
    hash_columns + partition_ids: eq-key lanes (int64 casts; string
    values via their dictionary hashes), splitmix64 lanes, null tags,
    31x + golden combine, nonlinear seed fold, pmod."""
    h = None
    for i, (vals, valid) in enumerate(cols):
        k = _np_mix64(_np_eq_lane(vals, valid))
        if valid is not None:
            null_tag = _np_mix64(
                np.asarray(0x6E756C6C + i, np.int64).view(np.uint64))
            k = np.where(valid, k, null_tag)
        if h is None:
            h = k
        else:
            with np.errstate(over="ignore"):
                h = _np_mix64(h * np.uint64(31) + k + _GOLDEN)
    seed_u = _np_mix64(np.asarray(seed, np.int64).view(np.uint64))
    final = _np_mix64(h ^ seed_u).view(np.int64)
    return (final % np.int64(num_out)).astype(np.int32)


@dataclass
class AnalysisReport:
    stages: list = field(default_factory=list)
    predicted_launches: dict = field(default_factory=dict)
    exact: bool = True
    # compile-tier decision (physical/whole_query.choose_tier): which of
    # whole / stage / operator ran, and the fallback reason when the
    # cost model declined a higher tier
    tier: Optional[dict] = None
    inexact_reasons: list = field(default_factory=list)
    fusion_boundaries: list = field(default_factory=list)
    recompile_hazards: list = field(default_factory=list)
    overflow_risks: list = field(default_factory=list)
    host_sync_notes: list = field(default_factory=list)
    # memory model (mirrors the layout model the launch counts ride on):
    # per-stage predicted HBM in stages[i]["hbm_bytes"]; the query peak
    # is the SUM of stage outputs — an upper bound on simultaneously
    # resident engine tiles (operators materialize whole output
    # partition lists; GC frees consumed children at uncertain points)
    predicted_peak_hbm: Optional[int] = None
    memory_exact: bool = True
    memory_notes: list = field(default_factory=list)

    @property
    def total(self) -> int:
        return sum(self.predicted_launches.values())

    def to_dict(self) -> dict:
        return {
            "stages": list(self.stages),
            "tier": dict(self.tier) if self.tier else None,
            "predicted_launches": dict(self.predicted_launches),
            "predicted_total": self.total,
            "exact": self.exact,
            "inexact_reasons": list(self.inexact_reasons),
            "fusion_boundaries": list(self.fusion_boundaries),
            "recompile_hazards": list(self.recompile_hazards),
            "overflow_risks": list(self.overflow_risks),
            "host_sync_notes": list(self.host_sync_notes),
            "predicted_peak_hbm": self.predicted_peak_hbm,
            "memory_exact": self.memory_exact,
            "memory_notes": list(self.memory_notes),
        }

    def render(self) -> str:
        out = ["== Plan Analysis =="]
        if self.tier:
            out.append(f"-- compilation tier: {self.tier.get('tier')} — "
                       f"{self.tier.get('reason', '')} --")
        out.append("-- stages (kernel launches per warm execution) --")
        for s in self.stages:
            kinds = ", ".join(f"{k}:{v}" for k, v in sorted(
                s["kinds"].items())) or "none"
            lpb = s.get("launches_per_batch")
            lpb_s = f", {lpb:g}/batch" if lpb is not None else ""
            out.append(f"  {s['op']}: {{{kinds}}} over "
                       f"{s['batches']} batch(es){lpb_s}")
            for n in s.get("notes", ()):
                out.append(f"      - {n}")
        pred = ", ".join(f"{k}:{v}" for k, v in sorted(
            self.predicted_launches.items()))
        tag = "EXACT" if self.exact else "approximate"
        out.append(f"-- predicted launches ({tag}): total {self.total} "
                   f"{{{pred}}} --")
        for r in self.inexact_reasons:
            out.append(f"  ? {r}")
        if self.predicted_peak_hbm is not None:
            mtag = "model exact" if self.memory_exact \
                else "model approximate"
            out.append(f"-- predicted peak HBM ({mtag}): "
                       f"~{self.predicted_peak_hbm / (1 << 20):.1f} MiB "
                       "resident engine tiles --")
            staged = sorted((s for s in self.stages
                             if s.get("hbm_bytes")),
                            key=lambda s: -s["hbm_bytes"])[:5]
            for s in staged:
                out.append(f"  {s['op']:<22} "
                           f"~{s['hbm_bytes'] / (1 << 20):.2f} MiB")
            for n in self.memory_notes:
                out.append(f"  ? {n}")
        if self.fusion_boundaries:
            out.append("-- fusion boundaries --")
            out.extend(f"  * {b}" for b in self.fusion_boundaries)
        if self.recompile_hazards:
            out.append("-- recompile hazards --")
            out.extend(f"  ! {h}" for h in self.recompile_hazards)
        if self.overflow_risks:
            out.append("-- dtype overflow risks --")
            out.extend(f"  ! {h}" for h in self.overflow_risks)
        if self.host_sync_notes:
            out.append("-- host-sync notes --")
            out.extend(f"  . {h}" for h in self.host_sync_notes)
        return "\n".join(out)


# ---------------------------------------------------------------------------
# host predicate evaluation over traced columns
# ---------------------------------------------------------------------------

_CMP = {EqualTo: "==", NotEqualTo: "!=", GreaterThan: ">",
        GreaterThanOrEqual: ">=", LessThan: "<", LessThanOrEqual: "<="}


def _eval_filter(e, trace: _Trace):
    """Boolean mask where the predicate holds (nulls → False), or None when
    the predicate is outside the traced language."""
    if isinstance(e, IsNotNull) and isinstance(e.child, AttributeReference):
        ent = trace.cols.get(e.child.expr_id)
        if ent is None:
            return None
        vals, valid = ent
        return np.ones(len(vals), bool) if valid is None else valid.copy()
    if type(e) in _CMP:
        l, r = e.left, e.right
        if isinstance(l, Literal) and isinstance(r, AttributeReference):
            l, r = r, l
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<=",
                  "==": "==", "!=": "!="}[_CMP[type(e)]]
        else:
            op = _CMP[type(e)]
        if not (isinstance(l, AttributeReference) and isinstance(r, Literal)
                and r.value is not None):
            return None
        ent = trace.cols.get(l.expr_id)
        if ent is None:
            return None
        vals, valid = ent
        fns = {"==": np.equal, "!=": np.not_equal, ">": np.greater,
               ">=": np.greater_equal, "<": np.less, "<=": np.less_equal}
        try:
            with np.errstate(all="ignore"):
                m = fns[op](vals, r.value)
        except Exception:
            return None
        if valid is not None:
            m = m & valid
        return np.asarray(m, bool)
    if isinstance(e, In) and isinstance(e.child, AttributeReference) \
            and all(isinstance(i, Literal) for i in e.items):
        ent = trace.cols.get(e.child.expr_id)
        if ent is None:
            return None
        vals, valid = ent
        m = np.isin(vals, [i.value for i in e.items if i.value is not None])
        if valid is not None:
            m = m & valid
        return m
    return None


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------

class _Analyzer:
    def __init__(self, conf: SQLConf, cluster: bool = False):
        self.conf = conf
        self._cluster = cluster
        self.report = AnalysisReport()
        self.predicted = Counter()
        self._fusion_on = bool(conf.get(FUSION_ENABLED))
        self._fusion_exchange = bool(conf.get(FUSION_EXCHANGE))
        self._fusion_mesh = bool(conf.get(FUSION_MESH))
        self._min_rows = int(conf.get(FUSION_MIN_ROWS))
        self._dense_keys = bool(conf.get(FUSION_DENSE_KEYS))
        self._encoding = bool(conf.get(ENCODING_ENABLED))
        self._tile = int(conf.get(BATCH_CAPACITY))
        # memory model state: the stage entry each node produced (so the
        # OUTPUT flow recorded after the handler returns can annotate it)
        self._stage_by_node: dict[int, dict] = {}
        self._hbm_total = 0
        self._hbm_any = False
        # persistent-cache mirror state (exec/persist_cache.py)
        self._plan_root = None
        self._persist_seed = None
        self._persist_seed_done = False

    # -- bookkeeping -------------------------------------------------------
    def _approx(self, reason: str):
        self.report.exact = False
        if reason not in self.report.inexact_reasons:
            self.report.inexact_reasons.append(reason)

    def _hazard(self, text: str):
        if text not in self.report.recompile_hazards:
            self.report.recompile_hazards.append(text)

    def _sync(self, text: str):
        if text not in self.report.host_sync_notes:
            self.report.host_sync_notes.append(text)

    def _stage(self, node, kinds: Counter, batches, notes=()):
        self.predicted.update(kinds)
        lpb = None
        if isinstance(batches, int) and batches:
            per_batch = sum(v for k, v in kinds.items()
                            if k in ("pipeline", "fused_agg", "fused_limit",
                                     "join_probe", "fused_probe",
                                     "djoin_probe", "fused_djoin_probe",
                                     "shuffle_pids", "shuffle_hash",
                                     "shuffle_rr", "shuffle_range",
                                     "fused_shuffle", "sample"))
            if per_batch and batches:
                lpb = round(per_batch / batches, 2)
        detail = node.simple_string() if hasattr(node, "simple_string") \
            else type(node).__name__
        ent = {
            "op": type(node).__name__,
            "detail": detail[:120],
            "kinds": dict(kinds),
            "batches": batches,
            "launches_per_batch": lpb,
            "notes": list(notes),
        }
        self.report.stages.append(ent)
        self._stage_by_node[id(node)] = ent

    # -- persistent-cache mirrors (exec/persist_cache.py) -------------------
    def _persist_seed_record(self):
        """The warm-start manifest record for the analyzed plan's full
        fingerprint (None when spark.tpu.cache.dir is unset or no prior
        same-fingerprint run recorded outcomes) — the SAME lookup
        QueryExecution performs, so the capacity mirrors below predict a
        seeded first attempt exactly. Memoized per analysis."""
        if self._persist_seed_done:
            return self._persist_seed
        self._persist_seed_done = True
        try:
            from ..exec.persist_cache import cache_root, manifest_seed

            if self._plan_root is not None and cache_root(self.conf):
                from ..obs.history import plan_fingerprint

                fp = plan_fingerprint(self._plan_root, self.conf)
                self._persist_seed = manifest_seed(self.conf,
                                                   fp["fingerprint"])
        except Exception:
            self._persist_seed = None
        return self._persist_seed

    def _mesh_quota_seed(self, node, child, fused_mesh: bool,
                         num_out: int):
        """Mirror of the mesh exchanges' warm-start quota lookup: the
        same mesh_quota_key the execution layer computes from the
        staging geometry, resolved against the same manifest record."""
        seed = self._persist_seed_record()
        quotas = (seed or {}).get("mesh_quotas") or {}
        if not quotas:
            return None
        caps = [b.cap for part in child.parts for b in part]
        if not caps or any(c is None for c in caps):
            return None
        from ..exec.persist_cache import (
            mesh_quota_key_fused, mesh_quota_key_plain,
        )
        from ..parallel.mesh_fusion import mesh_stage_geometry

        rows_per_shard, _cap, _q = mesh_stage_geometry(sum(caps), num_out)
        p = node.partitioning
        pos = {a.expr_id: i for i, a in enumerate(node.output)}
        try:
            key_idx = tuple(pos[e.expr_id] for e in p.exprs)
        except (AttributeError, KeyError):
            return None
        dtypes = [str(a.dtype) for a in node.output]
        if fused_mesh:
            mkey = mesh_quota_key_fused(num_out, rows_per_shard, key_idx,
                                        len(node.output), dtypes)
        else:
            mkey = mesh_quota_key_plain(num_out, rows_per_shard, key_idx,
                                        dtypes)
        return quotas.get(mkey)

    # -- entry -------------------------------------------------------------
    def run(self, plan) -> AnalysisReport:
        self._plan_root = plan
        # persistent result cache: a plan whose collect would answer
        # from the on-disk result cache RIGHT NOW launches NOTHING —
        # planning is host-only work and the payload is already on disk.
        # Same key computation as the execution path (result_probe), so
        # the zero-launch hit prediction is exact by construction.
        try:
            from ..exec.persist_cache import result_probe

            hit = result_probe(plan, self.conf)
        except Exception:
            hit = False
        if hit:
            dec = getattr(plan, "_tier_decision", None) \
                or getattr(plan, "decision", None)
            if dec is not None:
                try:
                    self.report.tier = dec.to_dict()
                except Exception:
                    pass
            self._stage(plan, Counter(), 0, notes=(
                "RESULT CACHE HIT: this plan's fingerprint + leaf data "
                "versions match a stored result (spark.tpu.cache.dir) — "
                "the collect answers from the Arrow payload with ZERO "
                "kernel launches",))
            self.report.predicted_launches = {}
            return self.report
        # compile-tier decision: the planner stashes the chooser's verdict
        # (incl. the whole-query fallback reason) on the plan root; the
        # whole tier's own root node carries it directly
        dec = getattr(plan, "_tier_decision", None)
        if dec is not None and self.report.tier is None:
            try:
                self.report.tier = dec.to_dict()
            except Exception:
                pass
        self.visit(plan)
        # adaptive stage-boundary re-admission (physical/adaptive.py):
        # with the layer on, a multi-stage plan may collapse its
        # remaining stages into one whole-tier program after any shuffle
        # materializes — and a recurring query may re-plan from its
        # warm-start history before the first batch. Both re-decisions
        # depend on observed runtime sizes the static model cannot know,
        # so a re-admittable plan is honestly inexact; single-stage and
        # already-whole plans stay exact (nothing left to re-admit).
        if bool(self.conf.get(ADAPTIVE_READMISSION)):
            from ..physical.exchange import ShuffleExchangeExec
            from ..physical.whole_query import WholeQueryExec

            if not isinstance(plan, WholeQueryExec) and any(
                    isinstance(n, ShuffleExchangeExec)
                    for n in plan.iter_nodes()):
                self._approx(
                    "adaptive re-admission: remaining stages may collapse "
                    "into a whole-tier program at a stage boundary "
                    "(spark.tpu.adaptive.readmission — tier re-decision "
                    "uses observed runtime sizes)")
        # zero-count kinds (a probe that never fires on this plan) are
        # bookkeeping, not predictions — the measured delta never lists
        # them either
        self.report.predicted_launches = {
            k: v for k, v in self.predicted.items() if v}
        if self._hbm_any:
            self.report.predicted_peak_hbm = self._hbm_total
        self._explain_boundaries(plan)
        self._overflow_pass(plan)
        return self.report

    # -- memory model ------------------------------------------------------
    def _mem_approx(self, reason: str) -> None:
        self.report.memory_exact = False
        if reason not in self.report.memory_notes:
            self.report.memory_notes.append(reason)

    def _record_memory(self, node, flow: _Flow) -> None:
        """Predicted HBM of one stage's OUTPUT tiles: capacity × device
        row bytes (column data + validity planes + row mask — the same
        schema_row_bytes the MemoryManager budgets with and the same
        planes the runtime ledger registers per batch). Unknown
        capacities fall back to the session tile and degrade the model
        to approximate; the query peak sums stages (everything an
        execution materializes counts once)."""
        try:
            from ..exec.memory import schema_row_bytes
            from ..physical.operators import attrs_schema

            row_bytes = schema_row_bytes(attrs_schema(node.output))
        except Exception:
            row_bytes = None
            self._mem_approx(f"{type(node).__name__}: output schema "
                             "unavailable — stage bytes estimated at "
                             "16 B/row")
        total = 0
        for p in flow.parts:
            for b in p:
                cap = b.cap
                if cap is None:
                    cap = self._tile
                    self._mem_approx(
                        f"{type(node).__name__}: unknown tile capacity "
                        "assumed spark.tpu.batch.capacity")
                total += cap * (row_bytes if row_bytes else 16)
        ent = self._stage_by_node.get(id(node))
        if ent is not None and "hbm_bytes" not in ent:
            ent["hbm_bytes"] = total
            self._hbm_total += total
            self._hbm_any = True

    # -- dispatch ----------------------------------------------------------
    def visit(self, node) -> _Flow:
        flow = self._dispatch(node)
        self._record_memory(node, flow)
        return flow

    def _dispatch(self, node) -> _Flow:
        from ..physical import operators as O
        from ..physical.exchange import (
            BroadcastExchangeExec, ShuffleExchangeExec,
        )
        from ..physical.fusion import FusedAggregateExec, FusedLimitExec
        from ..physical.mesh_whole import MeshWholeQueryExec
        from ..physical.python_eval import PythonEvalExec
        from ..physical.whole_query import WholeQueryExec

        # MeshWholeQueryExec subclasses WholeQueryExec: route it first
        if isinstance(node, MeshWholeQueryExec):
            return self._mesh_whole(node)
        if isinstance(node, WholeQueryExec):
            return self._whole_query(node)
        if isinstance(node, PythonEvalExec):
            return self._python_eval(node)
        if isinstance(node, O.LocalTableScanExec):
            return self._local_scan(node)
        if isinstance(node, O.ScanExec):
            return self._scan(node)
        if isinstance(node, O.RangeExec):
            return self._range(node)
        if isinstance(node, O.ComputeExec):
            return self._compute(node)
        if isinstance(node, FusedAggregateExec):
            return self._fused_agg(node)
        if isinstance(node, O.HashAggregateExec):
            return self._agg(node)
        if isinstance(node, FusedLimitExec):
            return self._fused_limit(node)
        if isinstance(node, O.LimitExec):
            return self._limit(node)
        if isinstance(node, O.SortExec):
            return self._sort(node)
        if isinstance(node, O.HashJoinExec):
            return self._join(node)
        if isinstance(node, O.NestedLoopJoinExec):
            return self._nl_join(node)
        if isinstance(node, BroadcastExchangeExec):
            return self._broadcast(node)
        if isinstance(node, ShuffleExchangeExec):
            return self._exchange(node)
        if isinstance(node, O.UnionExec):
            return self._union(node)
        if isinstance(node, O.CoalescePartitionsExec):
            return self._coalesce(node)
        if isinstance(node, O.SampleExec):
            return self._sample(node)
        return self._unknown(node)

    # -- scans -------------------------------------------------------------
    def _batches_for_rows(self, n: int) -> list:
        if n == 0:
            return [_Batch(0, _EMPTY_CAP, True, ingest=True)]
        out = []
        for start in range(0, n, self._tile):
            rows = min(self._tile, n - start)
            out.append(_Batch(rows, bucket_capacity(rows), True,
                              ingest=True))
        return out

    @staticmethod
    def _is_traced_string(t) -> bool:
        import pyarrow as pa

        return (pa.types.is_string(t) or pa.types.is_large_string(t)
                or (pa.types.is_dictionary(t)
                    and (pa.types.is_string(t.value_type)
                         or pa.types.is_large_string(t.value_type))))

    def _table_trace(self, node) -> tuple:
        """(row count, value trace | None) of a LocalTableScan — shared
        by the per-stage layout model and the whole-query mirror."""
        return self._arrow_trace(node.table, node.attrs)

    def _arrow_trace(self, table, attrs) -> tuple:
        import pyarrow as pa

        n = table.num_rows
        cols = {}
        if 0 < n <= _TRACE_MAX_ROWS:
            names = {a.name: a for a in attrs}
            for fld in table.schema:
                a = names.get(fld.name)
                if a is None:
                    continue
                if pa.types.is_integer(fld.type):
                    arr = table.column(fld.name)
                    if isinstance(arr, pa.ChunkedArray):
                        arr = arr.combine_chunks()
                    valid = np.asarray(arr.is_valid()) \
                        if arr.null_count else None
                    vals = np.asarray(arr.fill_null(0))
                    cols[a.expr_id] = (vals, valid)
                elif self._is_traced_string(fld.type):
                    # encoding model: string values trace as object
                    # arrays — dictionary domains (dense-on-codes
                    # cardinality) and eq-key hash lanes derive from them
                    arr = table.column(fld.name)
                    if isinstance(arr, pa.ChunkedArray):
                        arr = arr.combine_chunks()
                    valid = np.asarray(arr.is_valid()) \
                        if arr.null_count else None
                    vals = np.empty(n, dtype=object)
                    vals[:] = ["" if v is None else v
                               for v in arr.to_pylist()]
                    cols[a.expr_id] = (vals, valid)
        return n, (_Trace(cols, np.ones(n, bool)) if cols else None)

    def _local_scan(self, node) -> _Flow:
        n, trace = self._table_trace(node)
        flow = _Flow([self._batches_for_rows(n)], trace)
        self._stage(node, Counter(), flow.total_batches,
                    [f"{n} rows, device-cached (stable identity)"])
        return flow

    # -- encoding model helpers ---------------------------------------------
    def _trace_domain(self, trace: Optional[_Trace], expr_id,
                      lo=None, hi=None):
        """Ordered dictionary domain of a traced string column over row
        span [lo, hi) (None = whole trace): the EXPLICIT domain when one
        is recorded (join/agg/shuffle outputs whose runtime dictionary
        covers more than the traced rows — slice-independent), else the
        appearance-order distinct of non-null values over the span,
        mirroring pyarrow dictionary_encode at ingest."""
        if trace is None:
            return None
        dom = trace.dict_domains.get(expr_id)
        if dom is not None:
            return dom
        if not trace.dict_derivable:
            return None
        ent = trace.cols.get(expr_id)
        if ent is None or ent[0].dtype != object:
            return None
        vals, valid = ent
        sl = slice(lo, hi)
        v = vals[sl]
        m = np.ones(len(v), bool) if valid is None else valid[sl]
        live = v[m]
        if not len(live):
            return ()
        # appearance-order distinct, vectorized: unique + first-index sort
        uniq, first = np.unique(live, return_index=True)
        return tuple(uniq[np.argsort(first)])

    def _chunk_dict_domain(self, trace: Optional[_Trace], batches,
                           expr_id):
        """Merged dictionary domain of one aggregation chunk (its batches
        concat and unify dictionaries): the explicit per-partition
        domain, or the derived domain when the chunk covers the whole
        traced partition."""
        if trace is None:
            return None
        dom = trace.dict_domains.get(expr_id)
        if dom is not None:
            return dom
        rows = [b.rows for b in batches]
        if all(r is not None for r in rows) \
                and sum(rows) == len(trace.live):
            return self._trace_domain(trace, expr_id)
        return None

    @staticmethod
    def _ordered_union(domains) -> tuple:
        seen: dict = {}
        for dom in domains:
            for v in dom:
                if v not in seen:
                    seen[v] = None
        return tuple(seen)

    def _scan(self, node) -> _Flow:
        nparts = node.source.num_partitions()
        flow = _Flow([[_Batch(None, None, False)] for _ in range(nparts)],
                     None, counted=False)
        self._stage(node, Counter(), None,
                    ["external source: per-partition batch counts unknown"])
        return flow

    def _part_tiles(self, total: int, nparts: int) -> list:
        """Per-partition (rows, capacity) tile layout of `total` rows
        ceil-div split across `nparts` partitions, each partition tiled
        at spark.tpu.batch.capacity — the ONE mirror of
        RangeExec.execute / InMemorySource.read_partition +
        table_to_batches leaf batching (shared by the per-stage layout
        model and the whole-query walk so the formulas cannot drift)."""
        per = -(-total // nparts) if total else 0
        out = []
        for q in range(nparts):
            lo = min(q * per, total)
            hi = min(lo + per, total)
            tiles = [(min(self._tile, hi - s),
                      bucket_capacity(min(self._tile, hi - s)))
                     for s in range(lo, hi, self._tile)] \
                or [(0, _EMPTY_CAP)]
            out.append(tiles)
        return out

    def _range(self, node) -> _Flow:
        step = node.step
        total = max(0, -(-(node.end - node.start) // step)) if step > 0 \
            else max(0, -(-(node.start - node.end) // -step))
        per = -(-total // node.num_partitions)
        parts = [[_Batch(r, c, False) for r, c in tiles]
                 for tiles in self._part_tiles(total,
                                               node.num_partitions)]
        trace = None
        ptraces = None
        if 0 < total <= _TRACE_MAX_ROWS:
            vals = node.start + np.arange(total, dtype=np.int64) * step
            if node.num_partitions == 1:
                trace = _Trace({node.attr.expr_id: (vals, None)},
                               np.ones(total, bool))
            else:
                ptraces = []
                for q in range(node.num_partitions):
                    lo = min(q * per, total)
                    hi = min(lo + per, total)
                    ptraces.append(_Trace(
                        {node.attr.expr_id: (vals[lo:hi], None)},
                        np.ones(hi - lo, bool)))
        flow = _Flow(parts, trace, ptraces=ptraces)
        self._stage(node, Counter(), flow.total_batches, [])
        return flow

    # -- compute -----------------------------------------------------------
    @staticmethod
    def _compute_trivial(node) -> bool:
        return not node.filters and all(
            isinstance(o, AttributeReference) for o in node.outputs)

    @staticmethod
    def _passthrough_runs(outputs, child_attrs,
                          b_ingest) -> "bool | frozenset":
        """RunInfo survival through a pipeline: expr ids of OUTPUTS that
        are pass-through attribute references (or aliases of one) whose
        source column carries run metadata on the input tile — the
        runtime attaches the same RunInfo object to those output columns
        (physical/compile.ExprPipeline), so sorted-run (ragg) aggregation
        stays reachable on filter/project→agg chains."""
        if not b_ingest:
            return False
        src = {a.expr_id for a in child_attrs} if b_ingest is True \
            else set(b_ingest)
        out = set()
        for o in outputs:
            if isinstance(o, AttributeReference) and o.expr_id in src:
                out.add(o.expr_id)
            elif isinstance(o, Alias) \
                    and isinstance(o.child, AttributeReference) \
                    and o.child.expr_id in src:
                out.add(o.expr_id)
        return frozenset(out) if out else False

    def _project_trace(self, trace, filters, outputs) -> Optional[_Trace]:
        if trace is None:
            return None
        live = trace.live.copy()
        for f in filters:
            m = _eval_filter(f, trace)
            if m is None:
                return None
            live &= m
        cols = {}
        domains = {}
        for o in outputs:
            if isinstance(o, AttributeReference):
                if o.expr_id in trace.cols:
                    cols[o.expr_id] = trace.cols[o.expr_id]
                if o.expr_id in trace.dict_domains:
                    domains[o.expr_id] = trace.dict_domains[o.expr_id]
            elif isinstance(o, Alias) and isinstance(o.child,
                                                     AttributeReference):
                if o.child.expr_id in trace.cols:
                    cols[o.expr_id] = trace.cols[o.child.expr_id]
                if o.child.expr_id in trace.dict_domains:
                    domains[o.expr_id] = trace.dict_domains[o.child.expr_id]
        return _Trace(cols, live, trace.consecutive, domains,
                      trace.dict_derivable)

    def _project_ptraces(self, child: _Flow, filters, outputs):
        if child.ptraces is None:
            return None
        return [None if t is None
                else self._project_trace(t, filters, outputs)
                for t in child.ptraces]

    def _compute(self, node) -> _Flow:
        child = self.visit(node.child)
        kinds = Counter()
        if self._compute_trivial(node):
            trace = self._project_trace(child.trace, [], node.outputs)
            flow = _Flow(child.parts, trace, counted=child.counted,
                         ptraces=self._project_ptraces(child, [],
                                                       node.outputs))
            self._stage(node, kinds, child.total_batches
                        if child.counted else None,
                        ["pure column selection: shares child arrays, "
                         "zero launches"])
            return flow
        if child.counted:
            kinds["pipeline"] = child.total_batches
        else:
            self._approx(f"pipeline launches of {node.simple_string()[:60]} "
                         "depend on an unknown upstream batch count")
        parts = [[_Batch(b.rows, b.cap, False,
                         ingest=self._passthrough_runs(
                             node.outputs, node.child.output, b.ingest))
                  for b in p]
                 for p in child.parts]
        trace = self._project_trace(child.trace, node.filters, node.outputs)
        flow = _Flow(parts, trace, counted=child.counted,
                     ptraces=self._project_ptraces(child, node.filters,
                                                   node.outputs))
        self._stage(node, kinds, child.total_batches if child.counted
                    else None, [])
        return flow

    # -- aggregation -------------------------------------------------------
    def _key_group_info(self, trace, key_id):
        """(sorted unique valid key values, any-null-keys-live) or None."""
        if trace is None:
            return None
        ent = trace.cols.get(key_id)
        if ent is None:
            return None
        vals, valid = ent
        m = trace.live if valid is None else (trace.live & valid)
        nulls_live = bool(valid is not None and (trace.live & ~valid).any())
        return np.unique(vals[m]), nulls_live

    @staticmethod
    def _agg_out_trace(key_id, uniq, nulls_live) -> _Trace:
        """Aggregate output key trace: live groups in kernel order —
        valid keys ascending (dense iota scatter and sorted-segment both
        emit them sorted), the null-key group last when present."""
        if nulls_live:
            vals = np.append(uniq, 0)
            valid = np.append(np.ones(len(uniq), bool), False)
        else:
            vals, valid = uniq, None
        return _Trace({key_id: (vals, valid)}, np.ones(len(vals), bool))

    def _agg_chunk_kinds(self, node, batches, trace, kinds: Counter,
                         notes: list):
        """Mirror HashAggregateExec._aggregate_chunk over one partition's
        batch list: concat (no launch) + one aggregation kernel, with the
        dense-range scalar probe when the decision is neither memoized
        (stable scan arrays) nor pre-seeded (shuffle-read tiles carry
        map-side stats). Returns the chunk's (output _Batch, output key
        _Trace|None) so downstream stages keep predicting exactly."""
        vals = node._plan_values()
        has_pc = any(op in ("percentile", "collect") for op, _, _ in vals)
        caps = [b.cap for b in batches]
        cap = bucket_capacity(sum(caps)) if all(
            c is not None for c in caps) and caps else None

        if not node.grouping:
            kinds["uagg"] += 1
            for op, _, _ in vals:
                if op == "percentile":
                    kinds["uperc"] += 1
            return _Batch(1, 8, False), None

        if self._encoding and not has_pc and len(node.grouping) == 1 \
                and isinstance(node.grouping[0].dtype, StringType):
            return self._dict_agg_chunk(node, batches, trace, cap, kinds,
                                        notes)

        single_int_key = len(node.grouping) == 1 and isinstance(
            node.grouping[0].dtype, (IntegralType, DateType))
        kid = node.grouping[0].expr_id if single_int_key else None
        probe = len(batches) > 1 or any(not b.probe_free_for(kid)
                                        for b in batches)
        dense = False
        ginfo = None
        span = None
        if single_int_key and not has_pc:
            kinds["krange3"] += 1 if probe else 0
            if not probe:
                if any(b.seeded for b in batches):
                    notes.append("dense-range scalars pre-seeded from "
                                 "map-side shuffle stats — no krange3 "
                                 "probe even on fresh arrays")
                else:
                    notes.append("dense-range scalars memoized on stable "
                                 "scan arrays — no krange3 probe per run")
            ginfo = self._key_group_info(trace, node.grouping[0].expr_id)
            if ginfo is not None and cap is not None:
                uniq, _nulls = ginfo
                if uniq.size:
                    span = int(uniq.max()) - int(uniq.min()) + 1
                    dense = span + 1 <= min(4 * cap, _DENSE_AGG_LIMIT)
            else:
                self._approx("dense-scatter vs sorted-segment aggregation "
                             f"over {node.grouping[0].name} is decided by "
                             "the runtime key span (untraced)")
            self._hazard(
                f"aggregate on {node.grouping[0].name}: the dense-scatter "
                "kernel's output capacity derives from the DATA's key span "
                "— span drift across batches recompiles (value-dependent "
                "cache key)")
        if dense:
            kinds["dagg"] += 1
        elif self._ragg_applies(batches, trace, single_int_key, has_pc,
                                node.grouping[0].expr_id
                                if single_int_key else None):
            kinds["ragg"] += 1
            notes.append("sorted-run RLE fast path: ingest RunInfo says "
                         "the key is already sorted — segment reduce per "
                         "run boundary, no grouping sort")
        else:
            kinds["gagg"] += 1
        for op, _, _ in vals:
            if op == "percentile":
                kinds["gperc"] += 1
        if has_pc:
            self._sync("percentile/collect aggregates build results "
                       "host-side (per-group host loop)")
        # output layout: exact only for the traced single-int-key case
        if single_int_key and not has_pc and ginfo is not None \
                and cap is not None:
            uniq, nulls_live = ginfo
            rows = int(uniq.size) + (1 if nulls_live else 0)
            out_cap = bucket_capacity(span + 1) if dense else cap
            return (_Batch(rows, out_cap, False),
                    self._agg_out_trace(node.grouping[0].expr_id, uniq,
                                        nulls_live))
        return _Batch(None, None, False), None

    def _ragg_applies(self, batches, trace, single_int_key: bool,
                      has_pc: bool, kid) -> bool:
        """Mirror of HashAggregateExec._try_run_sorted: the sorted-run
        (RLE) aggregate runs when the dense path declined, the chunk is
        ONE host-ingested tile (concat of several drops RunInfo), the key
        has no validity plane, and its values are non-decreasing over the
        tile's rows (ingest sortedness survives mask-only filters)."""
        if not self._encoding or not single_int_key or has_pc:
            return False
        from ..columnar.encoding import runs_harvest_enabled

        if not runs_harvest_enabled():
            # tiles ingested by this process carry no RunInfo (session
            # started under the decoded oracle) — ragg is unreachable
            return False
        if len(batches) != 1 or not batches[0].runs_for(kid):
            return False
        b = batches[0]
        if trace is None or b.rows is None or b.rows != len(trace.live):
            return False
        ent = trace.cols.get(kid)
        if ent is None:
            return False
        vals, valid = ent
        if valid is not None or vals.dtype == object:
            return False
        n = b.rows
        return bool(n > 0 and (np.diff(vals[:n]) >= 0).all())

    def _dict_agg_chunk(self, node, batches, trace, cap, kinds: Counter,
                        notes: list):
        """Single dictionary-encoded (string) grouping key: the int32
        codes ARE a dense group domain [0, len(dict)) and the runtime
        decides dense-on-codes from len(dictionary) HOST-SIDE — no
        krange3 probe ever (compressed execution). The model needs the
        dictionary cardinality (traced domain) only for the dense-fit
        check and the output layout."""
        kid = node.grouping[0].expr_id
        name = node.grouping[0].name
        dom = self._chunk_dict_domain(trace, batches, kid)
        if dom is None:
            self._approx(f"dense-on-codes aggregation over {name}: "
                         "dictionary cardinality untraced")
            dense = True  # the overwhelmingly common runtime outcome
        elif cap is None:
            # tile capacities always bucket to >= _EMPTY_CAP, so a small
            # dictionary fits the dense table regardless of the actual
            # (unknown) capacity — the decision stays EXACT
            if len(dom) + 1 <= 4 * _EMPTY_CAP:
                dense = True
            else:
                self._approx(f"dense-on-codes fit for {name} needs tile "
                             "capacities (unknown)")
                dense = True
        else:
            dense = len(dom) + 1 <= min(4 * cap, _DENSE_AGG_LIMIT)
        kinds["dagg" if dense else "gagg"] += 1
        note = ("dictionary-encoded grouping key: codes are a dense "
                "group domain — len(dictionary) decides host-side, no "
                "krange3 probe")
        if note not in notes:
            notes.append(note)
        self._hazard(
            f"aggregate on {name}: the dense-on-codes kernel's output "
            "capacity derives from the dictionary cardinality — "
            "dictionary growth across batches recompiles "
            "(value-dependent cache key)")
        if dom is None or not dense:
            return _Batch(None, None, False), None
        out_cap = bucket_capacity(len(dom) + 1)
        ent = trace.cols.get(kid)
        if ent is None:
            # cardinality known (explicit domain) but row values are not
            # traced: the layout stays unknown while the DOMAIN still
            # propagates — a downstream final aggregate can keep
            # deciding dense-on-codes exactly
            return (_Batch(None, out_cap, False),
                    _Trace({}, np.zeros(0, bool), True, {kid: dom},
                           False))
        vals, valid = ent
        m = trace.live if valid is None else (trace.live & valid)
        live_set = set(vals[m])
        live_vals = [v for v in dom if v in live_set]
        nulls_live = bool(valid is not None
                          and (trace.live & ~valid).any())
        rows = len(live_vals) + (1 if nulls_live else 0)
        ovals = np.empty(rows, dtype=object)
        ovals[: len(live_vals)] = live_vals
        ovalid = None
        if nulls_live:
            ovals[-1] = ""
            ovalid = np.append(np.ones(len(live_vals), bool), False)
        out_trace = _Trace({kid: (ovals, ovalid)}, np.ones(rows, bool),
                           True, {kid: dom}, False)
        return _Batch(rows, out_cap, False), out_trace

    def _merge_group_traces(self, traces: list) -> Optional[_Trace]:
        """Concatenate compacted per-partition traces (coalesced groups:
        partition batch lists concatenate in order)."""
        if any(t is None for t in traces):
            return None
        comp = [t.compacted() for t in traces]
        ids = set(comp[0].cols)
        for t in comp[1:]:
            ids &= set(t.cols)
        if not ids:
            return None
        cols = {}
        for k in ids:
            vals = np.concatenate([t.cols[k][0] for t in comp])
            vs = [t.cols[k][1] for t in comp]
            valid = None
            if any(v is not None for v in vs):
                valid = np.concatenate(
                    [np.ones(len(t.live), bool) if v is None else v
                     for t, v in zip(comp, vs)])
            cols[k] = (vals, valid)
        # merged dictionary domains: concat unifies dictionaries in
        # partition order (first-appearance union)
        domains = {}
        dom_ids = set()
        for t in traces:
            dom_ids |= set(t.dict_domains)
        dom_ids |= {k for k in ids if comp[0].cols[k][0].dtype == object}
        for k in dom_ids:
            per = [self._trace_domain(t, k) for t in traces]
            if all(d is not None for d in per):
                domains[k] = self._ordered_union(per)
        n = sum(len(t.live) for t in comp)
        return _Trace(cols, np.ones(n, bool),
                      all(t.consecutive for t in traces),
                      domains, False)

    def _agg(self, node) -> _Flow:
        from ..physical.adaptive import plan_merge_groups, _row_width
        from ..physical.exchange import ShuffleExchangeExec

        child = self.visit(node.child)
        parts = child.parts
        ptraces = [child.part_trace(i) for i in range(len(parts))]
        notes = []
        if node.mode == "final" and isinstance(node.child,
                                               ShuffleExchangeExec) \
                and len(parts) > 1 \
                and self.conf.get(ADAPTIVE_ENABLED) \
                and self.conf.get(COALESCE_PARTITIONS_ENABLED):
            sizes = [sum(b.rows for b in p) if all(b.rows is not None
                                                   for b in p) else None
                     for p in parts]
            if all(s is not None for s in sizes):
                # exact mirror of adaptive.coalesce_after_exchange: the
                # exchange value model knows per-reducer rows, so the
                # merge plan is deterministic
                if sum(sizes) == 0:
                    groups = [list(range(len(parts)))]
                else:
                    advisory = int(self.conf.get(ADVISORY_PARTITION_BYTES)) \
                        // _row_width(node.child.output)
                    groups = plan_merge_groups(sizes, advisory)
                if len(groups) != len(parts):
                    parts = [[b for i in g for b in parts[i]]
                             for g in groups]
                    ptraces = [self._merge_group_traces(
                        [ptraces[i] for i in g]) for g in groups]
                    notes.append(f"AQE coalescing merges reducer outputs "
                                 f"into {len(parts)} partition(s) "
                                 "(exact: reducer rows traced)")
            else:
                # AQE coalescing merges undersized reducer outputs;
                # assume one merged group (row-count dependent)
                parts = [[b for p in parts for b in p]]
                ptraces = [None]
                notes.append("AQE coalescing assumed to merge all reducer "
                             "outputs into one partition")
                self._approx("AQE partition coalescing before the final "
                             "aggregate depends on runtime row counts")
        kinds = Counter()
        max_rows = int(self.conf.get(AGG_BLOCK_ROWS))
        out_parts, out_traces = [], []
        for p, pt in zip(parts, ptraces):
            caps = [b.cap for b in p]
            known = all(c is not None for c in caps)
            blockwise = known and len(p) > 1 and sum(caps) > max_rows \
                and node.grouping and all(s.mergeable for s in node.specs)
            if not known and not child.counted:
                self._approx("aggregate chunking depends on unknown "
                             "upstream batch sizes")
            if blockwise:
                # fold in blockRows-bounded chunks, then merge partials
                chunk, acc, cs = [], 0, 0
                for b in p:
                    chunk.append(b)
                    cs += b.cap
                    if cs >= max_rows:
                        self._agg_chunk_kinds(node, chunk, pt, kinds,
                                              notes)
                        chunk, cs = [], 0
                        acc += 1
                if chunk:
                    self._agg_chunk_kinds(node, chunk, pt, kinds, notes)
                    acc += 1
                merged = [_Batch(None, None, False)] * acc
                self._agg_chunk_kinds(node, merged, None, kinds, notes)
                notes.append(f"blockwise fold: {acc} chunks + merge")
                out_parts.append([_Batch(None, None, False)])
                out_traces.append(None)
            else:
                ob, ot = self._agg_chunk_kinds(node, p, pt, kinds, notes)
                out_parts.append([ob])
                out_traces.append(ot)
        self._stage(node, kinds, child.total_batches if child.counted
                    else None, notes)
        return _Flow(out_parts, None, counted=child.counted,
                     ptraces=out_traces)

    def _fused_agg(self, node) -> _Flow:
        child = self.visit(node.child)
        kinds = Counter()
        notes = []
        single_int_key = len(node.grouping) == 1 and isinstance(
            node.grouping[0].dtype, (IntegralType, DateType))
        single_dict_key = self._encoding and len(node.grouping) == 1 \
            and isinstance(node.grouping[0].dtype, StringType)
        key_passthrough = single_int_key and any(
            isinstance(o, AttributeReference)
            and o.expr_id == node.grouping[0].expr_id
            for o in node.pipe_outputs)
        out_parts, out_traces = [], []
        for i, p in enumerate(child.parts):
            in_trace = child.part_trace(i)
            pipe_trace = self._project_trace(in_trace, node.filters,
                                             node.pipe_outputs)
            # the fused dense decision reads the memoized/seeded range of
            # the INPUT column — a PRE-filter superset (fusion.py
            # _dense_decision) — while the unfused gate branch probes the
            # materialized post-filter pipeline output
            pre_trace = self._project_trace(in_trace, [],
                                            node.pipe_outputs)
            key_span = None
            if single_int_key and pre_trace is not None:
                st = pre_trace.stats(node.grouping[0].expr_id)
                if st is not None and st.size:
                    key_span = int(st.max()) - int(st.min()) + 1
            caps = [b.cap for b in p]
            known = all(c is not None for c in caps)
            if not known:
                self._approx("fusion minRows gate undecidable: unknown "
                             "partition tile capacities")
                known_sum = None
            else:
                known_sum = sum(caps)
            if known_sum is not None and known_sum < self._min_rows:
                # runtime size gate: unfused operator-at-a-time kernels
                # (the materialized pipeline outputs keep pass-through
                # RunInfo, so ragg stays reachable behind the gate)
                kinds["pipeline"] += len(p)
                ob, ot = self._agg_chunk_kinds(node, [
                    _Batch(b.rows, b.cap, False,
                           ingest=self._passthrough_runs(
                               node.pipe_outputs, node.child.output,
                               b.ingest))
                    for b in p],
                    pipe_trace, kinds, notes)
                notes.append(
                    f"partition under spark.tpu.fusion.minRows="
                    f"{self._min_rows}: shared unfused kernels at runtime")
                out_parts.append([ob])
                out_traces.append(ot)
                continue
            kinds["fused_agg"] += len(p)
            if key_passthrough and self._dense_keys:
                kid = node.grouping[0].expr_id
                fresh_in = sum(1 for b in p if not b.probe_free_for(kid))
                kinds["krange3"] += fresh_in
                if fresh_in == 0:
                    notes.append("dense-range decision memoized/seeded per "
                                 "input column (no per-run host sync)")
            if single_dict_key and self._dense_keys:
                note = ("dictionary-encoded grouping key: dense-on-codes "
                        "decided in-kernel from the host-pass dictionary "
                        "— no krange3 probe")
                if note not in notes:
                    notes.append(note)
            dense = key_passthrough and self._dense_keys \
                and key_span is not None \
                and all(c is not None for c in caps) and caps \
                and key_span + 1 <= min(4 * min(caps), _DENSE_AGG_LIMIT)
            # per-batch dictionary domains (slice-derived or explicit):
            # the fused dense-on-codes variant keys its output capacity
            # on len(batch dictionary)
            dict_doms = None
            if single_dict_key and pipe_trace is not None \
                    and all(b.rows is not None for b in p):
                kid = node.grouping[0].expr_id
                dict_doms, r0 = [], 0
                for b in p:
                    dict_doms.append(self._trace_domain(
                        pipe_trace, kid, r0, r0 + b.rows))
                    r0 += b.rows
                if r0 != len(pipe_trace.live) \
                        or any(d is None for d in dict_doms):
                    dict_doms = None
            if len(p) > 1:
                # per-batch partials merge with final-mode ops; the partial
                # output capacity mirrors the fused kernel variant
                pcaps = []
                for bi, b in enumerate(p):
                    if not node.grouping:
                        pcaps.append(8)
                    elif key_passthrough and self._dense_keys \
                            and key_span is not None and b.cap is not None \
                            and key_span + 1 <= min(4 * b.cap,
                                                    _DENSE_AGG_LIMIT):
                        pcaps.append(bucket_capacity(key_span + 1))
                    elif single_dict_key and self._dense_keys \
                            and dict_doms is not None \
                            and b.cap is not None \
                            and len(dict_doms[bi]) + 1 <= min(
                                4 * b.cap, _DENSE_AGG_LIMIT):
                        pcaps.append(
                            bucket_capacity(len(dict_doms[bi]) + 1))
                    else:
                        pcaps.append(b.cap)
                merge = HashAggMergeProxy(node)
                merge_trace = pipe_trace
                if single_dict_key and pipe_trace is not None:
                    # the merged partials' dictionary is the union of the
                    # per-batch dictionaries = the partition-wide domain
                    kid = node.grouping[0].expr_id
                    dom_p = self._trace_domain(pipe_trace, kid)
                    if dom_p is not None:
                        merge_trace = _Trace(
                            pipe_trace.cols, pipe_trace.live,
                            pipe_trace.consecutive,
                            {**pipe_trace.dict_domains, kid: dom_p},
                            False)
                ob, ot = self._agg_chunk_kinds(
                    merge, [_Batch(None, c, False) for c in pcaps],
                    merge_trace, kinds, notes)
                notes.append(f"{len(p)} per-batch partials merge with "
                             "final-mode ops")
                out_parts.append([ob])
                out_traces.append(ot)
                continue
            # single fused batch: the kernel output IS the partition output
            if not node.grouping:
                out_parts.append([_Batch(1, 8, False)])
                out_traces.append(None)
                continue
            if single_dict_key:
                kid = node.grouping[0].expr_id
                dom = dict_doms[0] if dict_doms else None
                dense_d = self._dense_keys and dom is not None \
                    and caps and caps[0] is not None \
                    and len(dom) + 1 <= min(4 * caps[0], _DENSE_AGG_LIMIT)
                if dense_d:
                    fake = Counter()
                    ob, ot = self._dict_agg_chunk(
                        node, p, _Trace(
                            pipe_trace.cols, pipe_trace.live,
                            pipe_trace.consecutive,
                            {**pipe_trace.dict_domains, kid: dom}, False),
                        caps[0], fake, [])
                    out_parts.append([ob])
                    out_traces.append(ot)
                else:
                    out_parts.append([_Batch(None, None, False)])
                    out_traces.append(None)
                continue
            ginfo = self._key_group_info(pipe_trace,
                                         node.grouping[0].expr_id) \
                if single_int_key else None
            if ginfo is not None and caps and caps[0] is not None:
                uniq, nulls_live = ginfo
                rows = int(uniq.size) + (1 if nulls_live else 0)
                out_cap = bucket_capacity(key_span + 1) if dense \
                    else caps[0]
                out_parts.append([_Batch(rows, out_cap, False)])
                out_traces.append(self._agg_out_trace(
                    node.grouping[0].expr_id, uniq, nulls_live))
            else:
                out_parts.append([_Batch(None, None, False)])
                out_traces.append(None)
        self._stage(node, kinds, child.total_batches if child.counted
                    else None,
                    ["FUSED stage: filter/project traced into the partial-"
                     "aggregate kernel — 1 launch/batch"] + notes)
        return _Flow(out_parts, None, counted=child.counted,
                     ptraces=out_traces)

    # -- limit / sort ------------------------------------------------------
    def _limit(self, node) -> _Flow:
        child = self.visit(node.child)
        kinds = Counter()
        out_parts = []
        for p in child.parts:
            if p:
                kinds["limit"] += 1
                out_parts.append([_Batch(min(node.n, self._tile), None,
                                         False)])
            else:
                out_parts.append([])
        self._sync("LimitExec compaction host-syncs the live-row count "
                   "per partition")
        self._stage(node, kinds, child.total_batches if child.counted
                    else None, [])
        return _Flow(out_parts, None, counted=child.counted)

    def _fused_limit(self, node) -> _Flow:
        child = self.visit(node.child)
        kinds = Counter()
        notes = []
        out_parts = []
        for p in child.parts:
            if not p:
                out_parts.append([])
                continue
            caps = [b.cap for b in p]
            known = all(c is not None for c in caps)
            if known and sum(caps) < self._min_rows:
                kinds["pipeline"] += len(p)
                kinds["limit"] += 1
                notes.append("partition under spark.tpu.fusion.minRows: "
                             "shared unfused kernels at runtime")
            else:
                if not known:
                    self._approx("fusion minRows gate undecidable for "
                                 "FusedLimit (unknown capacities)")
                kinds["fused_limit"] += 1
            out_parts.append([_Batch(min(node.n, self._tile), None, False)])
        self._stage(node, kinds, child.total_batches if child.counted
                    else None,
                    ["FUSED stage: pipeline traced into the limit kernel — "
                     "1 launch per partition (batches concatenate)"] + notes)
        return _Flow(out_parts, None, counted=child.counted)

    def _sort(self, node) -> _Flow:
        child = self.visit(node.child)
        kinds = Counter()
        notes = []
        budget = self._sort_budget(node)
        out_parts = []
        for p in child.parts:
            if not p:
                out_parts.append([])
                continue
            caps = [b.cap for b in p]
            known = all(c is not None for c in caps)
            if known and budget is not None and sum(caps) > budget:
                self._approx("external range-bucketed sort: bucket count "
                             "and per-bucket kernels are data-dependent")
                self._hazard("external sort cache keys embed the bucket "
                             "count B (data-dependent) — skewed inputs "
                             "recompile the pid kernel per B")
                notes.append("over device budget: external multi-pass sort")
                out_parts.append([_Batch(None, None, False)])
                continue
            if not known and not child.counted:
                self._approx("sort budget check over unknown capacities")
            kinds["sort"] += 1
            out_parts.append([_Batch(None, None, False)])
        self._stage(node, kinds, child.total_batches if child.counted
                    else None, notes)
        return _Flow(out_parts, None, counted=child.counted)

    def _sort_budget(self, node):
        try:
            from ..exec.memory import MemoryManager

            mm = MemoryManager(self.conf)
            from ..physical.operators import attrs_schema

            return mm.tile_rows(attrs_schema(node.child.output),
                                amplification=3)
        except Exception:
            return None

    # -- joins -------------------------------------------------------------
    def _join(self, node) -> _Flow:
        from ..physical.exchange import ShuffleExchangeExec

        left = self.visit(node.left)
        right = self.visit(node.right)
        kinds = Counter()
        notes = []
        if node.is_broadcast:
            pairs = [(lp, right.parts[0] if right.parts else [])
                     for lp in left.parts]
        else:
            if isinstance(node.left, ShuffleExchangeExec) or isinstance(
                    node.right, ShuffleExchangeExec):
                self._approx("shuffled join: AQE coalescing/skew splitting "
                             "reshape partitions at runtime")
            if len(left.parts) != len(right.parts):
                self._approx("join partition pairing unknown")
                pairs = []
            else:
                pairs = list(zip(left.parts, right.parts))

        rf_on = bool(self.conf.get(MINMAX_JOIN_FILTER)) \
            or bool(self.conf.get(BLOOM_JOIN_FILTER))
        fused = node.probe_fusion is not None and not (
            node.join_type == "full_outer" or rf_on)
        if node.probe_fusion is not None and not fused:
            notes.append("fused probe pipeline materialized up-front "
                         "(full_outer / runtime-filter path reads probe "
                         "keys outside the kernel)")
        if rf_on:
            self._approx("runtime join filters add data-dependent "
                         "filter/compaction kernels")

        single_int_bkey = len(node.right_keys) == 1 and isinstance(
            node.right_keys[0].dtype, (IntegralType, DateType))
        # string build keys: the dense-build fast paths stay int-only,
        # but the MATCH-CARDINALITY trace (probe-capacity retries) works
        # on raw values regardless of type
        single_str_bkey = len(node.right_keys) == 1 and isinstance(
            node.right_keys[0].dtype, StringType)

        # per-pair traces: post-exchange flows carry per-partition traces
        # (mesh/host shuffled layouts), so the probe AND build value
        # models hold through multi-partition joins too
        pair_traces = [left.part_trace(i) for i in range(len(pairs))]
        if fused:
            filters, outputs = node.probe_fusion
            pair_traces = [None if t is None
                           else self._project_trace(t, filters, outputs)
                           for t in pair_traces]
        build_traces = [right.part_trace(0 if node.is_broadcast else i)
                        for i in range(len(pairs))]

        # adaptive runtime join filters (physical/adaptive.py): once the
        # build side materializes, its key domain prunes rows — and whole
        # batches — inside the not-yet-run probe shuffle. Stage run
        # order, dense-range memo hits, and the fusion size gate are all
        # run-dependent, so an ELIGIBLE pattern degrades the launch model
        # honestly; ineligible shapes (broadcast, outer joins, composite
        # or non-integral/string keys, no shuffled probe) are evaluated
        # host-side and stay exact.
        adaptive_rf = (bool(self.conf.get(ADAPTIVE_RUNTIME_FILTER))
                       and not node.is_broadcast
                       and node.join_type in ("inner", "left_semi")
                       and len(node.left_keys) == 1
                       and isinstance(node.left, ShuffleExchangeExec)
                       and isinstance(node.left_keys[0].dtype,
                                      (IntegralType, DateType, StringType)))
        if adaptive_rf:
            self._approx(
                "adaptive runtime join filter: the materialized build "
                "side's key domain prunes probe-shuffle rows/batches at "
                "runtime (spark.tpu.adaptive.runtimeFilter)")
            bt = build_traces[0] if build_traces else None
            bvals = bt.stats(node.right_keys[0].expr_id) \
                if bt is not None else None
            if bvals is not None and bvals.size and isinstance(
                    node.right_keys[0].dtype, (IntegralType, DateType)):
                # the value model CAN evaluate the build domain host-side
                # — surface the evaluated filter in the report
                notes.append(
                    "runtime-filter build domain evaluated host-side: "
                    f"[{int(bvals.min())}, {int(bvals.max())}]")

        out_parts = []
        out_traces = []
        for pi, (lp, rp) in enumerate(pairs):
            probe_trace = pair_traces[pi]
            bstats = build_traces[pi].stats(node.right_keys[0].expr_id) \
                if (build_traces[pi] is not None
                    and (single_int_bkey or single_str_bkey)) \
                else None
            bcaps = [b.cap for b in rp]
            bknown = all(c is not None for c in bcaps) and rp
            bcap = bucket_capacity(sum(bcaps)) if bknown else None
            bkid = node.right_keys[0].expr_id if single_int_bkey else None
            bfresh = (len(rp) != 1) or any(not b.probe_free_for(bkid)
                                           for b in rp)
            grace = False
            if bknown:
                budget = self._join_budget(node)
                if budget is not None and sum(bcaps) > budget:
                    grace = True
            if grace:
                self._approx("grace hash join fragments both sides by key "
                             "hash — fragment kernels are data-dependent")
                notes.append("build side over device budget: grace join")
                out_parts.append([_Batch(None, None, False)])
                out_traces.append(None)
                continue
            pair_fused = fused
            if pair_fused:
                pcaps = [b.cap for b in lp]
                if lp and all(c is not None for c in pcaps) \
                        and sum(pcaps) < self._min_rows:
                    # runtime size gate: pipeline materializes up front
                    kinds["pipeline"] += len(lp)
                    pair_fused = False
                    notes.append("probe partition under spark.tpu.fusion."
                                 f"minRows={self._min_rows}: pipeline + "
                                 "shared probe kernels at runtime")
            dense = False
            if single_int_bkey:
                kinds["krange3"] += 1 if bfresh else 0
                if bstats is not None and bcap is not None:
                    span = (int(bstats.max()) - int(bstats.min()) + 1) \
                        if bstats.size else None
                    if span is not None and \
                            span <= min(8 * bcap, _DENSE_JOIN_LIMIT):
                        kinds["djoin_build"] += 1
                        dense = np.unique(bstats).size == bstats.size
                else:
                    self._approx(
                        f"dense vs sorted join build on "
                        f"{node.right_keys[0].name}: key span/uniqueness "
                        "untraced")
                    kinds["djoin_build"] += 1
                self._hazard(
                    f"join build on {node.right_keys[0].name}: dense table "
                    "capacity derives from the key span (value-dependent "
                    "cache key); duplicate keys fall back to sorted probe "
                    "at runtime")
                self._sync("dense join-build verdict is one memoized device "
                           "scalar per build column identity")
            if dense:
                kind = "fused_djoin_probe" if pair_fused else "djoin_probe"
                kinds[kind] += len(lp) if lp else 1
            else:
                kinds["join_build"] += 1
                kind = "fused_probe" if pair_fused else "join_probe"
                launches = 0
                batches = lp if lp else [_Batch(0, _EMPTY_CAP, True)]
                counts = self._build_key_counts(bstats)
                row0 = 0
                for b in batches:
                    launches += 1
                    launches += self._probe_retries(
                        node, b, row0, probe_trace, counts)
                    row0 += b.rows if b.rows is not None else (b.cap or 0)
                kinds[kind] += launches
            if node.join_type == "full_outer":
                notes.append("full_outer unmatched-build pass runs EAGER "
                             "device ops (uncached, uncounted dispatches)")
                self._hazard("full_outer unmatched-build pass bypasses the "
                             "KernelCache (eager per-run dispatches)")
            ob, ot = self._join_output(node, lp, dense, bstats,
                                       probe_trace, build_traces[pi])
            out_parts.append(ob)
            out_traces.append(ot)
        self._stage(node, kinds, left.total_batches if left.counted
                    else None, notes)
        return _Flow(out_parts, None,
                     counted=left.counted and right.counted,
                     ptraces=out_traces)

    def _join_output(self, node, lp, dense, bstats, probe_trace,
                     build_trace=None):
        """Per-pair output layout + value trace through the join. Exact
        for the dense inner case (unique integral build keys: the probe is
        a 1:1 gather in probe-row order); everything else keeps the
        unknown layout the earlier model reported. Dictionary domains of
        build-side string columns ride the output trace (the gather keeps
        the FULL build dictionary), so downstream dense-on-codes
        aggregates keep deciding exactly."""
        # dictionary domains are LAYOUT-independent: the join gathers
        # keep the probe batch's dictionary on probe columns and the
        # FULL build dictionary on build columns, whatever the match
        # cardinality — so they propagate even when the row layout is
        # unknown (downstream dense-on-codes aggregates keep deciding)
        domains = {}
        if probe_trace is not None:
            for k in probe_trace.cols:
                dom = self._trace_domain(probe_trace, k)
                if dom is not None:
                    domains[k] = dom
            for k, dom in probe_trace.dict_domains.items():
                domains.setdefault(k, dom)
        if build_trace is not None:
            for a in node.right.output:
                if isinstance(a.dtype, StringType):
                    dom = self._trace_domain(build_trace, a.expr_id)
                    if dom is not None:
                        domains[a.expr_id] = dom
        dom_trace = _Trace({}, np.zeros(0, bool), True, domains, False) \
            if domains else None
        nb = max(len(lp), 1) + (1 if node.join_type == "full_outer" else 0)
        unknown = ([_Batch(None, None, False) for _ in range(nb)],
                   dom_trace)
        if not (dense and node.join_type == "inner" and lp
                and probe_trace is not None and probe_trace.consecutive
                and bstats is not None and len(node.left_keys) == 1):
            return unknown
        ent = probe_trace.cols.get(node.left_keys[0].expr_id)
        if ent is None:
            return unknown
        vals, valid = ent
        bvals = np.unique(bstats)
        live = probe_trace.live if valid is None \
            else (probe_trace.live & valid)
        matched_mask = live & np.isin(vals, bvals)
        out_batches = []
        row0 = 0
        ok = True
        for b in lp:
            width = b.rows if b.rows is not None else b.cap
            if width is None or b.cap is None:
                ok = False
                break
            lo, hi = row0, min(row0 + width, len(vals))
            out_batches.append(
                _Batch(int(matched_mask[lo:hi].sum()), b.cap, False))
            row0 += width
        if not ok:
            return unknown
        # probe-side columns pass through row-for-row where matched
        sel = np.nonzero(matched_mask)[0]
        cols = {k: (v[sel], None if vv is None else vv[sel])
                for k, (v, vv) in probe_trace.cols.items()}
        return (out_batches,
                _Trace(cols, np.ones(len(sel), bool), True, domains,
                       False))

    def _build_key_counts(self, bstats):
        if bstats is None or bstats.size == 0:
            return None
        vals, counts = np.unique(bstats, return_counts=True)
        return vals, counts

    def _probe_retries(self, node, batch, row0, probe_trace, counts) -> int:
        """Capacity-retry launches for one sorted-probe batch: the kernel
        re-runs with a doubled output bucket when matched pairs overflow
        max(probe_cap, 1024)."""
        if node.join_type != "inner":
            # outer/semi needed-row semantics differ; only retry-predict
            # the inner case, flag the rest
            self._approx(f"{node.join_type} sorted-probe output capacity "
                         "is data-dependent (retry count untraced)")
            return 0
        if batch.cap is None:
            self._approx("sorted-probe retry check needs the probe batch "
                         "capacity (unknown)")
            return 0
        out_cap = max(batch.cap, 1 << 10)
        if counts is None or probe_trace is None \
                or not probe_trace.consecutive:
            self._approx("sorted-probe join expansion untraced: capacity "
                         "retries unpredictable")
            self._hazard("sorted-probe kernels re-launch with doubled "
                         "output capacity on overflow (value-dependent "
                         "cache key + extra dispatches)")
            return 0
        pk = node.left_keys[0] if len(node.left_keys) == 1 else None
        if pk is None:
            self._approx("multi-key sorted-probe expansion untraced")
            return 0
        ent = probe_trace.cols.get(pk.expr_id)
        if ent is None:
            self._approx(f"probe key {pk.name} untraced: sorted-probe "
                         "retries unpredictable")
            return 0
        vals, valid = ent
        m = probe_trace.live if valid is None else (probe_trace.live & valid)
        width = batch.rows if batch.rows is not None else (batch.cap or 0)
        lo, hi = row0, min(row0 + width, len(vals))
        bvals = vals[lo:hi][m[lo:hi]]
        cvals, ccounts = counts
        idx = np.searchsorted(cvals, bvals)
        idx = np.clip(idx, 0, len(cvals) - 1)
        matched = cvals[idx] == bvals
        needed = int(ccounts[idx[matched]].sum())
        if needed > out_cap:
            self._hazard("sorted-probe join overflowed its output bucket "
                         f"(needed {needed} > {out_cap}): one retry launch "
                         "with a doubled capacity (value-dependent key)")
            return 1
        return 0

    def _join_budget(self, node):
        try:
            from ..exec.memory import MemoryManager
            from ..physical.operators import attrs_schema

            mm = MemoryManager(self.conf)
            return mm.tile_rows(attrs_schema(node.right.output),
                                amplification=4)
        except Exception:
            return None

    def _nl_join(self, node) -> _Flow:
        left = self.visit(node.left)
        self.visit(node.right)
        kinds = Counter()
        if node.condition is not None and left.counted:
            kinds["pipeline"] = left.total_batches
        elif node.condition is not None:
            self._approx("nested-loop condition pipeline count unknown")
        self._hazard("NestedLoopJoinExec cross-product runs EAGER device "
                     "ops (uncached, uncounted dispatches; output capacity "
                     "is |probe|x|build|)")
        out_parts = [[_Batch(None, None, False)
                      for _ in range(max(len(p), 1)
                                     * (2 if node.join_type == "left_outer"
                                        else 1))]
                     for p in left.parts]
        self._stage(node, kinds, left.total_batches if left.counted
                    else None, [])
        return _Flow(out_parts, None, counted=left.counted)

    # -- exchanges ---------------------------------------------------------
    def _broadcast(self, node) -> _Flow:
        child = self.visit(node.child)
        merged = [b for p in child.parts for b in p]
        if len(merged) == 1:
            out = [merged[0]]
        else:
            caps = [b.cap for b in merged]
            cap = bucket_capacity(sum(caps)) if merged and all(
                c is not None for c in caps) else None
            rows = sum(b.rows for b in merged) if all(
                b.rows is not None for b in merged) else None
            out = [_Batch(rows, cap, False)]
        trace = child.trace
        if trace is None:
            # multi-partition child (e.g. a mesh/host shuffled flow):
            # the replicate concatenates every partition, so the merged
            # value trace is the per-partition traces in order — build
            # sides over broadcast exchange outputs keep their key stats
            ptr = child.all_part_traces()
            if ptr is not None:
                trace = self._merge_group_traces(ptr)
        self._stage(node, Counter(), child.total_batches if child.counted
                    else None, ["no kernels: host-orchestrated replicate"])
        return _Flow([out], trace, counted=child.counted)

    def _mesh_active(self, num_out: int) -> bool:
        if not self.conf.get(MESH_ENABLED):
            return False
        if num_out < 2 or (num_out & (num_out - 1)) != 0:
            return False
        try:
            import jax

            return len(jax.devices()) >= num_out
        except Exception:
            return False

    # -- mesh stage model ---------------------------------------------------
    def _mesh_exchange(self, node, child, p, fused: bool, kinds: Counter,
                       notes: list) -> _Flow:
        """Launch model of the mesh SPMD stage (parallel/mesh_fusion.py):
        ONE sharded dispatch per step for the whole stage, plus one
        re-dispatch per quota-overflow retry. The staging geometry and
        the retry loop mirror mesh_exchange exactly, so when the key
        values trace the prediction is EXACT — retries included."""
        num_out = p.num_partitions
        if self._cluster:
            # a cluster scheduler splits the plan at exchanges: whether
            # this exchange runs a (worker-)local mesh collective or the
            # host shuffle write + fetch depends on stage placement, and
            # reduce tiles rebuilt from MapStatus arrive pre-seeded —
            # launch counts here are placement-dependent, not exact
            self._approx("cluster scheduler: mesh-capable exchange "
                         "placement (local collective vs host shuffle "
                         "write) is a stage-scheduling decision")
            kinds["mesh_stage"] += 1
            notes.append("mesh-capable exchange under a cluster "
                         "scheduler: placement decided at stage build")
            self._stage(node, kinds, child.total_batches if child.counted
                        else None, notes)
            return _Flow([[_Batch(None, None, False, seeded=True)]
                          for _ in range(num_out)], None, counted=False)
        fused_mesh = fused and self._fusion_mesh
        if fused and not fused_mesh:
            if child.counted:
                kinds["pipeline"] += child.total_batches
            else:
                self._approx("mesh pipeline materialization count depends "
                             "on an unknown upstream batch count")
            notes.append("mesh fallback (spark.tpu.fusion.mesh=false): "
                         "the fused map side materializes the pipeline "
                         "per batch before the all-to-all")
        if fused_mesh:
            notes.append("FUSED mesh stage: pipeline + partition ids + "
                         "all-to-all compiled as ONE shard_map program — "
                         "1 sharded dispatch per step, send buffers "
                         "donated (spark.tpu.fusion.minRows does not "
                         "apply: one program per step, not per batch; "
                         "dictionary-encoded keys hash through replicated "
                         "codes→value-hash lut aux planes)")
        else:
            notes.append("mesh SPMD stage: ONE sharded dispatch "
                         "redistributes the staged batches")
        self._hazard("mesh stage cache key embeds the per-pair row quota "
                     "— skewed data recompiles with a doubled quota")
        key_ids = [e.expr_id for e in p.exprs
                   if isinstance(e, AttributeReference)]
        # the stage program accumulates per-reduce-partition min/max for
        # the exchange's stat columns IN-PROGRAM and seeds the
        # dense-range memo at build time (parallel/mesh_exchange.
        # _seed_mesh_stats) — mesh reduce tiles are probe-free for those
        # columns exactly like host-shuffle rebuilt tiles, and the
        # seeded span equals the tile's own rows' span, so the dense
        # decision model below stays exact
        seeded = self._exchange_seeded(node)
        sim = None
        if len(key_ids) == len(p.exprs) and child.counted:
            in_traces = self._exchange_input_traces(node, child, fused)
            if in_traces is not None:
                sim = self._mesh_sim(child, in_traces, key_ids, num_out,
                                     seeded=seeded,
                                     quota_seed=self._mesh_quota_seed(
                                         node, child, fused_mesh,
                                         num_out))
        if sim is None:
            self._approx("mesh stage quota retries are data-dependent "
                         "and the key values are untraced — assuming one "
                         "dispatch, reduce layout unknown")
            kinds["mesh_stage"] += 1
            self._stage(node, kinds, child.total_batches if child.counted
                        else None, notes)
            return _Flow([[_Batch(None, None, False, seeded=seeded)]
                          for _ in range(num_out)], None, counted=True)
        attempts, flow = sim
        kinds["mesh_stage"] += attempts
        if attempts > 1:
            notes.append(f"{attempts - 1} quota "
                         f"retr{'y' if attempts == 2 else 'ies'}: a "
                         "(src,dst) pair overflowed its row quota and "
                         "the stage re-dispatched doubled")
        notes.append("reduce layout EXACT: staged-shard hash simulation "
                     "decides per-reducer rows and the retry count")
        self._stage(node, kinds, child.total_batches if child.counted
                    else None, notes)
        return flow

    def _mesh_sim(self, child: _Flow, traces: list, key_ids: list,
                  num_out: int, seeded: "bool | frozenset" = False,
                  quota_seed: "int | None" = None):
        """Host mirror of the mesh staging + quota-retry loop. Returns
        (attempts, output _Flow) or None when the layout cannot be
        reconstructed. Mirrors parallel/mesh_exchange: batches flatten
        partition-major into a [total_cap] plane, shard s owns data rows
        [s*rows_per_shard, (s+1)*rows_per_shard), pids come from the
        splitmix64 host mirror, and the quota doubles (one extra
        dispatch) while any (src,dst) bucket overflows. `quota_seed`
        mirrors the persistent warm-start manifest: a seeded first
        attempt starts at the prior run's final quota."""
        # the SAME geometry helper the runtime stages with — the mirror
        # cannot drift from the execution layer
        from ..parallel.mesh_fusion import mesh_stage_geometry

        ids = set(traces[0].cols)
        for t in traces[1:]:
            ids &= set(t.cols)
        if any(k not in ids for k in key_ids):
            return None
        # global staged plane: per-batch capacity slots, data rows first
        total_cap = 0
        spans = []  # (trace, r0, rows_b, off, cap)
        for part, t in zip(child.parts, traces):
            r0 = 0
            for b in part:
                if b.cap is None or b.rows is None:
                    return None
                spans.append((t, r0, b.rows, total_cap, b.cap))
                r0 += b.rows
                total_cap += b.cap
            if r0 != len(t.live):
                return None
        if total_cap == 0:
            return None
        live = np.zeros(total_cap, bool)
        gcols = {}
        for k in ids:
            dt = traces[0].cols[k][0].dtype
            has_valid = any(t.cols[k][1] is not None for t in traces)
            base = np.full(total_cap, "", dtype=object) \
                if dt == object else np.zeros(total_cap, dtype=dt)
            gcols[k] = [base,
                        np.zeros(total_cap, bool) if has_valid else None]
        # mesh staging merges every batch's dictionary into ONE global
        # dictionary (parallel/mesh_exchange._stage_payloads) — every
        # reduce partition shares the merged domain
        global_doms = {}
        for k in ids:
            if traces[0].cols[k][0].dtype == object:
                per = [self._trace_domain(t, k) for t in traces]
                if all(d is not None for d in per):
                    global_doms[k] = self._ordered_union(per)
        for t, r0, rows_b, off, _cap in spans:
            sl = slice(r0, r0 + rows_b)
            live[off: off + rows_b] = t.live[sl]
            for k in ids:
                vals, valid = t.cols[k]
                gvals, gvalid = gcols[k]
                gvals[off: off + rows_b] = vals[sl]
                if gvalid is not None:
                    gvalid[off: off + rows_b] = (
                        np.ones(rows_b, bool) if valid is None
                        else valid[sl])
        pids = _np_hash_pids([(gcols[k][0], gcols[k][1])
                              for k in key_ids], num_out)
        live_idx = np.nonzero(live)[0]
        rows_per_shard, _shard_cap, quota = mesh_stage_geometry(
            total_cap, num_out)
        if quota_seed and int(quota_seed) > quota:
            quota = int(quota_seed)
        shard = live_idx // rows_per_shard
        pid_live = pids[live_idx]
        attempts = 1
        while True:
            counts = np.zeros((num_out, num_out), np.int64)
            np.add.at(counts, (shard, pid_live), 1)
            if not len(live_idx) or counts.max() <= quota:
                break
            if attempts >= 8:
                return None  # degrades to the host shuffle at runtime
            quota *= 2
            attempts += 1
        out_cap = num_out * quota
        parts, ptraces = [], []
        for q in range(num_out):
            sel = live_idx[pid_live == q]  # ascending == shard-major,
            # then original position: the stable per-shard pid sort
            rows_q = int(len(sel))
            parts.append([_Batch(rows_q, out_cap, False, seeded=seeded)])
            cols_q = {k: (gv[sel],
                          None if gvalid is None else gvalid[sel])
                      for k, (gv, gvalid) in gcols.items()}
            ptraces.append(_Trace(cols_q, np.ones(rows_q, bool), True,
                                  dict(global_doms), False))
        return attempts, _Flow(parts, None, counted=True, ptraces=ptraces)

    # -- exchange layout/value helpers -------------------------------------
    @staticmethod
    def _exchange_seeded(node) -> "bool | frozenset":
        """Which output columns the exchange's map-side write accumulates
        stats for — the SAME annotation the execution layer consumes
        (ShuffleExchangeExec.stat_cols, set by annotate_exchange_stat_
        cols to the plan-reachable dense candidates). None = the legacy
        every-integral-column model (bare plans)."""
        sc = getattr(node, "stat_cols", None)
        if sc is None:
            return True
        out = node.output
        return frozenset(out[i].expr_id for i in sc if i < len(out))

    def _built_partition(self, rows_p: int,
                         seeded: "bool | frozenset" = True) -> list:
        """Output tiles of one reduce partition as exec/shuffle._OutBuffer
        builds them: tile rows capped at spark.tpu.batch.capacity,
        power-of-two capacity per tile, every tile pre-seeded with the
        map-side column stats of the exchange's stat columns (fresh
        arrays, no krange3 probe for those columns)."""
        if rows_p == 0:
            return [_Batch(0, _EMPTY_CAP, False, seeded=seeded,
                           ingest=True)]
        out = []
        for start in range(0, rows_p, self._tile):
            n = min(self._tile, rows_p - start)
            # rebuilt tiles are host-ingested (ColumnarBatch.from_numpy):
            # integral columns carry RunInfo, so a reducer whose rows
            # arrive sorted can take the ragg kernel
            out.append(_Batch(n, bucket_capacity(n), False, seeded=seeded,
                              ingest=True))
        return out

    def _exchange_input_traces(self, node, child: _Flow,
                               fused: bool) -> Optional[list]:
        """Per-input-partition traces at the exchange's consumption level
        (the pipeline OUTPUT when the map side is fused)."""
        traces = child.all_part_traces()
        if traces is None:
            return None
        if fused:
            filters, outputs = node.pipe_fusion
            traces = [self._project_trace(t, filters, outputs)
                      for t in traces]
            if any(t is None for t in traces):
                return None
        if not all(t.consecutive for t in traces):
            return None
        return traces

    def _shuffled_flow(self, in_traces: list, pids_per_part: list,
                       num_out: int,
                       seeded: "bool | frozenset" = True,
                       in_parts: Optional[list] = None) -> _Flow:
        """Exact post-shuffle layout + per-reduce-partition value traces:
        reduce partition q = every input partition's live rows with
        pid == q, input order preserved (the stable pid sort groups rows
        without reordering within a pid). With `in_parts` (the exchange
        input's batch layout), per-reduce dictionary domains mirror the
        rebuild: a reduce tile's merged dictionary is the union of the
        FULL dictionaries of every input batch that contributed rows
        (exec/shuffle._OutBuffer chunks carry whole-batch dictionaries)."""
        comp = [t.compacted() for t in in_traces]
        ids = set(comp[0].cols) if comp else set()
        for t in comp[1:]:
            ids &= set(t.cols)
        dict_ids = [k for k in ids
                    if comp and comp[0].cols[k][0].dtype == object]
        # per input partition: (live-row -> source batch index, per-batch
        # domain) for the dictionary-union mirror
        chunk_info = None
        if dict_ids and in_parts is not None:
            chunk_info = []
            for t, part in zip(in_traces, in_parts):
                rows = [b.rows for b in part]
                if any(r is None for r in rows) \
                        or sum(rows) != len(t.live):
                    chunk_info = None
                    break
                src = np.repeat(np.arange(len(part)), rows)[t.live]
                doms = {}
                ok = True
                for k in dict_ids:
                    r0, per = 0, []
                    for r in rows:
                        d = self._trace_domain(t, k, r0, r0 + r)
                        per.append(d)
                        r0 += r
                        if d is None:
                            ok = False
                    doms[k] = per
                if not ok:
                    chunk_info = None
                    break
                chunk_info.append((src, doms))
        parts, ptraces = [], []
        for q in range(num_out):
            sels = [np.nonzero(pids == q)[0] for pids in pids_per_part]
            rows_q = int(sum(len(s) for s in sels))
            built = self._built_partition(rows_q, seeded)
            parts.append(built)
            cols_q = {}
            for k in ids:
                vals = np.concatenate(
                    [t.cols[k][0][s] for t, s in zip(comp, sels)])
                vs = [t.cols[k][1] for t in comp]
                valid = None
                if any(v is not None for v in vs):
                    valid = np.concatenate(
                        [np.ones(len(s), bool) if v is None else v[s]
                         for v, s in zip(vs, sels)])
                cols_q[k] = (vals, valid)
            domains = {}
            if chunk_info is not None and len(built) == 1:
                # single rebuilt tile: its dictionary = ordered union of
                # contributing chunks' full batch dictionaries
                for k in dict_ids:
                    contributing = []
                    for (src, doms), s in zip(chunk_info, sels):
                        hit = np.unique(src[s]) if len(s) else []
                        contributing.extend(doms[k][int(b)] for b in hit)
                    domains[k] = self._ordered_union(contributing)
            ptraces.append(_Trace(cols_q, np.ones(rows_q, bool), True,
                                  domains, False))
        return _Flow(parts, None, counted=True, ptraces=ptraces)

    def _map_side_kinds(self, node, child: _Flow, fused: bool,
                        plain_kind: str, kinds: Counter, notes: list):
        """Map-side launch model: fused exchanges run ONE fused_shuffle
        dispatch per batch (partitions under minRows fall back to the
        shared pipeline + shuffle kernels); unfused exchanges run the
        plain shuffle kind per batch."""
        if not fused:
            if child.counted:
                kinds[plain_kind] += child.total_batches
            else:
                self._approx("host shuffle launches depend on unknown "
                             "upstream batch count")
            return
        gated = False
        for pp in child.parts:
            caps = [b.cap for b in pp]
            if not all(c is not None for c in caps):
                self._approx("fusion minRows gate undecidable for the "
                             "fused exchange (unknown tile capacities)")
                kinds["fused_shuffle"] += len(pp)
            elif sum(caps) < self._min_rows:
                kinds["pipeline"] += len(pp)
                kinds[plain_kind] += len(pp)
                gated = True
            else:
                kinds["fused_shuffle"] += len(pp)
        notes.append("FUSED map side: pipeline + partition-id kernel "
                     "traced into ONE program per batch; shuffle writes "
                     "consume the grouped result directly")
        if gated:
            notes.append(f"map partition under spark.tpu.fusion.minRows="
                         f"{self._min_rows}: pipeline + shared shuffle "
                         "kernels at runtime")

    def _exchange(self, node) -> _Flow:
        from ..physical.partitioning import (
            HashPartitioning, RangePartitioning, SinglePartition,
            UnknownPartitioning,
        )

        child = self.visit(node.child)
        p = node.partitioning
        kinds = Counter()
        notes = []
        fused = getattr(node, "pipe_fusion", None) is not None
        if isinstance(p, SinglePartition):
            merged = [b for part in child.parts for b in part]
            self._stage(node, kinds, child.total_batches if child.counted
                        else None, ["gather: no kernels"])
            return _Flow([merged], child.trace, counted=child.counted)
        if isinstance(p, HashPartitioning):
            if self._mesh_active(p.num_partitions):
                return self._mesh_exchange(node, child, p, fused, kinds,
                                           notes)
            self._map_side_kinds(node, child, fused,
                                 self._host_shuffle_kind(), kinds, notes)
            self._sync("host sort-shuffle pulls grouped columns to host "
                       "once per batch (by design: the DCN path)")
            seeded = self._exchange_seeded(node)
            flow = None
            in_traces = self._exchange_input_traces(node, child, fused)
            key_ids = [e.expr_id for e in p.exprs
                       if isinstance(e, AttributeReference)]
            if in_traces is not None and len(key_ids) == len(p.exprs) \
                    and all(k in t.cols for t in in_traces
                            for k in key_ids):
                pids_per_part = []
                for t in in_traces:
                    tc = t.compacted()
                    pids_per_part.append(_np_hash_pids(
                        [tc.cols[k] for k in key_ids], p.num_partitions))
                flow = self._shuffled_flow(in_traces, pids_per_part,
                                           p.num_partitions, seeded,
                                           child.parts)
                notes.append("reduce layout EXACT: host-side splitmix64 "
                             "of the traced keys decides per-reducer rows")
            if flow is None:
                self._approx("hash exchange reduce layout untraced (key "
                             "values unknown): downstream counts are "
                             "approximate")
                flow = _Flow([[_Batch(None, None, False, seeded=seeded)]
                              for _ in range(p.num_partitions)], None,
                             counted=False)
            self._stage(node, kinds, child.total_batches if child.counted
                        else None, notes)
            return flow
        if isinstance(p, RangePartitioning):
            self._map_side_kinds(node, child, fused, "shuffle_range",
                                 kinds, notes)
            if fused and child.counted:
                # post-pipeline bound sampling materializes the pipeline
                # for ≤3 spread batches per partition
                kinds["pipeline"] += sum(min(3, len(pp))
                                         for pp in child.parts)
                notes.append("fused range bounds sample the POST-pipeline "
                             "key column (≤3 materialized batches per "
                             "partition)")
            self._approx("range exchange: sampled bounds may collapse to a "
                         "single gather (data-dependent)")
            self._sync("range-bound sampling reads per-batch samples "
                       "host-side (fused: fresh pipeline outputs each "
                       "run; unfused: memoized per column identity)")
            out = [[_Batch(None, None, False,
                           seeded=self._exchange_seeded(node))]
                   for _ in range(p.num_partitions)]
            self._stage(node, kinds, child.total_batches if child.counted
                        else None, notes)
            return _Flow(out, None, counted=False)
        if isinstance(p, UnknownPartitioning):
            self._map_side_kinds(node, child, fused, "shuffle_rr", kinds,
                                 notes)
            seeded = self._exchange_seeded(node)
            # the running row offset rides as a kernel argument, so the
            # cache key is (capacity, num_out)-shaped — no recompile
            # hazard (the historical storm keyed by start % num_out;
            # fixed alongside this model)
            notes.append("round-robin start offset rides as a kernel "
                         "argument: one compile per capacity bucket, "
                         "1 launch/batch")
            flow = None
            in_traces = self._exchange_input_traces(node, child, fused)
            if in_traces is not None:
                offset = 0
                pids_per_part = []
                for t in in_traces:
                    n = int(t.live.sum())
                    pids_per_part.append(
                        ((np.arange(n) + offset) % p.num_partitions)
                        .astype(np.int32))
                    offset += n
                flow = self._shuffled_flow(in_traces, pids_per_part,
                                           p.num_partitions, seeded,
                                           child.parts)
                notes.append("reduce layout EXACT: round-robin over the "
                             "traced live-row order")
            if flow is None:
                flow = _Flow([[_Batch(None, None, False, seeded=seeded)]
                              for _ in range(p.num_partitions)], None,
                             counted=False)
            self._stage(node, kinds, child.total_batches if child.counted
                        else None, notes)
            return flow
        self._approx(f"exchange over {type(p).__name__} not modeled")
        return _Flow([[_Batch(None, None, False)]], None, counted=False)

    @staticmethod
    def _host_shuffle_kind() -> str:
        try:
            from ..utils.native import radix_partition  # noqa: F401

            return "shuffle_pids"
        except Exception:
            return "shuffle_hash"

    # -- misc --------------------------------------------------------------
    def _union(self, node) -> _Flow:
        parts = []
        counted = True
        for c in node.children_plans:
            f = self.visit(c)
            parts.extend(f.parts)
            counted = counted and f.counted
        self._stage(node, Counter(), None, ["no kernels: rewraps batches"])
        return _Flow(parts, None, counted=counted)

    def _coalesce(self, node) -> _Flow:
        child = self.visit(node.child)
        n = max(1, min(node.num_partitions, max(len(child.parts), 1)))
        out = [[] for _ in range(n)]
        for i, p in enumerate(child.parts):
            out[i % n].extend(p)
        self._stage(node, Counter(), child.total_batches if child.counted
                    else None, ["no kernels"])
        return _Flow(out, child.trace if len(child.parts) <= 1 else None,
                     counted=child.counted)

    def _sample(self, node) -> _Flow:
        child = self.visit(node.child)
        kinds = Counter()
        if child.counted:
            kinds["sample"] = child.total_batches
        parts = [[_Batch(b.rows, b.cap, False) for b in p]
                 for p in child.parts]
        # the per-(partition,batch) position base is a kernel INPUT, so
        # one compiled kernel per (capacity, seed, fraction) serves every
        # batch — no recompile hazard (the historical storm keyed by
        # batch indices; fixed alongside this model)
        self._stage(node, kinds, child.total_batches if child.counted
                    else None,
                    ["sample offset rides as a kernel argument: one "
                     "compile per capacity bucket, 1 launch/batch"])
        return _Flow(parts, None, counted=child.counted)

    # -- python UDF evaluation ---------------------------------------------
    def _python_eval(self, node) -> _Flow:
        """PythonEvalExec launch model: one argument-pipeline dispatch per
        batch per UDF (the UDF itself runs host-side — zero kernel
        launches); the output batch wraps the SAME input columns plus one
        fresh host-built column, so identity/seed/RunInfo metadata and the
        value trace all pass through (the UDF column stays untraced)."""
        child = self.visit(node.child)
        kinds = Counter()
        notes = []
        nudf = len(node.udf_aliases)
        if child.counted:
            kinds["pipeline"] = nudf * child.total_batches
        else:
            self._approx("python UDF argument-pipeline launches depend on "
                         "an unknown upstream batch count")
        self._sync("python UDFs pull live argument rows to host once per "
                   "batch (by design: host evaluation)")
        if self._encoding:
            for al in node.udf_aliases:
                udf = al.child
                args = getattr(udf, "args", [])
                if len(args) == 1 \
                        and isinstance(args[0], AttributeReference) \
                        and isinstance(args[0].dtype, StringType) \
                        and getattr(udf, "deterministic", True):
                    notes.append(
                        f"{getattr(udf, 'fname', 'udf')}: dictionary-"
                        "domain lane — the UDF evaluates once per "
                        "DISTINCT value of its dictionary-encoded string "
                        "argument and maps over codes (per-row only when "
                        "the domain is not smaller than the live rows)")
                    break
        parts = [[_Batch(b.rows, b.cap, b.stable, seeded=b.seeded,
                         ingest=b.ingest) for b in p]
                 for p in child.parts]
        self._stage(node, kinds, child.total_batches if child.counted
                    else None, notes)
        return _Flow(parts, child.trace, counted=child.counted,
                     ptraces=child.ptraces)

    # -- whole-query tier ---------------------------------------------------
    def _whole_query(self, node) -> _Flow:
        """Launch model of the whole-query tier (physical/whole_query.py):
        the ENTIRE plan is ONE jitted program — leaves execute launch-free
        (device-cached ingest), exchanges lower to in-program gathers, and
        the only dispatches are the program itself plus one re-dispatch
        per join output-capacity retry round. The mirror walks the inner
        plan with the single-flow layout (gathered capacities) and the
        value model to predict the retry count EXACTLY when the join keys
        trace; memory is the fully-resident sum of every lowered
        operator's tile plus the leaf input planes."""
        from ..exec.memory import schema_row_bytes
        from ..physical import operators as O
        from ..physical.exchange import (
            BroadcastExchangeExec, ShuffleExchangeExec,
        )
        from ..physical.fusion import FusedAggregateExec, FusedLimitExec
        from ..physical.operators import attrs_schema

        kinds = Counter()
        notes = []
        dec = getattr(node, "decision", None)
        if dec is not None:
            self.report.tier = dec.to_dict()
            notes.append(f"tier decision: {dec.reason}")
        hbm = [0]
        untraced = [False]
        # retry-loop state shared across simulation rounds: per-join
        # output capacities in lowering order, exactly as the runtime's
        # join_caps list evolves. A persistent warm-start seed
        # (exec/persist_cache.py manifest, same lookup the runtime
        # performs) pre-populates the list — the seeded first attempt is
        # the prior run's FINAL program, so its retry rounds collapse.
        seed_rec = self._persist_seed_record() or {}
        seed_caps = seed_rec.get("join_caps") or ()
        caps_state: dict[int, int] = {i: int(c)
                                      for i, c in enumerate(seed_caps)}
        # dense direct-address probe state (warm-start span seed): which
        # joins compile the dense 1:1 variant up front, and which turned
        # it off after the in-program guard fired — one retry round each,
        # exactly the runtime's dense_off escalation
        spans_seed = seed_rec.get("join_spans") or None
        dense_off: set = set()
        dense_used = [False]
        round_state = {"seq": 0, "overflow": [], "guards": []}

        def mem(n, cap, extra_planes: int = 0):
            try:
                rb = schema_row_bytes(attrs_schema(n.output))
            except Exception:
                rb = 16
                self._mem_approx(f"{type(n).__name__}: output schema "
                                 "unavailable — 16 B/row assumed")
            hbm[0] += (cap + extra_planes) * rb

        def walk(n):
            """(gathered cap, value trace | None) of the lowered flow."""
            if isinstance(n, O.LocalTableScanExec):
                rows, trace = self._table_trace(n)
                caps = [b.cap for b in self._batches_for_rows(rows)]
                cap = bucket_capacity(max(sum(caps), 1))
                mem(n, cap, extra_planes=sum(caps))
                return cap, trace
            if isinstance(n, O.ScanExec):
                from ..physical.whole_query import (
                    _external_scan_rows, _scan_table,
                )

                t = _scan_table(n)
                if t is None:
                    # parquet-stats admission (spark.tpu.adaptive.
                    # parquetStats): footer row-group counts give the
                    # exact layout without reading data — only the
                    # VALUES stay untraced
                    rows = _external_scan_rows(n)
                    if rows is not None:
                        self._approx(
                            f"whole-query external scan [{n.name}]: "
                            "footer statistics model the layout, values "
                            "untraced")
                        caps = [c for tiles in self._part_tiles(
                            rows, n.source.num_partitions())
                            for _r, c in tiles]
                        cap = bucket_capacity(max(sum(caps), 1))
                        mem(n, cap, extra_planes=sum(caps))
                        return cap, None
                    self._approx("whole-query leaf layout unknown "
                                 f"(external scan [{n.name}])")
                    return self._tile, None
                caps = [c for tiles in self._part_tiles(
                    t.num_rows, n.source.num_partitions())
                    for _r, c in tiles]
                cap = bucket_capacity(max(sum(caps), 1))
                _rows2, trace = self._arrow_trace(t, n.attrs)
                mem(n, cap, extra_planes=sum(caps))
                return cap, trace
            if isinstance(n, O.RangeExec):
                step = n.step
                total = max(0, -(-(n.end - n.start) // step)) if step > 0 \
                    else max(0, -(-(n.start - n.end) // -step))
                caps = [c for tiles in self._part_tiles(
                    total, n.num_partitions) for _r, c in tiles]
                cap = bucket_capacity(max(sum(caps), 1))
                trace = None
                if 0 < total <= _TRACE_MAX_ROWS:
                    vals = n.start + np.arange(total, dtype=np.int64) * step
                    trace = _Trace({n.attr.expr_id: (vals, None)},
                                   np.ones(total, bool))
                mem(n, cap, extra_planes=sum(caps))
                return cap, trace
            if isinstance(n, O.ComputeExec):
                cap, tr = walk(n.child)
                mem(n, cap)
                return cap, self._project_trace(tr, n.filters, n.outputs)
            if isinstance(n, ShuffleExchangeExec):
                cap, tr = walk(n.child)
                if n.pipe_fusion is not None:
                    f_, o_ = n.pipe_fusion
                    tr = self._project_trace(tr, f_, o_)
                    mem(n, cap)
                return cap, tr
            if isinstance(n, (BroadcastExchangeExec,
                              O.CoalescePartitionsExec)):
                return walk(n.child)
            if isinstance(n, FusedAggregateExec):
                cap, _tr = walk(n.child)
                out_cap = cap if n.grouping else 8
                mem(n, out_cap)
                return out_cap, None
            if isinstance(n, O.HashAggregateExec):
                cap, _tr = walk(n.child)
                out_cap = cap if n.grouping else 8
                mem(n, out_cap)
                return out_cap, None
            if isinstance(n, (FusedLimitExec, O.LimitExec, O.SortExec)):
                cap, _tr = walk(n.child)
                mem(n, cap)
                return cap, None
            if isinstance(n, O.UnionExec):
                pairs = [walk(c) for c in n.children_plans]
                cap = bucket_capacity(max(sum(c for c, _ in pairs), 1))
                traces = [t for _, t in pairs]
                tr = self._merge_group_traces(traces) \
                    if all(t is not None for t in traces) else None
                mem(n, cap)
                return cap, tr
            if isinstance(n, O.HashJoinExec):
                pcap, ptr = walk(n.left)
                if n.probe_fusion is not None:
                    f_, o_ = n.probe_fusion
                    ptr = self._project_trace(ptr, f_, o_)
                bcap, btr = walk(n.right)
                jid = round_state["seq"]
                round_state["seq"] += 1
                out_cap = caps_state.setdefault(jid, max(pcap, 1 << 10))
                dense = self._whole_dense_span(jid, bcap, spans_seed,
                                               dense_off) \
                    if self._whole_dense_eligible(n) else None
                if dense is not None:
                    # dense direct-address probe (runtime _join_dense):
                    # 1:1 with the probe plane, no expansion buffer —
                    # the join cap never binds, but the in-program span/
                    # dup guard may disable it for the next round
                    dense_used[0] = True
                    guard, out_tr = self._whole_dense_mirror(
                        n, ptr, btr, *dense)
                    if guard is None:
                        untraced[0] = True
                    elif guard:
                        round_state["guards"].append(jid)
                    mem(n, pcap)
                    return pcap, out_tr
                needed = self._whole_join_needed(n, ptr, btr)
                if needed is None:
                    untraced[0] = True
                elif needed > out_cap:
                    round_state["overflow"].append(
                        (jid, bucket_capacity(needed)))
                mem(n, out_cap)
                out_tr = self._whole_join_trace(n, ptr, btr)
                if out_tr is not None and needed is not None \
                        and needed > out_cap:
                    # the failed attempt TRUNCATES at the output bucket:
                    # downstream joins of this round see the prefix (the
                    # kernel fills output slots probe-major, within a
                    # probe row's block in original build-row order —
                    # exactly this expansion's order)
                    if n.join_type == "inner" \
                            and len(out_tr.live) >= out_cap:
                        sel = np.arange(out_cap)
                        out_tr = out_tr.select(sel, True)
                    else:
                        untraced[0] = True
                        out_tr = None
                return out_cap, out_tr
            # admission should prevent this; degrade honestly
            self._approx(f"whole-query mirror missing for "
                         f"{type(n).__name__}")
            return self._tile, None

        # mirror of WholeQueryExec.execute's retry loop: each round
        # re-walks with the bumped capacities; truncated upstream traces
        # make the observed `needed` of cascading joins exact too. The
        # memory model keeps the LAST round's accumulation — the peak
        # attempt runs with the bumped join output buckets
        attempts = 0
        out_cap, out_tr = self._tile, None
        while attempts < 8:
            attempts += 1
            round_state["seq"] = 0
            round_state["overflow"] = []
            round_state["guards"] = []
            hbm[0] = 0
            out_cap, out_tr = walk(node.plan)
            if untraced[0] or not (round_state["overflow"]
                                   or round_state["guards"]):
                break
            for jid, newcap in round_state["overflow"]:
                caps_state[jid] = newcap
            for jid in round_state["guards"]:
                dense_off.add(jid)
        if untraced[0]:
            self._approx("whole-query join output capacity untraced (key "
                         "values outside the traced language): retry "
                         "dispatches unpredictable")
        if attempts > 1:
            notes.append(
                f"{attempts - 1} capacity "
                f"retr{'y' if attempts == 2 else 'ies'}: a join "
                "overflowed its output bucket (or a dense-probe guard "
                "fired) and the whole program re-dispatched with the "
                "bumped capacity")
        if dense_used[0]:
            notes.append("dense direct-address probe compiled up front "
                         "from the warm-start key-span seed (1:1 with "
                         "the probe plane, no expansion buffer), "
                         "guarded in-program")
        kinds["whole_query"] = attempts
        notes.insert(0, "WHOLE-QUERY program: all stages in ONE jitted "
                        "dispatch per step — exchanges lowered to "
                        "in-program gathers, intermediates never leave "
                        "HBM, zero host shuffle round-trips")
        self._sync("whole-query join capacity verdicts sync once after "
                   "the single dispatch (the query's last device "
                   "interaction before collect)")
        self._hazard("whole-query join output capacities are "
                     "value-dependent program-key components — match "
                     "growth recompiles the whole program")
        self._stage(node, kinds, 1, notes)
        ent = self._stage_by_node.get(id(node))
        if ent is not None and "hbm_bytes" not in ent:
            ent["hbm_bytes"] = hbm[0]
            self._hbm_total += hbm[0]
            self._hbm_any = True
        return _Flow([[_Batch(None, out_cap, False)]], out_tr,
                     counted=True)

    def _whole_join_needed(self, node, ptr, btr):
        """Mirror of ops/joining.probe_join's `needed` scalar over the
        single gathered flow: per live probe row, the count of verified
        build matches (semi/anti/outer reserve >= 1 slot per live row).
        None when the keys/values are outside the traced language."""
        if len(node.left_keys) != 1 or ptr is None or btr is None:
            return None
        pent = ptr.cols.get(node.left_keys[0].expr_id)
        bstats = btr.stats(node.right_keys[0].expr_id)
        if pent is None or bstats is None:
            return None
        pv, pvalid = pent
        live = ptr.live
        usable = live if pvalid is None else (live & pvalid)
        counts = np.zeros(len(pv), np.int64)
        if bstats.size:
            bvals, bcounts = np.unique(bstats, return_counts=True)
            if pv.dtype == object or bvals.dtype == object:
                cmap = {v: int(c) for v, c in zip(bvals.tolist(),
                                                  bcounts.tolist())}
                counts = np.array([cmap.get(x, 0) for x in pv.tolist()],
                                  np.int64)
            else:
                idx = np.clip(np.searchsorted(bvals, pv), 0,
                              len(bvals) - 1)
                counts = np.where(bvals[idx] == pv, bcounts[idx],
                                  0).astype(np.int64)
        counts = np.where(usable, counts, 0)
        if node.join_type != "inner":
            counts = np.maximum(counts, live.astype(np.int64))
        return int(counts.sum())

    def _whole_join_trace(self, node, ptr, btr):
        """Value trace through an in-program join (whole-query mirror):
        the output MULTISET of probe AND build columns — semi/anti select
        probe rows; inner joins expand fully (each live usable probe row
        repeats once per matching build row, duplicate build keys
        included); left_outer maps 1:1 when the build key is unique.
        Downstream whole-query consumers only SUM over these traces
        (further join `needed` counts), so within-group ordering need not
        mirror the kernel's hash-sorted layout."""
        jt = node.join_type
        if ptr is None or btr is None or len(node.left_keys) != 1:
            return None
        pent = ptr.cols.get(node.left_keys[0].expr_id)
        bent = btr.cols.get(node.right_keys[0].expr_id)
        if pent is None or bent is None:
            return None
        pv, pvalid = pent
        live = ptr.live
        usable = live if pvalid is None else (live & pvalid)
        bvals_all, bvalid_all = bent
        blive = btr.live if bvalid_all is None \
            else (btr.live & bvalid_all)
        bsel = np.nonzero(blive)[0]
        bkeys = bvals_all[bsel]

        def probe_only(sel):
            cols = {k: (v[sel], None if vv is None else vv[sel])
                    for k, (v, vv) in ptr.cols.items()}
            return _Trace(cols, np.ones(len(sel), bool), True,
                          dict(ptr.dict_domains), False)

        if jt in ("left_semi", "left_anti"):
            matched = usable & np.isin(pv, bkeys)
            sel_mask = matched if jt == "left_semi" \
                else (live & ~matched)
            return probe_only(np.nonzero(sel_mask)[0])
        if jt == "left_outer":
            if np.unique(bkeys).size != bkeys.size:
                return None  # dup-build outer expansion: layout unclear
            sel = np.nonzero(live)[0]
            out = probe_only(sel)
            # 1:1 build-column mapping: matched rows gather the build
            # row, unmatched rows read NULL
            order = np.argsort(bkeys, kind="stable")
            bs = bkeys[order]
            pos = np.clip(np.searchsorted(bs, pv[sel]), 0,
                          max(len(bs) - 1, 0))
            hit = (len(bs) > 0) & usable[sel]
            if len(bs):
                hit = hit & (bs[pos] == pv[sel])
            pick = bsel[order][pos] if len(bs) else np.zeros(len(sel), int)
            for k, (bv, bvv) in btr.cols.items():
                vals = bv[pick] if len(bs) else np.zeros(len(sel),
                                                         bv.dtype)
                valid = np.asarray(hit, bool).copy()
                if bvv is not None and len(bs):
                    valid &= bvv[pick]
                out.cols.setdefault(k, (vals, valid))
            return out
        if jt != "inner":
            return None
        # inner: full expansion over sorted build keys
        order = np.argsort(bkeys, kind="stable")
        bs = bkeys[order]
        lo = np.searchsorted(bs, pv, side="left")
        hi = np.searchsorted(bs, pv, side="right")
        counts = np.where(usable, hi - lo, 0).astype(np.int64)
        total = int(counts.sum())
        src = np.repeat(np.arange(len(pv)), counts)
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        within = np.arange(total) - starts
        offs = np.repeat(lo, counts) + within
        pick = bsel[order][offs] if total else np.zeros(0, int)
        cols = {k: (v[src], None if vv is None else vv[src])
                for k, (v, vv) in ptr.cols.items()}
        for k, (bv, bvv) in btr.cols.items():
            cols.setdefault(k, (bv[pick],
                                None if bvv is None else bvv[pick]))
        return _Trace(cols, np.ones(total, bool), True,
                      dict(ptr.dict_domains), False)

    def _whole_dense_eligible(self, node) -> bool:
        """Mirror of whole_query._dense_eligible: single plain
        integral/date equi-key on both sides with the dense fast path
        enabled — the shape that CAN compile the direct-address probe."""
        if len(node.left_keys) != 1 or len(node.right_keys) != 1:
            return False
        if not self._dense_keys:
            return False
        return all(isinstance(k.dtype, (IntegralType, DateType))
                   for k in (node.left_keys[0], node.right_keys[0]))

    @staticmethod
    def _whole_dense_span(join_id, build_cap, spans_seed, dense_off):
        """Mirror of whole_query._dense_span: the seeded [lo, hi] span
        when the manifest proves last run's build keys were unique and
        dense enough (and an in-program guard hasn't disabled it)."""
        if spans_seed is None or join_id in dense_off:
            return None
        if join_id >= len(spans_seed):
            return None
        sp = spans_seed[join_id]
        if not sp or len(sp) < 3 or not int(sp[2]):
            return None
        lo, hi = int(sp[0]), int(sp[1])
        span = hi - lo + 1
        if span <= 0 or span > min(8 * build_cap, 1 << 23):
            return None
        return lo, hi

    def _whole_dense_mirror(self, node, ptr, btr, lo, hi):
        """(guard fired, output trace) of whole_query._join_dense — the
        faithful value mirror INCLUDING the drift modes: when the guard
        fires, the round's runtime output is the drifted dense result
        (out-of-span matches missing, duplicate keys last-writer), and
        downstream verdicts of that failed round observe exactly it.
        (None, None) when the keys are outside the traced language."""
        if ptr is None or btr is None:
            return None, None
        pent = ptr.cols.get(node.left_keys[0].expr_id)
        bent = btr.cols.get(node.right_keys[0].expr_id)
        if pent is None or bent is None:
            return None, None
        tcap = bucket_capacity(hi - lo + 1)
        bv_, bvv_ = bent
        blive = btr.live if bvv_ is None else (btr.live & bvv_)
        bk = bv_.astype(np.int64)
        bsel = np.nonzero(blive)[0]
        guard = False
        present = np.zeros(tcap, np.int64)
        rowidx = np.zeros(tcap, np.int64)
        if len(bsel):
            ks = bk[bsel]
            if int(ks.min()) < lo or int(ks.max()) > hi:
                guard = True
            slot = ks - lo
            ok = (slot >= 0) & (slot < tcap)
            np.add.at(present, slot[ok], 1)
            if int(present.max()) > 1:
                guard = True
            # scatter-set semantics: among colliding writes the mirror
            # keeps the last in row order (collisions imply guard anyway)
            rowidx[slot[ok]] = bsel[ok]
        pv_, pvv_ = pent
        live = ptr.live
        pk = pv_.astype(np.int64) - lo
        in_range = (pk >= 0) & (pk < tcap)
        pslot = np.clip(pk, 0, tcap - 1)
        usable = live & in_range
        if pvv_ is not None:
            usable = usable & pvv_
        matched = usable & (present[pslot] > 0)
        bidx = rowidx[pslot]
        jt = node.join_type
        if jt in ("inner", "left_semi"):
            out_live = matched
        elif jt == "left_outer":
            out_live = live.copy()
        else:  # left_anti
            out_live = live & ~matched
        cols = dict(ptr.cols)
        if jt not in ("left_semi", "left_anti"):
            for k, (bvx, bvvx) in btr.cols.items():
                base = np.ones(len(bidx), bool) if bvvx is None \
                    else bvvx[bidx]
                cols.setdefault(k, (bvx[bidx], base & matched))
        return guard, _Trace(cols, out_live, True,
                             dict(ptr.dict_domains), False)

    # -- mesh whole-query tier ----------------------------------------------
    def _mesh_whole(self, node) -> _Flow:
        """Launch model of the mesh whole-query tier
        (physical/mesh_whole.py): the ENTIRE sharded plan is ONE
        shard_map program — leaf planes stage row-sharded over the mesh,
        hash exchanges lower to in-program all_to_alls with the per-stage
        mesh path's quota/overflow contract, reduce-side consumers fold
        in behind the collective on the sharded layouts, and the only
        dispatches are the program itself plus one re-dispatch per retry
        round (join capacity bumps, DOUBLED exchange quotas and
        dense-guard fallbacks — all of a round's verdicts applied
        together, mirroring the runtime's single post-dispatch check).
        The mirror walks the inner plan per shard with the staged-shard
        value model, so {mesh_whole: attempts} is EXACT when the key
        values trace."""
        from ..exec.memory import schema_row_bytes
        from ..exec.persist_cache import mesh_quota_key
        from ..parallel.mesh_fusion import mesh_stage_geometry
        from ..physical import operators as O
        from ..physical.exchange import (
            BroadcastExchangeExec, ShuffleExchangeExec,
        )
        from ..physical.fusion import FusedAggregateExec, FusedLimitExec
        from ..physical.operators import attrs_schema
        from ..physical.partitioning import HashPartitioning
        from ..physical.whole_query import _scan_table

        kinds = Counter()
        notes = []
        dec = getattr(node, "decision", None)
        if dec is not None:
            self.report.tier = dec.to_dict()
            notes.append(f"tier decision: {dec.reason}")
        P = int((dec.details or {}).get("mesh_devices") or 0) \
            if dec is not None else 0
        if P < 2:
            self._approx("mesh-whole mirror: mesh axis unknown on the "
                         "tier decision")
            kinds["mesh_whole"] = 1
            self._stage(node, kinds, 1, notes)
            return _Flow([[_Batch(None, None, False)]], None,
                         counted=True)
        seed_rec = self._persist_seed_record() or {}
        seed_caps = seed_rec.get("join_caps") or ()
        caps_state: dict = {i: int(c) for i, c in enumerate(seed_caps)}
        spans_seed = seed_rec.get("join_spans") or None
        mesh_seed = seed_rec.get("mesh_quotas") or {}
        # persistent across rounds, exactly like the builder's state:
        # per-exchange live quotas (init once from geometry + manifest
        # seed at the FIRST round's staging caps, doubled on overflow)
        # and per-join dense disablement after a guard fired
        quota_state: dict = {}
        dense_off: set = set()
        hbm = [0]
        untraced = [False]
        dense_used = [False]
        partial_merged: set = set()
        rs = {"jseq": 0, "xseq": 0, "cap_over": [], "quota_over": [],
              "guards": []}

        def mem(n, cap, extra_planes: int = 0):
            # per-shard tile x row bytes x P shards (replicated flows
            # hold the full gathered tile on EVERY shard — same scale)
            try:
                rb = schema_row_bytes(attrs_schema(n.output))
            except Exception:
                rb = 16
                self._mem_approx(f"{type(n).__name__}: output schema "
                                 "unavailable — 16 B/row assumed")
            hbm[0] += (cap + extra_planes) * rb * P

        # flow states mirror the builder's forms:
        #   ("shard", per-shard cap, [P traces] | None, part_ids)
        #   ("rep",   gathered cap,  trace | None)
        def to_rep(st):
            if st[0] == "rep":
                return st
            _f, cap, trs, _p = st
            out_cap = cap * P
            if trs is None or any(t is None for t in trs):
                return ("rep", out_cap, None)
            ids = set(trs[0].cols)
            for t in trs[1:]:
                ids &= set(t.cols)
            cols = {}
            for k in ids:
                has_valid = any(t.cols[k][1] is not None for t in trs)
                vals = np.concatenate([t.cols[k][0] for t in trs])
                valid = None
                if has_valid:
                    valid = np.concatenate(
                        [np.ones(len(t.live), bool)
                         if t.cols[k][1] is None else t.cols[k][1]
                         for t in trs])
                cols[k] = (vals, valid)
            live = np.concatenate([t.live for t in trs])
            return ("rep", out_cap,
                    _Trace(cols, live, True,
                           dict(trs[0].dict_domains), False))

        def pipe(st, filters, outputs):
            if st[0] == "rep":
                tr = None if st[2] is None \
                    else self._project_trace(st[2], filters, outputs)
                return ("rep", st[1], tr)
            _f, cap, trs, pids_t = st
            out = None if trs is None else [
                None if t is None
                else self._project_trace(t, filters, outputs)
                for t in trs]
            return ("shard", cap, out, pids_t)

        def leaf_layout(n):
            """([(rows, cap)] tiles, execution order; global trace)."""
            if isinstance(n, O.LocalTableScanExec):
                rows, trace = self._table_trace(n)
                return [(b.rows, b.cap)
                        for b in self._batches_for_rows(rows)], trace
            if isinstance(n, O.ScanExec):
                t = _scan_table(n)
                if t is None:
                    return None, None
                _r, trace = self._arrow_trace(t, n.attrs)
                tiles = [rc for part in self._part_tiles(
                    t.num_rows, n.source.num_partitions())
                    for rc in part]
                return tiles, trace
            if isinstance(n, O.RangeExec):
                step = n.step
                total = max(0, -(-(n.end - n.start) // step)) \
                    if step > 0 \
                    else max(0, -(-(n.start - n.end) // -step))
                tiles = [rc for part in self._part_tiles(
                    total, n.num_partitions) for rc in part]
                trace = None
                if 0 < total <= _TRACE_MAX_ROWS:
                    vals = n.start + np.arange(total,
                                               dtype=np.int64) * step
                    trace = _Trace({n.attr.expr_id: (vals, None)},
                                   np.ones(total, bool))
                return tiles, trace
            return None, None

        def leaf_walk(n):
            """Mirror of _stage_leaf_host + _lower_mesh_leaf: flatten
            the leaf's batches to [total_cap] planes (rows-first per
            batch capacity slot), pad to P*rps, slice per shard."""
            tiles, trace = leaf_layout(n)
            if tiles is None:
                self._approx("mesh-whole leaf layout unknown "
                             f"({type(n).__name__})")
                untraced[0] = True
                return ("shard", self._tile, None, ())
            total_cap = max(sum(c for _r, c in tiles), 1)
            rps = max(-(-total_cap // P), 1)
            mem(n, rps, extra_planes=rps)
            if trace is None and any(r for r, _c in tiles):
                return ("shard", rps, None, ())
            plane = P * rps
            glive = np.zeros(plane, bool)
            cols = {} if trace is None else trace.cols
            gcols = {}
            for k, (v, vv) in cols.items():
                base = np.full(plane, "", dtype=object) \
                    if v.dtype == object else np.zeros(plane, v.dtype)
                gcols[k] = [base,
                            np.zeros(plane, bool)
                            if vv is not None else None]
            off = r0 = 0
            for rows_b, cap_b in tiles:
                if rows_b:
                    glive[off:off + rows_b] = True
                    for k, (v, vv) in cols.items():
                        gcols[k][0][off:off + rows_b] = v[r0:r0 + rows_b]
                        if gcols[k][1] is not None:
                            gcols[k][1][off:off + rows_b] = \
                                vv[r0:r0 + rows_b]
                off += cap_b
                r0 += rows_b
            doms = {}
            for k, (v, _vv) in cols.items():
                if v.dtype == object:
                    d = self._trace_domain(trace, k)
                    if d is not None:
                        doms[k] = d
            strs = []
            for s in range(P):
                sl = slice(s * rps, (s + 1) * rps)
                strs.append(_Trace(
                    {k: (gv[sl], None if gvv is None else gvv[sl])
                     for k, (gv, gvv) in gcols.items()},
                    glive[sl], True, dict(doms), False))
            return ("shard", rps, strs, ())

        def exchange_a2a(n, st, key_ids):
            """Mirror of _exchange_all_to_all / _exchange_tail: per
            (src, dst) keep the FIRST `quota` live rows in row order —
            truncation happens EVERY dispatch, the psum'd overflow
            scalar only reports it for the host's doubling verdict."""
            _f, cap, trs, _p = st
            xid = rs["xseq"]
            rs["xseq"] += 1
            q = quota_state.get(xid)
            if q is None:
                pos = {a.expr_id: i for i, a in enumerate(n.output)}
                kidx = tuple(pos[e.expr_id]
                             for e in n.partitioning.exprs)
                sig = "|".join(str(a.dtype) for a in n.output)
                mkey = mesh_quota_key("w", P, cap,
                                      f"x{xid}:k{kidx}:s{sig}")
                q = mesh_stage_geometry(P * cap, P)[2]
                seed = mesh_seed.get(mkey)
                if seed and int(seed) > q:
                    q = int(seed)
                quota_state[xid] = q
            out_cap = P * q
            mem(n, out_cap)
            if trs is None or any(t is None for t in trs):
                untraced[0] = True
                return ("shard", out_cap, None, key_ids)
            ids = set(trs[0].cols)
            for t in trs[1:]:
                ids &= set(t.cols)
            sent = [[] for _ in range(P)]   # per dst: (trace, sel) rows
            overflow = False
            for t in trs:
                live_idx = np.nonzero(t.live)[0]
                if not len(live_idx):
                    for qd in range(P):
                        sent[qd].append((t, live_idx))
                    continue
                if any(k not in t.cols for k in key_ids):
                    untraced[0] = True
                    return ("shard", out_cap, None, key_ids)
                pids = _np_hash_pids([t.cols[k] for k in key_ids], P)
                pl = pids[live_idx]
                for qd in range(P):
                    sel = live_idx[pl == qd]
                    if len(sel) > q:
                        overflow = True
                        sel = sel[:q]
                    sent[qd].append((t, sel))
            if overflow:
                rs["quota_over"].append(xid)
            out_trs = []
            for qd in range(P):
                cols_q = {}
                for k in ids:
                    has_valid = any(t.cols[k][1] is not None
                                    for t, _s in sent[qd])
                    vals = np.concatenate(
                        [t.cols[k][0][sel] for t, sel in sent[qd]])
                    valid = None
                    if has_valid:
                        valid = np.concatenate(
                            [np.ones(len(sel), bool)
                             if t.cols[k][1] is None
                             else t.cols[k][1][sel]
                             for t, sel in sent[qd]])
                    cols_q[k] = (vals, valid)
                nrows = sum(len(sel) for _t, sel in sent[qd])
                out_trs.append(_Trace(cols_q, np.ones(nrows, bool),
                                      True, dict(trs[0].dict_domains),
                                      False))
            return ("shard", out_cap, out_trs, key_ids)

        def exchange_local(n, st, key_ids):
            """Mirror of _exchange_local_filter: a hash exchange on a
            replicated flow keeps each shard's own pid rows — no
            collective, no quota, no overflow."""
            cap, tr = st[1], st[2]
            mem(n, cap)
            if tr is None:
                untraced[0] = True
                return ("shard", cap, None, key_ids)
            if any(k not in tr.cols for k in key_ids):
                if tr.live.any():
                    untraced[0] = True
                    return ("shard", cap, None, key_ids)
                pids = np.zeros(len(tr.live), np.int32)
            else:
                pids = _np_hash_pids([tr.cols[k] for k in key_ids], P)
            out_trs = [_Trace(dict(tr.cols), tr.live & (pids == s),
                              True, dict(tr.dict_domains), False)
                       for s in range(P)]
            return ("shard", cap, out_trs, key_ids)

        def register_merge(n):
            if getattr(n, "mode", "") != "final":
                return
            c = n.child
            while isinstance(c, (ShuffleExchangeExec,
                                 O.CoalescePartitionsExec)):
                c = c.child
            if isinstance(c, O.HashAggregateExec) \
                    and getattr(c, "mode", "") == "partial":
                partial_merged.add(id(c))

        def agg_out_trace(n, t):
            """Output key trace of an in-program aggregate: live groups
            in the per-stage layout model's order (valid keys ascending,
            the null group last). Single-key groupings only — this is
            what downstream a2a exchanges partition by."""
            if t is None or len(n.grouping) != 1:
                return None
            info = self._key_group_info(t, n.grouping[0].expr_id)
            if info is None:
                return None
            return self._agg_out_trace(n.grouping[0].expr_id, *info)

        def agg_walk(n, st):
            out_part = None
            if st[0] == "shard":
                part_ids = st[3]
                gids = set(g.expr_id for g in n.grouping)
                co = bool(part_ids) and set(part_ids) <= gids
                if getattr(n, "mode", "") == "partial" \
                        and id(n) in partial_merged:
                    out_part = part_ids if (n.grouping and co) else ()
                elif n.grouping and co:
                    out_part = part_ids
                else:
                    st = to_rep(st)
            if st[0] == "shard":
                cap, trs = st[1], st[2]
                out_cap = cap if n.grouping else 8
                mem(n, out_cap)
                out_trs = None if trs is None \
                    else [agg_out_trace(n, t) for t in trs]
                return ("shard", out_cap, out_trs, out_part)
            cap, tr = st[1], st[2]
            out_cap = cap if n.grouping else 8
            mem(n, out_cap)
            return ("rep", out_cap, agg_out_trace(n, tr))

        def join_walk(n):
            pst = walk(n.left)
            if n.probe_fusion is not None:
                f_, o_ = n.probe_fusion
                pst = pipe(pst, f_, o_)
            bst = walk(n.right)
            lkeys = tuple(k.expr_id for k in n.left_keys)
            rkeys = tuple(k.expr_id for k in n.right_keys)
            sharded = pst[0] == "shard"
            if sharded:
                co = (bst[0] == "shard" and len(lkeys) > 0
                      and pst[3] == lkeys and bst[3] == rkeys)
                if bst[0] == "shard" and not co:
                    bst = to_rep(bst)
            elif bst[0] == "shard":
                bst = to_rep(bst)
            pcap, bcap = pst[1], bst[1]
            if sharded:
                pts = pst[2] if pst[2] is not None else [None] * P
                bts = (bst[2] if bst[2] is not None else [None] * P) \
                    if bst[0] == "shard" else [bst[2]] * P
            else:
                pts = [pst[2]]
                bts = [bst[2]]
            jid = rs["jseq"]
            rs["jseq"] += 1
            out_cap = caps_state.setdefault(jid, max(pcap, 1 << 10))
            dense = self._whole_dense_span(jid, bcap, spans_seed,
                                           dense_off) \
                if self._whole_dense_eligible(n) else None
            if dense is not None:
                # dense direct-address probe per shard: 1:1 with the
                # probe plane, the join cap never binds; the pmax'd
                # guard disables it for the next round on drift
                dense_used[0] = True
                out_cap = pcap
                mem(n, out_cap)
                guard_any = False
                out_trs = []
                for pt, bt in zip(pts, bts):
                    g, tr = self._whole_dense_mirror(n, pt, bt, *dense)
                    if g is None:
                        untraced[0] = True
                    else:
                        guard_any = guard_any or g
                    out_trs.append(tr)
                if guard_any:
                    rs["guards"].append(jid)
            else:
                mem(n, out_cap)
                needs = [self._whole_join_needed(n, pt, bt)
                         for pt, bt in zip(pts, bts)]
                out_trs = []
                if any(nd is None for nd in needs):
                    untraced[0] = True
                    out_trs = [None] * len(pts)
                else:
                    # the host reads the pmax'd `needed` — ONE bump
                    # covers every shard's worst case
                    nd_max = max(needs) if needs else 0
                    if nd_max > out_cap:
                        rs["cap_over"].append(
                            (jid, bucket_capacity(nd_max)))
                    for pt, bt, nd in zip(pts, bts, needs):
                        tr = self._whole_join_trace(n, pt, bt)
                        if tr is not None and nd > out_cap:
                            # this shard's failed attempt truncates at
                            # the bucket (probe-major fill order)
                            if n.join_type == "inner" \
                                    and len(tr.live) >= out_cap:
                                tr = tr.select(np.arange(out_cap), True)
                            else:
                                untraced[0] = True
                                tr = None
                        out_trs.append(tr)
            if sharded:
                return ("shard", out_cap, out_trs, pst[3])
            return ("rep", out_cap, out_trs[0])

        def walk(n):
            if isinstance(n, (O.LocalTableScanExec, O.RangeExec,
                              O.ScanExec)):
                return leaf_walk(n)
            if isinstance(n, FusedAggregateExec):
                register_merge(n)
                st = pipe(walk(n.child), n.filters, n.pipe_outputs)
                return agg_walk(n, st)
            if isinstance(n, O.HashAggregateExec):
                register_merge(n)
                return agg_walk(n, walk(n.child))
            if isinstance(n, FusedLimitExec):
                st = to_rep(walk(n.child))
                mem(n, st[1])
                return ("rep", st[1], None)
            if isinstance(n, (O.LimitExec, O.SortExec)):
                st = to_rep(walk(n.child))
                mem(n, st[1])
                return ("rep", st[1], None)
            if isinstance(n, O.HashJoinExec):
                return join_walk(n)
            if isinstance(n, O.ComputeExec):
                st = walk(n.child)
                mem(n, st[1])
                return pipe(st, n.filters, n.outputs)
            if isinstance(n, ShuffleExchangeExec):
                st = walk(n.child)
                if n.pipe_fusion is not None:
                    f_, o_ = n.pipe_fusion
                    st = pipe(st, f_, o_)
                    mem(n, st[1])
                p = n.partitioning
                if isinstance(p, HashPartitioning):
                    key_ids = tuple(e.expr_id for e in p.exprs)
                    if st[0] == "shard":
                        return exchange_a2a(n, st, key_ids)
                    return exchange_local(n, st, key_ids)
                return to_rep(st)
            if isinstance(n, BroadcastExchangeExec):
                return to_rep(walk(n.child))
            if isinstance(n, O.CoalescePartitionsExec):
                return walk(n.child)
            if isinstance(n, O.UnionExec):
                sts = [to_rep(walk(c)) for c in n.children_plans]
                cap = bucket_capacity(max(sum(s[1] for s in sts), 1))
                traces = [s[2] for s in sts]
                tr = self._merge_group_traces(traces) \
                    if all(t is not None for t in traces) else None
                mem(n, cap)
                return ("rep", cap, tr)
            # admission should prevent this; degrade honestly
            self._approx(f"mesh-whole mirror missing for "
                         f"{type(n).__name__}")
            untraced[0] = True
            return ("rep", self._tile, None)

        # mirror of MeshWholeQueryExec's retry loop: all of a round's
        # verdicts (pmax'd join `needed`s, psum'd exchange overflows,
        # pmax'd dense guards) are read together after the ONE dispatch
        # and applied together before the re-dispatch. The memory model
        # keeps the LAST round's accumulation
        attempts = 0
        final = ("rep", self._tile, None)
        while attempts < 8:
            attempts += 1
            rs["jseq"] = 0
            rs["xseq"] = 0
            rs["cap_over"] = []
            rs["quota_over"] = []
            rs["guards"] = []
            hbm[0] = 0
            partial_merged.clear()
            final = to_rep(walk(node.plan))
            if untraced[0]:
                break
            if not (rs["cap_over"] or rs["quota_over"] or rs["guards"]):
                break
            for jid, newcap in rs["cap_over"]:
                caps_state[jid] = newcap
            for xid in rs["quota_over"]:
                quota_state[xid] = quota_state[xid] * 2
            for jid in rs["guards"]:
                dense_off.add(jid)
        if untraced[0]:
            self._approx("mesh-whole verdicts untraced (key values "
                         "outside the traced language): retry "
                         "dispatches unpredictable")
        if attempts > 1:
            notes.append(
                f"{attempts - 1} retry round"
                f"{'' if attempts == 2 else 's'}: join capacity bumps, "
                "doubled exchange quotas and dense-guard fallbacks "
                "re-dispatch the whole program (all of a round's "
                "verdicts applied together; retries restage from the "
                "undonated base planes, never from host)")
        if dense_used[0]:
            notes.append("dense direct-address probe compiled up front "
                         "from the warm-start key-span seed (1:1 with "
                         "the probe plane, no expansion buffer), "
                         "guarded in-program")
        kinds["mesh_whole"] = attempts
        notes.insert(0, f"MESH WHOLE-QUERY program: the entire sharded "
                        f"plan as ONE shard_map dispatch per step over "
                        f"{P} devices — hash exchanges are in-program "
                        "all_to_alls, reduce consumers fold in behind "
                        "the collective, intermediates never leave HBM")
        self._sync("mesh-whole verdict scalars (pmax'd join `needed`s, "
                   "psum'd exchange overflows, dense guards) sync ONCE "
                   "after the single sharded dispatch")
        self._hazard("mesh-whole join output capacities and exchange "
                     "quotas are value-dependent program-key components "
                     "— growth recompiles the whole sharded program")
        self._stage(node, kinds, 1, notes)
        ent = self._stage_by_node.get(id(node))
        if ent is not None and "hbm_bytes" not in ent:
            ent["hbm_bytes"] = hbm[0]
            self._hbm_total += hbm[0]
            self._hbm_any = True
        return _Flow([[_Batch(None, final[1], False)]], final[2],
                     counted=True)

    def _unknown(self, node) -> _Flow:
        flows = [self.visit(c) for c in node.children]
        self._approx(f"{type(node).__name__}: no launch model — counts "
                     "below this operator are a lower bound")
        parts = flows[0].parts if flows else [[_Batch(None, None, False)]]
        self._stage(node, Counter(), None, ["no launch model"])
        return _Flow([[_Batch(None, None, False)] for _ in parts], None,
                     counted=False)

    # -- fusion boundary explanations -------------------------------------
    def _explain_boundaries(self, plan):
        from ..physical import operators as O
        from ..physical.exchange import ShuffleExchangeExec
        from ..physical.fusion import (
            FusedAggregateExec, FusedLimitExec, _compute_nontrivial,
        )
        from ..physical.aggregates import FUSABLE_OPS

        out = self.report.fusion_boundaries
        gate = (f"runtime gate: partitions under spark.tpu.fusion.minRows="
                f"{self._min_rows} tile rows take the shared unfused "
                "kernels (per-structure fused compiles only amortize on "
                "volume)")
        if not self._fusion_on:
            out.append("whole-stage fusion DISABLED "
                       "(spark.tpu.fusion.enabled=false): operator-at-a-"
                       "time oracle — every stage boundary is unfused")
        if (self.report.tier or {}).get("tier") == "operator":
            out.append("compilation tier OPERATOR "
                       "(spark.tpu.compile.tier): shared operator-at-a-"
                       "time kernels — whole-stage fusion rewrites "
                       "skipped at plan time")
        for node in plan.iter_nodes():
            if isinstance(node, FusedAggregateExec):
                out.append(f"FUSED {node.simple_string()[:80]}: pipeline "
                           f"traced into the partial-agg kernel; {gate}")
            elif isinstance(node, FusedLimitExec):
                out.append(f"FUSED {node.simple_string()[:80]}; {gate}")
            elif isinstance(node, ShuffleExchangeExec):
                if getattr(node, "pipe_fusion", None) is not None:
                    out.append(f"FUSED map side "
                               f"{node.simple_string()[:80]}: partition-id "
                               f"kernel traced into the pipeline; {gate}")
                else:
                    reasons = self._exchange_boundary_reasons(node, O)
                    if reasons:
                        out.append(
                            f"UNFUSED exchange "
                            f"{node.simple_string()[:80]}: "
                            + "; ".join(reasons))
            elif isinstance(node, O.HashJoinExec) \
                    and node.probe_fusion is not None:
                out.append(f"FUSED probe {node.simple_string()[:80]}; "
                           f"{gate}")
            elif isinstance(node, O.HashAggregateExec) \
                    and node.mode == "partial":
                reasons = self._agg_boundary_reasons(
                    node, O, FUSABLE_OPS, _compute_nontrivial)
                if reasons:
                    out.append(f"UNFUSED {node.simple_string()[:80]}: "
                               + "; ".join(reasons))
            elif isinstance(node, O.HashJoinExec):
                reasons = self._join_boundary_reasons(
                    node, O, _compute_nontrivial)
                if reasons:
                    out.append(f"UNFUSED probe "
                               f"{node.simple_string()[:80]}: "
                               + "; ".join(reasons))
            elif isinstance(node, O.LimitExec) and not isinstance(
                    node, FusedLimitExec):
                if isinstance(node.child, O.SortExec):
                    msg = ("UNFUSED Limit over Sort: SortExec has no "
                           "fused consume side yet (needs the sort-key "
                           "rank domain inside the trace — ROADMAP item)")
                    if msg not in out:
                        out.append(msg)

    def _agg_boundary_reasons(self, node, O, FUSABLE_OPS,
                              _compute_nontrivial):
        reasons = []
        c = node.child
        if not self._fusion_on:
            return []
        if not isinstance(c, O.ComputeExec):
            if isinstance(c, (O.HashJoinExec,)):
                reasons.append("consume side is a join output (only "
                               "filter/project pipelines splice into the "
                               "agg kernel)")
            elif type(c).__name__.endswith("ExchangeExec"):
                reasons.append("stage boundary is an exchange — fusion "
                               "never crosses exchanges")
            else:
                reasons.append(f"consume side {type(c).__name__} is not a "
                               "fusable pipeline")
            return reasons
        if not _compute_nontrivial(c):
            reasons.append("upstream pipeline is a pure column selection — "
                           "nothing to fuse (zero launches either way)")
            return reasons
        if not all(s.mergeable for s in node.specs):
            reasons.append("non-mergeable aggregate (percentile/collect "
                           "needs host-side finishing)")
        out_ids = {a.expr_id for a in c.output}
        if any(g.expr_id not in out_ids for g in node.grouping):
            reasons.append("grouping key is not produced by the pipeline")
        for op, attr, _ in node._plan_values():
            if op not in FUSABLE_OPS:
                reasons.append(f"op {op} has no fused kernel")
            # string min/max no longer breaks fusion: the fused kernel
            # reduces in rank space with the inverse-rank lut as an aux
            # input
        return reasons or ["not rewritten (unexpected: report this plan)"]

    def _exchange_boundary_reasons(self, node, O) -> list:
        """Why a shuffle exchange over a nontrivial pipeline did NOT fuse
        its map side (mirrors fusion._exchange_fusable)."""
        from ..physical.fusion import _compute_nontrivial
        from ..physical.partitioning import (
            HashPartitioning, RangePartitioning, SinglePartition,
            UnknownPartitioning,
        )

        if not self._fusion_on:
            return []
        c = node.child
        if not isinstance(c, O.ComputeExec) or not _compute_nontrivial(c):
            return []
        p = node.partitioning
        if isinstance(p, SinglePartition):
            return []  # gather launches no partition kernel — nothing lost
        if not self._fusion_exchange:
            return ["exchange map-side fusion disabled "
                    "(spark.tpu.fusion.exchange=false)"]
        out_by_id = {a.expr_id: a for a in c.output}
        if isinstance(p, HashPartitioning):
            for e in p.exprs:
                a = out_by_id.get(getattr(e, "expr_id", -1))
                if a is None:
                    continue
                if isinstance(a.dtype, StringType):
                    if not self._encoding:
                        return [f"partition key {a.name} is a dictionary-"
                                "encoded string and compressed execution "
                                "is off (spark.tpu.encoding.enabled="
                                "false): eq-keys ride host-side "
                                "dictionary hashes"]
                elif dict_encoded(a.dtype):
                    return [f"partition key {a.name} is a nested "
                            "dictionary-encoded type: codes are not a "
                            "cross-dictionary equality domain"]
            return ["not rewritten (unexpected: report this plan)"]
        if isinstance(p, RangePartitioning):
            if len(p.orders) != 1:
                return ["multi-key range partitioning is not fused"]
            oc = p.orders[0].child
            a = out_by_id.get(getattr(oc, "expr_id", -1))
            if a is not None and (isinstance(a.dtype, StringType)
                                  or dict_encoded(a.dtype)):
                return [f"range key {a.name} is a dictionary-encoded "
                        "string: pids ride a host rank→pid lut"]
            # computed sort keys fuse: bounds sample the post-pipeline
            # key column (the sampled batches materialize the pipeline)
            return ["not rewritten (unexpected: report this plan)"]
        if isinstance(p, UnknownPartitioning):
            return ["not rewritten (unexpected: report this plan)"]
        return []

    def _join_boundary_reasons(self, node, O, _compute_nontrivial):
        if not self._fusion_on:
            return []
        c = node.left
        if not isinstance(c, O.ComputeExec):
            return []
        if not _compute_nontrivial(c):
            return ["probe pipeline is a pure column selection — nothing "
                    "to fuse"]
        out_by_id = {a.expr_id: a for a in c.output}
        for k in node.left_keys:
            a = out_by_id.get(k.expr_id)
            if a is None:
                return ["probe key is not produced by the pipeline"]
            if isinstance(a.dtype, StringType):
                if not self._encoding:
                    return [f"probe key {a.name} is a dictionary-encoded "
                            "string and compressed execution is off "
                            "(spark.tpu.encoding.enabled=false): "
                            "equality rides host-side dictionary hashes"]
            elif dict_encoded(a.dtype):
                return [f"probe key {a.name} is a nested dictionary-"
                        "encoded type: codes are not a cross-dictionary "
                        "equality domain"]
        return []

    # -- overflow ----------------------------------------------------------
    def _overflow_pass(self, plan):
        from ..physical import operators as O

        seen = set()
        for node in plan.iter_nodes():
            if not isinstance(node, O.HashAggregateExec):
                continue
            for s in node.specs:
                if id(s) in seen:
                    continue
                seen.add(id(s))
                for op in s.ops:
                    if op not in ("sum", "count", "countstar"):
                        continue
                    name = s.input_expr.name if isinstance(
                        s.input_expr, AttributeReference) else (
                        s.result_alias.name)
                    msg = None
                    if op == "sum" and s.input_expr is not None and \
                            isinstance(s.input_expr.dtype, IntegralType):
                        msg = (f"SUM({name}) accumulates in int64: with "
                               "ANSI off, |value|*rows beyond 2^63 wraps "
                               "silently (partial+final merges compound "
                               "the range)")
                    elif op in ("count", "countstar"):
                        # int64 counter: saturation needs ~9.2e18 rows
                        pass
                    elif op == "sum" and s.input_expr is not None and \
                            str(s.input_expr.dtype) == "float":
                        msg = (f"SUM({name}) over float32 input "
                               "accumulates in float64 (precision, not "
                               "overflow)")
                    if msg and msg not in self.report.overflow_risks:
                        self.report.overflow_risks.append(msg)


class HashAggMergeProxy:
    """Adapter: the fused aggregate's merge step behaves like a final-mode
    HashAggregateExec over the partial buffers (same grouping/specs)."""

    def __init__(self, fused):
        self.grouping = fused.grouping
        self.specs = fused.specs
        self._inner = fused

    def _plan_values(self):
        from ..physical.aggregates import PARTIAL_TO_MERGE

        out = []
        for s in self.specs:
            for i, op in enumerate(s.ops):
                out.append((PARTIAL_TO_MERGE.get(op, op),
                            s.buffer_attrs[i], s.param))
        return out


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

def analyze_plan(plan, conf: SQLConf, cluster: bool = False) -> AnalysisReport:
    """Analyze an optimized PHYSICAL plan. Predictions model one WARM
    execution: kernel caches compiled, device-cached scans resident, and
    the device-scalar memo primed (first runs add one krange3 probe per
    distinct stable column plus the compile misses). `cluster` models
    execution under a cluster scheduler, where exchanges run the host
    shuffle path in worker map tasks instead of the driver-local mesh
    collective."""
    return _Analyzer(conf, cluster=cluster).run(plan)
