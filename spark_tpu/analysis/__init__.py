"""Static analysis for the TPU engine (role of the reference's
EXPLAIN CODEGEN / debugCodegen surface plus the lint layer the reference
spreads across Catalyst checks and scalastyle rules).

Three cooperating passes:

  * analysis.lint — AST-level source lint over spark_tpu/ for host-sync,
    recompile, and fusion-break hazards in operator/kernel hot paths
    (CLI: dev/tpulint.py, baseline: dev/tpulint_baseline.json).
  * analysis.plan_lint — plan/trace-level analyzer over an optimized
    physical plan: predicts kernel launches per batch per stage, explains
    why stage boundaries did or did not fuse, and flags recompile and
    dtype-overflow hazards (surfaced via df.explain("analysis"),
    QueryExecution.analysis_report(), and bench.py --analyze).
  * analysis.race_lint — whole-repo concurrency model: shared-mutation
    races, lock-order cycles, contextvar-losing thread spawns, and
    worker re-init gaps (CLI: dev/racecheck.py, baseline:
    dev/race_baseline.json; runtime cross-check: utils/lockwatch.py +
    dev/validate_trace.py --race).
"""

from .lint import (  # noqa: F401
    Violation, lint_paths, lint_source, load_baseline, new_violations,
    write_baseline,
)
from .plan_lint import AnalysisReport, analyze_plan  # noqa: F401
from .race_lint import (  # noqa: F401
    RepoModel, build_model, build_model_from_sources,
)
