"""tpulint source pass: AST lint for host-sync / recompile / fusion hazards.

Role of the reference's style+semantics gates (scalastyle rules banning
`Await.result` on hot paths, Catalyst's sanity checks) adapted to the XLA
execution model, where the expensive mistakes are different:

  * ``host-sync`` — device→host round-trips on operator hot paths:
    ``.item()``, ``int()/float()/bool()`` over computed values,
    ``np.asarray(...)`` on device arrays, ``block_until_ready`` outside
    bench code. One sync stalls the async dispatch pipeline
    (utils/device_memo.memo_device_scalars exists precisely to kill
    these); on transfer-bound transports each is a permanent tax.
  * ``row-loop`` — Python-level per-row loops inside ops/ and physical/:
    a ``for`` over ``range(num_rows/capacity)`` is the antithesis of the
    one-dispatch-per-batch contract.
  * ``raw-jit`` — ``jax.jit`` calls that bypass the structurally-keyed
    ``KernelCache``: uncached jits recompile per call site/instance and
    never show up in the launch counters the fusion regression tests key
    on (physical/compile.KernelCache).
  * ``config-key`` — ``spark.tpu.*`` keys read by string literal but never
    registered as a typed ConfigEntry: typos read defaults silently and
    config loses its single source of truth (config.py registry).

Suppression: a trailing/preceding ``# tpulint: ignore[rule]`` pragma, or a
checked-in baseline (dev/tpulint_baseline.json) so existing debt doesn't
block CI while NEW violations do.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field

__all__ = ["Violation", "lint_source", "lint_paths", "load_baseline",
           "write_baseline", "new_violations", "RULES"]

RULES = ("host-sync", "row-loop", "raw-jit", "config-key")

# directories (relative to the package root) whose code is operator/kernel
# hot path: host syncs there stall the dispatch pipeline
_HOT_DIRS = ("ops", "physical", "columnar", "exec", "parallel")
# per-row Python loops are only outlawed where kernels live
_LOOP_DIRS = ("ops", "physical")

_KEY_RE = re.compile(r"^spark\.tpu\.[A-Za-z0-9_.]+$")
_PRAGMA_RE = re.compile(r"#\s*tpulint:\s*ignore(?:\[([a-z\-,\s]+)\])?")

_ROW_LOOP_NAMES = {"num_rows", "n_rows", "nrows", "capacity"}


@dataclass
class Violation:
    rule: str
    path: str            # repo-relative
    line: int
    col: int
    snippet: str
    message: str

    @property
    def bucket(self) -> str:
        """Baseline bucket: stable under line shifts."""
        return f"{self.path}::{self.rule}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}\n    {self.snippet}")


# ---------------------------------------------------------------------------
# pragma handling
# ---------------------------------------------------------------------------

def _pragmas(source_lines: list[str]) -> dict[int, set[str] | None]:
    """line number (1-based) → suppressed rule set (None = all rules).
    A trailing pragma suppresses its own line only; a comment-ONLY pragma
    line also suppresses the following line (so it can sit above a long
    statement) — a trailing pragma must not grandfather whatever lands on
    the next line."""
    out: dict[int, set[str] | None] = {}
    for i, line in enumerate(source_lines, start=1):
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        rules = None
        if m.group(1):
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        targets = (i,) if line[:m.start()].strip() else (i, i + 1)
        for ln in targets:
            prev = out.get(ln, set())
            if rules is None or prev is None:
                out[ln] = None
            else:
                out[ln] = prev | rules
    return out


def _is_suppressed(pragmas, line: int, rule: str) -> bool:
    if line not in pragmas:
        return False
    rules = pragmas[line]
    return rules is None or rule in rules


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------

def _attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._tpulint_parent = node  # type: ignore[attr-defined]


def _enclosing_functions(node: ast.AST, lambdas: bool = False):
    """Enclosing function scopes, innermost first."""
    kinds = (ast.FunctionDef, ast.AsyncFunctionDef)
    if lambdas:
        kinds = kinds + (ast.Lambda,)
    out = []
    cur = getattr(node, "_tpulint_parent", None)
    while cur is not None:
        if isinstance(cur, kinds):
            out.append(cur)
        cur = getattr(cur, "_tpulint_parent", None)
    return out


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('jax.jit', 'np.asarray')."""
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _contains_call(fn: ast.AST, names: tuple) -> bool:
    for n in ast.walk(fn):
        if isinstance(n, ast.Call):
            tgt = n.func
            if isinstance(tgt, ast.Attribute) and tgt.attr in names:
                return True
            if isinstance(tgt, ast.Name) and tgt.id in names:
                return True
    return False


def _contains_get_or_build(fn: ast.AST) -> bool:
    return _contains_call(fn, ("get_or_build",))


_MEMO_NAMES = ("memo_device_scalars", "_memo_device_scalars",
               "seed_dense_range_memo")


def _memo_protected(tree: ast.AST) -> tuple[set, set]:
    """(function names, lambda node ids) passed as arguments to a
    memo_device_scalars-family call — ONLY those closures run once per
    array identity; code merely near a memo call still syncs per call."""
    names: set[str] = set()
    lams: set[int] = set()
    for n in ast.walk(tree):
        if not isinstance(n, ast.Call):
            continue
        tgt = n.func
        tname = tgt.attr if isinstance(tgt, ast.Attribute) else (
            tgt.id if isinstance(tgt, ast.Name) else "")
        if tname not in _MEMO_NAMES:
            continue
        for arg in list(n.args) + [kw.value for kw in n.keywords]:
            if isinstance(arg, ast.Lambda):
                lams.add(id(arg))
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name):
                    names.add(sub.id)
    return names, lams


def _memoized_context(node: ast.AST, memo_names: set,
                      memo_lambdas: set) -> bool:
    """True when `node`'s INNERMOST enclosing function/lambda is itself the
    closure handed to a memo_device_scalars call — the sanctioned
    once-per-array-identity wrapper for host reads (utils/device_memo.py).
    Code outside that closure gets no exemption, even in the same
    function."""
    encl = _enclosing_functions(node, lambdas=True)
    if not encl:
        return False
    inner = encl[0]
    if isinstance(inner, ast.Lambda):
        return id(inner) in memo_lambdas
    return inner.name in memo_names


def _names_used_in_cache_builders(tree: ast.AST) -> set[str]:
    """Function names referenced inside any get_or_build(...) call's
    arguments — module-level kernel builders wrapped at the call site
    (`get_or_build(key, lambda: _group_kernel(...))`)."""
    out: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr == "get_or_build":
            for arg in list(n.args) + [kw.value for kw in n.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
    return out


# ---------------------------------------------------------------------------
# registered config keys
# ---------------------------------------------------------------------------

def registered_config_keys(root: str) -> set[str]:
    """Every key registered as `ConfigEntry("<key>", ...)` anywhere under
    `root` (config.py is the canonical registry; memory.py et al. register
    their own entries through the same type)."""
    keys: set[str] = set()
    for path in _iter_py(root):
        try:
            tree = ast.parse(open(path, encoding="utf-8").read())
        except SyntaxError:
            continue
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) and _dotted(n.func).endswith(
                    "ConfigEntry") and n.args:
                a0 = n.args[0]
                if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                    keys.add(a0.value)
    return keys


def _config_entry_arg_lines(tree: ast.AST) -> set[int]:
    """Lines where a string literal is the ConfigEntry key itself."""
    out: set[int] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Call) and _dotted(n.func).endswith(
                "ConfigEntry") and n.args:
            a0 = n.args[0]
            if isinstance(a0, ast.Constant):
                out.add(a0.lineno)
    return out


# ---------------------------------------------------------------------------
# the lint proper
# ---------------------------------------------------------------------------

def _rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(path, root).replace(os.sep, "/")
    except ValueError:
        return path


def _in_dirs(relpath: str, dirs) -> bool:
    parts = relpath.split("/")
    return any(d in parts[:-1] for d in dirs)


def lint_source(source: str, relpath: str,
                registered_keys: set[str] | None = None) -> list[Violation]:
    """Lint one module's source. `relpath` decides hot-path scoping."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Violation("host-sync", relpath, e.lineno or 0, 0, "",
                          f"syntax error prevents linting: {e.msg}")]
    _attach_parents(tree)
    lines = source.splitlines()
    pragmas = _pragmas(lines)
    builder_names = _names_used_in_cache_builders(tree)
    memo_names, memo_lambdas = _memo_protected(tree)
    entry_lines = _config_entry_arg_lines(tree)
    hot = _in_dirs(relpath, _HOT_DIRS)
    loopable = _in_dirs(relpath, _LOOP_DIRS)
    is_registry = relpath.endswith("config.py")
    is_cache = relpath.endswith("physical/compile.py")

    out: list[Violation] = []

    def emit(rule: str, node: ast.AST, message: str):
        line = getattr(node, "lineno", 0)
        if _is_suppressed(pragmas, line, rule):
            return
        snippet = lines[line - 1].strip() if 0 < line <= len(lines) else ""
        out.append(Violation(rule, relpath, line,
                             getattr(node, "col_offset", 0), snippet,
                             message))

    for node in ast.walk(tree):
        # ---- host-sync -------------------------------------------------
        if isinstance(node, ast.Call):
            target = _dotted(node.func)
            memoized = hot and _memoized_context(node, memo_names,
                                                 memo_lambdas)
            if memoized:
                # inside the closure handed to memo_device_scalars: the
                # pull runs once per array identity — sanctioned
                pass
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args and hot:
                emit("host-sync", node,
                     ".item() syncs one scalar per call on a hot path — "
                     "memoize via utils/device_memo.memo_device_scalars or batch the reads")
            elif target in ("np.asarray", "numpy.asarray") and hot:
                emit("host-sync", node,
                     "np.asarray on a device array is a device→host "
                     "transfer; hoist it out of per-batch loops or memoize "
                     "(utils/device_memo.memo_device_scalars)")
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                emit("host-sync", node,
                     "block_until_ready stalls the dispatch pipeline; it "
                     "belongs in bench/test code, not the engine")
            elif isinstance(node.func, ast.Name) \
                    and node.func.id in ("int", "float", "bool") \
                    and hot and len(node.args) == 1 \
                    and isinstance(node.args[0],
                                   (ast.Call, ast.Attribute, ast.Subscript)):
                emit("host-sync", node,
                     f"{node.func.id}() over a computed value host-syncs "
                     "if it is a device scalar — memoize "
                     "(utils/device_memo) or keep it on device")
            # ---- raw-jit -----------------------------------------------
            if target in ("jax.jit", "jit") and target and not is_cache:
                encl = _enclosing_functions(node)
                ok = any(_contains_get_or_build(f) for f in encl) \
                    or any(f.name in builder_names for f in encl)
                if not ok:
                    emit("raw-jit", node,
                         "jax.jit outside KernelCache.get_or_build: the "
                         "kernel recompiles per call site and its launches "
                         "are invisible to the dispatch-count regression "
                         "counters (physical/compile.KernelCache)")
        # ---- row-loop --------------------------------------------------
        if isinstance(node, ast.For) and loopable:
            it = node.iter
            flagged = False
            if isinstance(it, ast.Call) and _dotted(it.func) == "range":
                for a in it.args:
                    for sub in ast.walk(a):
                        if (isinstance(sub, ast.Name)
                                and sub.id in _ROW_LOOP_NAMES) or \
                           (isinstance(sub, ast.Attribute)
                                and sub.attr in _ROW_LOOP_NAMES):
                            flagged = True
            elif isinstance(it, ast.Call) and isinstance(it.func,
                                                         ast.Attribute) \
                    and it.func.attr in ("to_pylist", "tolist"):
                flagged = True
            if flagged:
                emit("row-loop", node,
                     "Python-level per-row loop in a kernel module — this "
                     "breaks the one-dispatch-per-batch contract; express "
                     "it as a masked device kernel")
        # ---- config-key ------------------------------------------------
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _KEY_RE.match(node.value) and not is_registry \
                and node.lineno not in entry_lines \
                and registered_keys is not None \
                and node.value not in registered_keys:
            emit("config-key", node,
                 f"config key '{node.value}' read by literal but never "
                 "registered as a ConfigEntry — register it in config.py "
                 "so defaults/typing have one source of truth")
    return out


def _iter_py(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _package_root(path: str) -> str:
    """Topmost enclosing python package of `path` (ascends while an
    __init__.py is present) — the scope ConfigEntry registrations are
    collected over, so linting a single file still sees the sibling
    config.py registry."""
    p = os.path.abspath(path)
    if os.path.isfile(p):
        p = os.path.dirname(p)
    while os.path.isfile(os.path.join(os.path.dirname(p), "__init__.py")):
        parent = os.path.dirname(p)
        if parent == p:
            break
        p = parent
    if os.path.isfile(os.path.join(p, "__init__.py")):
        return p
    return path


def lint_paths(paths, repo_root: str | None = None) -> list[Violation]:
    """Lint every .py under `paths`. Registered config keys are collected
    over each path's whole enclosing PACKAGE (not just the linted subset),
    so linting one file never produces false config-key violations."""
    paths = [paths] if isinstance(paths, str) else list(paths)
    repo_root = repo_root or os.path.commonpath(
        [os.path.abspath(p) for p in paths])
    if os.path.isfile(repo_root):
        repo_root = os.path.dirname(repo_root)
    keys: set[str] = set()
    for p in paths:
        keys |= registered_config_keys(_package_root(p))
    out: list[Violation] = []
    for p in paths:
        for path in _iter_py(p):
            rel = _rel(os.path.abspath(path), repo_root)
            try:
                src = open(path, encoding="utf-8").read()
            except OSError:
                continue
            out.extend(lint_source(src, rel, registered_keys=keys))
    out.sort(key=lambda v: (v.path, v.line, v.col))
    return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def baseline_counts(violations) -> dict[str, int]:
    counts: dict[str, int] = {}
    for v in violations:
        counts[v.bucket] = counts.get(v.bucket, 0) + 1
    return counts


def write_baseline(path: str, violations) -> dict:
    data = {"version": 1, "counts": baseline_counts(violations)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


def load_baseline(path: str) -> dict[str, int]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return dict(data.get("counts", {}))


def new_violations(violations, baseline: dict[str, int]) -> list[Violation]:
    """Violations beyond the baselined count per (file, rule) bucket.
    Counted per bucket (line-shift tolerant); the overflow sites reported
    are the LAST ones in the file — newest code tends to sit lowest."""
    by_bucket: dict[str, list[Violation]] = {}
    for v in violations:
        by_bucket.setdefault(v.bucket, []).append(v)
    out: list[Violation] = []
    for bucket, vs in sorted(by_bucket.items()):
        allowed = baseline.get(bucket, 0)
        if len(vs) > allowed:
            out.extend(vs[allowed:])
    return out
