"""race_lint: whole-repo static race & lock-discipline analyzer.

tpulint (analysis/lint.py) guards the engine's PERFORMANCE contracts
(host syncs, recompiles); this pass guards its CONCURRENCY contracts —
the bug class every review-hardening pass since the engine went
multi-threaded (par_map lanes, heartbeat flushers, serve pools, RPC
handler threads, speculation watchers) has been fixing by hand.

Pure AST, no jax import, whole-repo: unlike tpulint's per-file rules,
these properties only exist at the repo level — a mutation in one
module is racy because of a thread spawned in another.

The model, built in two passes:

  1. Per module: process-global mutable state (module-level dict/list/
     set/counter assignments, attributes of singleton instances like
     ``GLOBAL_KERNEL_CACHE = KernelCache()``), every mutation site of
     that state, lock definitions (module-level ``X = threading.Lock()``
     and ``self.X = threading.Lock()`` class locks), ``with <lock>:``
     guard structure, call/reference names per function, and thread
     spawn sites (``threading.Thread``, ``pool.submit``,
     ``scoped_submit``, ``par_map``).
  2. Whole repo: a name-based call graph links spawn roots to every
     mutation they can reach; guard sets are inferred from enclosing
     ``with`` blocks (plus ``# guarded-by: <lock>`` annotations where
     the lock is held by a caller the AST cannot see); a lock-nesting
     graph is built from lexical ``with`` nesting plus transitive
     acquires of functions called under a held lock.

Rules:

  * ``shared-mutation`` — a process-global object mutated at sites
    reachable from a thread root with NO lock common to all of its
    mutation sites. Fix with a shared lock, the utils/counters.py
    locked-counter helpers (recognized as internally guarded), or a
    ``# guarded-by:`` annotation naming the caller-held lock.
  * ``lock-order`` — a cycle in the inferred lock-acquisition nesting
    graph (deadlock hazard). Same-name self-loops are ignored: the
    graph buckets per-instance locks by class, and two instances of one
    class cannot deadlock a single holder ordering.
  * ``bare-submit`` — a bare ``threading.Thread(...)`` or
    ``pool.submit(fn)`` in obs-scoped code: pool/thread entry without
    ``scoped_submit``/``par_map`` drops the contextvar query scope (the
    PR 4/6 attribution-loss bug class, now a rule instead of a
    test-by-test hunt). Long-lived service threads that never dispatch
    query-scoped work carry a pragma with a written justification.
  * ``worker-reinit`` — mutated process-global state in worker-shipped
    modules with no re-init path (no reset/configure-style function
    reassigning or clearing it): a forked/spawned worker inherits or
    re-imports the module and the state silently diverges from the
    driver's.

Suppression mirrors tpulint: a ``# race-lint: ignore[rule]`` pragma on
(or immediately above) the offending line, or the checked-in
per-(file,rule)-count baseline ``dev/race_baseline.json`` so existing
debt doesn't block CI while NEW violations do.

The model is also the contract the runtime half validates
(utils/lockwatch.py + ``dev/validate_trace.py --race``): exported
``lock_edges`` are unioned with OBSERVED acquisition orders (no cycle
may appear), and every ``# guarded-by:`` annotation must be held where
claimed at instrumented mutation sites.
"""

from __future__ import annotations

import ast
import builtins as _builtins
import json
import os
import re
from dataclasses import dataclass, field

_BUILTIN_NAMES = frozenset(dir(_builtins))

__all__ = ["RULES", "RepoModel", "Violation", "baseline_counts",
           "build_model", "build_model_from_sources", "find_cycle",
           "lint_paths", "lint_sources", "load_baseline",
           "new_violations", "write_baseline"]

RULES = ("shared-mutation", "lock-order", "bare-submit", "worker-reinit")

# directories whose code may run with the obs query scope active: thread
# handoffs there must propagate contextvars (scoped_submit / par_map)
_OBS_DIRS = ("exec", "serve", "obs", "rdd", "streaming", "connect",
             "deploy")
# modules shipped to (re-imported by) cluster worker processes: mutated
# globals there need an explicit re-init path
_WORKER_DIRS = ("exec", "net", "obs", "utils", "columnar", "ops",
                "physical", "parallel")

_PRAGMA_RE = re.compile(r"#\s*race-lint:\s*ignore(?:\[([a-z\-,\s]+)\])?")
_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z0-9_.]+)")
_REINIT_RE = re.compile(r"(reset|configure|install|init|clear)", re.I)

_LOCK_CTORS = {"threading.Lock", "Lock", "threading.RLock", "RLock"}
# state kinds created by these constructors never count as bare shared
# state: contextvars/thread-locals are per-context by design, the
# locked counters are internally guarded (utils/counters.py)
_EXEMPT_CTORS = {"ContextVar", "contextvars.ContextVar", "local",
                 "threading.local", "LockedCounter", "LockedCounterMap",
                 "Event", "threading.Event"}
_CONTAINER_CTORS = {"dict", "list", "set", "collections.Counter",
                    "Counter", "collections.OrderedDict", "OrderedDict",
                    "collections.defaultdict", "defaultdict",
                    "collections.deque", "deque"}
_MUTATORS = {"append", "extend", "add", "update", "setdefault", "pop",
             "popitem", "clear", "remove", "discard", "insert",
             "appendleft", "popleft"}


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------

@dataclass
class Violation:
    rule: str
    path: str            # repo-relative
    line: int
    col: int
    snippet: str
    message: str

    @property
    def bucket(self) -> str:
        """Baseline bucket: stable under line shifts."""
        return f"{self.path}::{self.rule}"

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
                f"{self.message}\n    {self.snippet}")


# ---------------------------------------------------------------------------
# Per-module scan products
# ---------------------------------------------------------------------------

@dataclass
class _Mutation:
    state: str           # repo-global state id ("net.transport.RETRY_STATS")
    path: str
    line: int
    col: int
    func: str            # enclosing function qualname
    guards: frozenset    # resolved lock ids + annotation lock names
    annotated: tuple     # guarded-by annotation lock names on this site


@dataclass
class _Spawn:
    kind: str            # thread | submit | scoped_submit | par_map
    target: str          # bare callable name ("" unknown, "<lambda>")
    target_is_func: bool  # Name/Attribute/Lambda (callable-shaped arg)
    path: str
    line: int
    col: int
    func: str            # spawning function qualname


@dataclass
class _WithBlock:
    expr: tuple          # (dotted, class_ctx) — resolved in pass 2
    line: int
    col: int
    nested: list         # inner _WithBlock list
    calls: list          # (call descriptor, line) made under the lock


@dataclass
class _Func:
    qualname: str
    bare: str
    modname: str
    path: str
    class_ctx: str | None
    calls: set = field(default_factory=set)     # call descriptors
    refs: set = field(default_factory=set)      # bare Name loads
    withs: list = field(default_factory=list)   # top-level _WithBlocks


@dataclass
class _Module:
    path: str
    modname: str
    lines: list
    pragmas: dict
    locks: dict = field(default_factory=dict)        # local name -> id
    class_locks: dict = field(default_factory=dict)  # (cls, attr) -> id
    # state id -> kind ("container" | "scalar" | "exempt")
    states: dict = field(default_factory=dict)
    state_lines: dict = field(default_factory=dict)  # state id -> def line
    singleton_classes: set = field(default_factory=set)
    funcs: dict = field(default_factory=dict)        # qualname -> _Func
    mutations: list = field(default_factory=list)
    # (class, attr) self-mutations kept until singleton filter in pass 2
    attr_mutations: list = field(default_factory=list)
    spawns: list = field(default_factory=list)
    # function bare name -> set of state names it re-initializes
    reinits: dict = field(default_factory=dict)
    annotations: list = field(default_factory=list)  # {path,line,lock,state}


# ---------------------------------------------------------------------------
# Small AST helpers (same idioms as analysis/lint.py)
# ---------------------------------------------------------------------------

def _attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._rl_parent = node  # type: ignore[attr-defined]


def _parent(node):
    return getattr(node, "_rl_parent", None)


def _dotted(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _call_desc(func_node) -> tuple | None:
    """Call-site descriptor ("bare"|"self"|"qual"|"obj", recv, name) —
    the key pass 2 resolves into call-graph edges. Precision over
    recall: an unresolvable receiver yields "obj", which only links when
    the method name is UNIQUE repo-wide (so `cache.get_or_build(...)`
    links but a dict's `.get(...)` links nowhere)."""
    if isinstance(func_node, ast.Name):
        return ("bare", None, func_node.id)
    if isinstance(func_node, ast.Attribute):
        name = func_node.attr
        recv = func_node.value
        if isinstance(recv, ast.Name):
            if recv.id == "self":
                return ("self", None, name)
            return ("qual", recv.id, name)
        return ("obj", None, name)
    return None


def _pragmas(source_lines: list) -> dict:
    """line -> suppressed rule set (None = all). A trailing pragma
    covers its own line; a comment-only pragma line covers itself, any
    continuation comment lines below it (the written justification the
    bare-submit rule asks for), and the next CODE line."""
    out: dict = {}
    for i, line in enumerate(source_lines, start=1):
        m = _PRAGMA_RE.search(line)
        if not m:
            continue
        rules = None
        if m.group(1):
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if line[:m.start()].strip():
            targets = [i]
        else:
            targets = [i]
            j = i + 1
            while j <= len(source_lines):
                text = source_lines[j - 1].strip()
                targets.append(j)
                if text and not text.startswith("#"):
                    break   # the code line the pragma governs
                j += 1
        for ln in targets:
            prev = out.get(ln, set())
            out[ln] = None if rules is None or prev is None \
                else prev | rules
    return out


def _is_suppressed(pragmas: dict, line: int, rule: str) -> bool:
    if line not in pragmas:
        return False
    rules = pragmas[line]
    return rules is None or rule in rules


def _guard_annotations(source_lines: list, line: int) -> tuple:
    """guarded-by lock names annotated on `line` (trailing) or on a
    comment-only line immediately above."""
    out = []
    for ln in (line, line - 1):
        if not (0 < ln <= len(source_lines)):
            continue
        text = source_lines[ln - 1]
        m = _GUARDED_BY_RE.search(text)
        if not m:
            continue
        if ln == line or not text[:m.start()].strip():
            out.append(m.group(1))
    return tuple(out)


def _modname(relpath: str) -> str:
    p = relpath.replace(os.sep, "/")
    if p.startswith("spark_tpu/"):
        p = p[len("spark_tpu/"):]
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def _in_dirs(relpath: str, dirs) -> bool:
    parts = relpath.replace(os.sep, "/").split("/")
    return any(d in parts[:-1] for d in dirs)


def _enclosing(node):
    """(class_ctx, qualname suffix parts) from the parent chain."""
    parts: list = []
    cls = None
    cur = _parent(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parts.append(cur.name)
        elif isinstance(cur, ast.ClassDef):
            if cls is None:
                cls = cur.name
            parts.append(cur.name)
        cur = _parent(cur)
    return cls, list(reversed(parts))


def _is_lock_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    d = _dotted(value.func)
    if d in _LOCK_CTORS:
        return True
    # lockwatch.maybe_wrap("name", threading.Lock()) keeps lock-ness
    if d.endswith("maybe_wrap") and len(value.args) >= 2:
        return _is_lock_ctor(value.args[1])
    return False


def _state_kind(value: ast.AST) -> str | None:
    """Classify a module-level assignment's value as shared-state
    candidate kind, or None when it is not mutable shared state."""
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return "container"
    if isinstance(value, ast.Constant) and isinstance(
            value.value, (int, float)) and not isinstance(value.value, bool):
        return "scalar"
    if isinstance(value, ast.Constant) and isinstance(value.value, bool):
        return "scalar"
    if isinstance(value, ast.Call):
        d = _dotted(value.func)
        tail = d.rsplit(".", 1)[-1]
        if d in _EXEMPT_CTORS or tail in {t.rsplit(".", 1)[-1]
                                          for t in _EXEMPT_CTORS}:
            return "exempt"
        if d in _CONTAINER_CTORS:
            return "container"
    return None


# ---------------------------------------------------------------------------
# Pass 1: scan one module
# ---------------------------------------------------------------------------

def _scan_module(source: str, relpath: str) -> _Module | None:
    try:
        tree = ast.parse(source)
    except SyntaxError:
        return None
    _attach_parents(tree)
    lines = source.splitlines()
    mod = _Module(path=relpath, modname=_modname(relpath), lines=lines,
                  pragmas=_pragmas(lines))

    class_names = {n.name for n in tree.body if isinstance(n, ast.ClassDef)}

    # ---- module level: locks, states, singletons -----------------------
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            continue
        name = node.targets[0].id
        if name.startswith("__"):
            continue
        if _is_lock_ctor(node.value):
            mod.locks[name] = f"{mod.modname}.{name}"
            continue
        if isinstance(node.value, ast.Call):
            d = _dotted(node.value.func)
            if d in class_names:
                mod.singleton_classes.add(d)
                continue
        kind = _state_kind(node.value)
        if kind is not None:
            sid = f"{mod.modname}.{name}"
            mod.states[sid] = kind
            mod.state_lines[sid] = node.lineno

    # ---- class locks ----------------------------------------------------
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Attribute) \
                and isinstance(node.targets[0].value, ast.Name) \
                and node.targets[0].value.id == "self" \
                and _is_lock_ctor(node.value):
            cls, _parts = _enclosing(node)
            if cls is not None:
                attr = node.targets[0].attr
                mod.class_locks[(cls, attr)] = \
                    f"{mod.modname}.{cls}.{attr}"

    # ---- functions ------------------------------------------------------
    fn_nodes = [n for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    for fn in fn_nodes:
        cls, parts = _enclosing(fn)
        qual = ".".join([mod.modname] + parts + [fn.name])
        info = _Func(qualname=qual, bare=fn.name, modname=mod.modname,
                     path=relpath, class_ctx=cls)
        mod.funcs[qual] = info
        _scan_function(mod, fn, info)

    # ---- re-init paths --------------------------------------------------
    for fn in fn_nodes:
        if not _REINIT_RE.search(fn.name):
            continue
        names: set = set()
        declared: set = set()
        for n in _body_walk(fn):
            if isinstance(n, ast.Global):
                declared.update(n.names)
        for n in _body_walk(fn):
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                tgt = n.targets[0] if isinstance(n, ast.Assign) else n.target
                if isinstance(tgt, ast.Name) and tgt.id in declared:
                    names.add(tgt.id)
                if isinstance(tgt, ast.Subscript) and isinstance(
                        tgt.value, ast.Name):
                    names.add(tgt.value.id)
            elif isinstance(n, ast.Call) and isinstance(
                    n.func, ast.Attribute) and isinstance(
                    n.func.value, ast.Name) \
                    and n.func.attr in ("clear", "reset", "update"):
                names.add(n.func.value.id)
        if names:
            mod.reinits.setdefault(fn.name, set()).update(names)
    return mod


def _body_walk(fn: ast.AST):
    """Walk a function body WITHOUT descending into nested function
    definitions (they are separate call-graph nodes); lambdas stay in
    the parent (they execute inline where they are invoked)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        n = stack.pop()
        yield n
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(n))


def _scan_function(mod: _Module, fn: ast.AST, info: _Func) -> None:
    declared_globals: set = set()
    in_init = info.bare == "__init__"

    def enclosing_withs(node) -> list:
        out = []
        cur = _parent(node)
        while cur is not None and cur is not fn:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    out.append(_dotted(item.context_expr))
            cur = _parent(cur)
        return out

    def add_mutation(state_local: str | None, attr_pair, node) -> None:
        anns = _guard_annotations(mod.lines, node.lineno)
        raw_guards = tuple(enclosing_withs(node))
        entry = (raw_guards, anns, node.lineno,
                 getattr(node, "col_offset", 0), info)
        if state_local is not None:
            mod.mutations.append((f"{mod.modname}.{state_local}",) + entry)
        else:
            mod.attr_mutations.append((attr_pair,) + entry)

    for node in _body_walk(fn):
        if isinstance(node, ast.Global):
            declared_globals.update(node.names)

    for node in _body_walk(fn):
        # ---- mutations of module-level names ---------------------------
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Name) \
                        and tgt.id in declared_globals:
                    add_mutation(tgt.id, None, node)
                elif isinstance(tgt, ast.Subscript):
                    base = tgt.value
                    if isinstance(base, ast.Name):
                        add_mutation(base.id, None, node)
                    elif _is_self_attr(base) and not in_init \
                            and info.class_ctx:
                        add_mutation(None,
                                     (info.class_ctx, base.attr), node)
                elif _is_self_attr(tgt) and not in_init \
                        and info.class_ctx:
                    add_mutation(None, (info.class_ctx, tgt.attr), node)
        elif isinstance(node, ast.Delete):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) and isinstance(
                        tgt.value, ast.Name):
                    add_mutation(tgt.value.id, None, node)
        elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute) and node.func.attr in _MUTATORS:
            recv = node.func.value
            if isinstance(recv, ast.Name):
                add_mutation(recv.id, None, node)
            elif _is_self_attr(recv) and not in_init and info.class_ctx:
                add_mutation(None, (info.class_ctx, recv.attr), node)

        # ---- calls / references ----------------------------------------
        if isinstance(node, ast.Call):
            desc = _call_desc(node.func)
            if desc is not None:
                info.calls.add(desc)
            _maybe_spawn(mod, node, _dotted(node.func), info)
        elif isinstance(node, ast.Name) and isinstance(
                node.ctx, ast.Load):
            # bare references are potential callbacks handed to pools or
            # registries; reachability (only) follows them SAME-MODULE
            info.refs.add(node.id)

    # ---- with structure (lexical lock nesting) -------------------------
    def build_with(node: ast.With) -> list:
        out = []
        for item in node.items:
            wb = _WithBlock(expr=(_dotted(item.context_expr),
                                  info.class_ctx),
                            line=node.lineno,
                            col=node.col_offset, nested=[], calls=[])
            _fill_with_body(wb, node)
            out.append(wb)
        return out

    def _fill_with_body(wb: _WithBlock, node: ast.With) -> None:
        stack = list(node.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if isinstance(n, ast.With):
                wb.nested.extend(build_with(n))
                continue   # inner with records its own body
            if isinstance(n, ast.Call):
                desc = _call_desc(n.func)
                if desc is not None:
                    wb.calls.append((desc, n.lineno))
            stack.extend(ast.iter_child_nodes(n))

    for node in _body_walk(fn):
        if isinstance(node, ast.With):
            p = _parent(node)
            # only top-level withs here; nested ones ride wb.nested
            inside = False
            while p is not None and p is not fn:
                if isinstance(p, ast.With):
                    inside = True
                    break
                p = _parent(p)
            if not inside:
                info.withs.extend(build_with(node))


def _is_self_attr(node) -> bool:
    return isinstance(node, ast.Attribute) and isinstance(
        node.value, ast.Name) and node.value.id == "self"


def _maybe_spawn(mod: _Module, node: ast.Call, dotted: str,
                 info: _Func) -> None:
    tail = dotted.rsplit(".", 1)[-1] if dotted else ""

    def describe(arg) -> tuple:
        if isinstance(arg, ast.Lambda):
            return "<lambda>", True
        if isinstance(arg, ast.Name):
            return arg.id, True
        if isinstance(arg, ast.Attribute):
            return arg.attr, True
        return "", False

    if tail == "Thread" and dotted in ("Thread", "threading.Thread"):
        target = None
        for kw in node.keywords:
            if kw.arg == "target":
                target = kw.value
        if target is None and node.args:
            target = node.args[0]
        name, is_fn = describe(target) if target is not None else ("",
                                                                   False)
        mod.spawns.append(_Spawn("thread", name, is_fn, mod.path,
                                 node.lineno, node.col_offset,
                                 info.qualname))
    elif tail == "scoped_submit" and len(node.args) >= 2:
        name, is_fn = describe(node.args[1])
        mod.spawns.append(_Spawn("scoped_submit", name, is_fn, mod.path,
                                 node.lineno, node.col_offset,
                                 info.qualname))
    elif tail == "par_map" and node.args:
        name, is_fn = describe(node.args[0])
        mod.spawns.append(_Spawn("par_map", name, is_fn, mod.path,
                                 node.lineno, node.col_offset,
                                 info.qualname))
    elif tail == "submit" and node.args:
        name, is_fn = describe(node.args[0])
        mod.spawns.append(_Spawn("submit", name, is_fn, mod.path,
                                 node.lineno, node.col_offset,
                                 info.qualname))


# ---------------------------------------------------------------------------
# Pass 2: the repo model
# ---------------------------------------------------------------------------

@dataclass
class RepoModel:
    violations: list = field(default_factory=list)
    lock_edges: list = field(default_factory=list)   # (A, B) post-pragma
    annotations: list = field(default_factory=list)  # {path,line,lock,state}
    locks: set = field(default_factory=set)
    states: dict = field(default_factory=dict)       # id -> kind
    spawns: list = field(default_factory=list)

    def to_dict(self) -> dict:
        return {"lock_edges": sorted(self.lock_edges),
                "annotations": list(self.annotations),
                "locks": sorted(self.locks),
                "states": dict(sorted(self.states.items())),
                "spawn_sites": len(self.spawns)}


def find_cycle(edges) -> list | None:
    """First directed cycle over (src, dst) pairs as [a, b, ..., a];
    self-loops ignored (per-instance locks bucket by class)."""
    adj: dict = {}
    for a, b in edges:
        if a == b:
            continue
        adj.setdefault(a, []).append(b)
    color: dict = {}
    path: list = []

    def dfs(u):
        color[u] = 1
        path.append(u)
        for v in sorted(adj.get(u, ())):
            c = color.get(v, 0)
            if c == 1:
                return path[path.index(v):] + [v]
            if c == 0:
                found = dfs(v)
                if found:
                    return found
        path.pop()
        color[u] = 2
        return None

    for node in sorted(adj):
        if color.get(node, 0) == 0:
            found = dfs(node)
            if found:
                return found
    return None


def _resolve_lock(mods: dict, mod: _Module, class_ctx: str | None,
                  dotted: str, attr_index: dict) -> str | None:
    """Map a `with <expr>:` dotted text to a repo lock id, or None when
    the expression is not recognizably a lock (plain context managers
    stay out of the nesting graph)."""
    if not dotted:
        return None
    if "." not in dotted:
        return mod.locks.get(dotted)
    head, _, attr = dotted.rpartition(".")
    if head == "self" and class_ctx is not None:
        lid = mod.class_locks.get((class_ctx, attr))
        if lid is not None:
            return lid
        if "lock" in attr.lower():
            # a lock attribute the scan did not see assigned (inherited,
            # conditional): still class-scoped identity
            return f"{mod.modname}.{class_ctx}.{attr}"
        return None
    # module-qualified: "othermod._LOCK" via import
    tail = head.rsplit(".", 1)[-1]
    for other in mods.values():
        if other.modname == tail or other.modname.endswith("." + tail):
            lid = other.locks.get(attr)
            if lid is not None:
                return lid
    cands = attr_index.get(attr, ())
    if len(cands) == 1:
        return next(iter(cands))
    if "lock" in attr.lower():
        # ambiguous or unknown owner: opaque module-scoped identity so
        # the lexical guard still counts at its own sites
        return f"{mod.modname}.<{dotted}>"
    return None


def _build(mods: list) -> RepoModel:
    model = RepoModel()
    by_name = {m.modname: m for m in mods}

    # ---- singleton classes across the repo -----------------------------
    singleton_classes: set = set()
    for m in mods:
        singleton_classes.update(m.singleton_classes)

    # ---- lock attr index (attr name -> lock ids) -----------------------
    attr_index: dict = {}
    for m in mods:
        for name, lid in m.locks.items():
            attr_index.setdefault(name, set()).add(lid)
            model.locks.add(lid)
        for (_cls, attr), lid in m.class_locks.items():
            attr_index.setdefault(attr, set()).add(lid)
            model.locks.add(lid)

    # ---- functions + call graph ----------------------------------------
    funcs: dict = {}
    all_index: dict = {}    # bare -> [qual], every function (spawn targets)
    func_index: dict = {}   # bare -> [qual], module-level/nested only
    method_index: dict = {} # bare -> [qual], methods only
    permod: dict = {}       # (modname, bare) -> [qual], class_ctx None
    percls: dict = {}       # (modname, cls, bare) -> [qual]
    modtail: dict = {}      # module tail segment -> [modname]
    for m in mods:
        modtail.setdefault(m.modname.rsplit(".", 1)[-1],
                           []).append(m.modname)
    for m in mods:
        for qual, f in m.funcs.items():
            funcs[qual] = f
            all_index.setdefault(f.bare, []).append(qual)
            if f.class_ctx is None:
                func_index.setdefault(f.bare, []).append(qual)
                permod.setdefault((f.modname, f.bare), []).append(qual)
            else:
                method_index.setdefault(f.bare, []).append(qual)
                percls.setdefault((f.modname, f.class_ctx, f.bare),
                                  []).append(qual)

    def resolve_call(f: _Func, desc: tuple) -> list:
        """Call descriptor -> function qualnames. Precise first (same
        module, own class, module-qualified); an opaque receiver only
        links when the method name is unique repo-wide."""
        kind, recv, name = desc
        if kind == "bare":
            local = permod.get((f.modname, name))
            if local:
                return local
            if name in _BUILTIN_NAMES:
                return []
            cands = func_index.get(name, ())
            return list(cands) if len(cands) == 1 else []
        if kind == "self":
            if f.class_ctx is not None:
                own = percls.get((f.modname, f.class_ctx, name))
                if own:
                    return own
            cands = method_index.get(name, ())
            return list(cands) if len(cands) == 1 else []
        if kind == "qual":
            for modname in modtail.get(recv, ()):
                hit = permod.get((modname, name))
                if hit:
                    return hit
            for m2 in mods:
                hit = percls.get((m2.modname, recv, name))
                if hit:   # ClassName.method(...) static-style call
                    return hit
        cands = all_index.get(name, ())
        return list(cands) if len(cands) == 1 else []

    def callees(f: _Func):
        out = []
        for desc in f.calls:
            out.extend(resolve_call(f, desc))
        return out

    def reach_callees(f: _Func):
        # reachability additionally follows same-module bare references
        # (callbacks registered/handed off without an explicit call)
        out = callees(f)
        for name in f.refs:
            out.extend(permod.get((f.modname, name), ()))
        return out

    # ---- states ---------------------------------------------------------
    for m in mods:
        for sid, kind in m.states.items():
            model.states[sid] = kind

    # resolve mutations: module-name states + singleton attrs
    mutations: list = []
    for m in mods:
        for (sid, raw_guards, anns, line, col, f) in m.mutations:
            if sid in model.states:
                mutations.append((m, sid, raw_guards, anns, line, col, f))
        for ((cls, attr), raw_guards, anns, line, col, f) \
                in m.attr_mutations:
            if cls not in singleton_classes:
                continue
            if (cls, attr) in m.class_locks:
                continue    # the lock slot itself
            sid = f"{m.modname}.{cls}.{attr}"
            model.states.setdefault(sid, "singleton-attr")
            mutations.append((m, sid, raw_guards, anns, line, col, f))

    # guard resolution + annotation collection
    resolved: list = []
    for (m, sid, raw_guards, anns, line, col, f) in mutations:
        guards = set()
        for g in raw_guards:
            lid = _resolve_lock(by_name, m, f.class_ctx, g, attr_index)
            if lid is not None:
                guards.add(lid)
        for a in anns:
            lid = _resolve_annotation(a, model.locks)
            guards.add(lid)
            model.annotations.append({"path": m.path, "line": line,
                                      "lock": lid, "state": sid})
        resolved.append(_Mutation(sid, m.path, line, col, f.qualname,
                                  frozenset(guards), anns))

    # ---- spawns + reachability -----------------------------------------
    for m in mods:
        model.spawns.extend(m.spawns)

    reach_cache: dict = {}

    def reachable_from(bare: str) -> set:
        cached = reach_cache.get(bare)
        if cached is not None:
            return cached
        seen: set = set()
        frontier = list(all_index.get(bare, ()))
        seen.update(frontier)
        while frontier:
            q = frontier.pop()
            for cq in reach_callees(funcs[q]):
                if cq not in seen:
                    seen.add(cq)
                    frontier.append(cq)
        reach_cache[bare] = seen
        return seen

    spawn_roots: list = []   # (spawn, reachable qualname set)
    for sp in model.spawns:
        if not sp.target or sp.target == "<lambda>":
            # unknown body: treat the SPAWNING function's callees as the
            # root frontier (the lambda closes over them)
            spawn_roots.append((sp, reachable_from(
                sp.func.rsplit(".", 1)[-1])))
        else:
            spawn_roots.append((sp, reachable_from(sp.target)))

    def spawn_reaching(func_qual: str) -> list:
        return [sp for sp, reach in spawn_roots if func_qual in reach]

    # ---- rule: shared-mutation -----------------------------------------
    by_state: dict = {}
    for mu in resolved:
        by_state.setdefault(mu.state, []).append(mu)
    for sid, sites in sorted(by_state.items()):
        if model.states.get(sid) == "exempt":
            continue
        active = [mu for mu in sites
                  if not _is_suppressed(_pragmas_of(mods, mu.path),
                                        mu.line, "shared-mutation")]
        if not active:
            continue
        common = frozenset.intersection(*[mu.guards for mu in active])
        if common:
            continue
        roots: list = []
        for mu in active:
            roots.extend(spawn_reaching(mu.func))
        if not roots:
            continue    # only ever mutated on the spawning/main thread
        root_desc = sorted({f"{sp.kind}@{sp.path}:{sp.line}"
                            for sp in roots})[:3]
        guard_desc = sorted({lid for mu in active for lid in mu.guards})
        for mu in active:
            _emit(model, mods, "shared-mutation", mu.path, mu.line,
                  mu.col,
                  f"process-global '{sid}' is mutated on thread roots "
                  f"({', '.join(root_desc)}) with no lock common to all "
                  f"{len(active)} mutation site(s)"
                  + (f" (guards seen: {', '.join(guard_desc)})"
                     if guard_desc else " (no guards seen)")
                  + " — guard every site with one lock, use a "
                    "utils/counters.py locked counter, or annotate the "
                    "caller-held lock with '# guarded-by: <lock>'")

    # ---- rule: lock-order ----------------------------------------------
    # acq*: transitive lock acquisitions per function (fixpoint)
    direct_acq: dict = {}
    for qual, f in funcs.items():
        mod = by_name[f.modname]
        acc: set = set()

        def collect(wb: _WithBlock):
            lid = _resolve_lock(by_name, mod, wb.expr[1], wb.expr[0],
                                attr_index)
            if lid is not None:
                acc.add(lid)
            for nb in wb.nested:
                collect(nb)

        for wb in f.withs:
            collect(wb)
        direct_acq[qual] = acc

    trans_acq = {q: set(s) for q, s in direct_acq.items()}
    changed = True
    while changed:
        changed = False
        for qual, f in funcs.items():
            cur = trans_acq[qual]
            before = len(cur)
            for cq in callees(f):
                cur |= trans_acq.get(cq, ())
            if len(cur) != before:
                changed = True

    edges: dict = {}   # (A, B) -> (path, line, col)

    def add_edge(a: str, b: str, path: str, line: int, col: int) -> None:
        if a == b:
            return
        edges.setdefault((a, b), (path, line, col))

    for qual, f in funcs.items():
        mod = by_name[f.modname]

        def walk_wb(wb: _WithBlock):
            lid = _resolve_lock(by_name, mod, wb.expr[1], wb.expr[0],
                                attr_index)
            if lid is not None:
                for nb in wb.nested:
                    nlid = _resolve_lock(by_name, mod, nb.expr[1],
                                         nb.expr[0], attr_index)
                    if nlid is not None:
                        add_edge(lid, nlid, f.path, nb.line, nb.col)
                for (desc, line) in wb.calls:
                    for cq in resolve_call(f, desc):
                        for b in trans_acq.get(cq, ()):
                            add_edge(lid, b, f.path, line, 0)
            for nb in wb.nested:
                walk_wb(nb)

        for wb in f.withs:
            walk_wb(wb)

    # pragma'd edges leave both the findings AND the exported graph (a
    # suppressed edge is an assertion the nesting cannot happen)
    kept = {}
    for (a, b), (path, line, col) in edges.items():
        if _is_suppressed(_pragmas_of(mods, path), line, "lock-order"):
            continue
        kept[(a, b)] = (path, line, col)
    model.lock_edges = sorted(kept)

    graph_edges = set(kept)
    while True:
        cyc = find_cycle(graph_edges)
        if cyc is None:
            break
        cyc_edges = list(zip(cyc, cyc[1:]))
        site_edge = min(cyc_edges, key=lambda e: kept[e])
        path, line, col = kept[site_edge]
        _emit(model, mods, "lock-order", path, line, col,
              "lock-acquisition-order cycle (deadlock hazard): "
              + " -> ".join(cyc)
              + " — invert one nesting or suppress the impossible edge "
                "with '# race-lint: ignore[lock-order]' and a written "
                "justification", force=True)
        # break the cycle and keep scanning for independent ones
        graph_edges.discard(site_edge)

    # ---- rule: bare-submit ---------------------------------------------
    for sp in model.spawns:
        if sp.kind in ("scoped_submit", "par_map"):
            continue
        if not _in_dirs(sp.path, _OBS_DIRS):
            continue
        encl_bare = sp.func.rsplit(".", 1)[-1]
        if encl_bare in ("scoped_submit", "par_map"):
            continue    # the sanctioned context-propagating wrappers:
            # their own pool.submit/Thread IS the propagation mechanism
        if sp.kind == "submit":
            known_fn = sp.target_is_func and (
                sp.target == "<lambda>" or sp.target in all_index)
            if not known_fn:
                continue    # admission tickets etc., not an executor
            msg = (f"bare pool.submit({sp.target}) in obs-scoped code: "
                   "worker threads start with an EMPTY contextvars "
                   "context, so kernel launches lose query/operator "
                   "attribution and spans lose their query tag — route "
                   "through obs.metrics.scoped_submit")
        else:
            msg = ("bare threading.Thread in obs-scoped code: the new "
                   "thread drops the contextvar query scope "
                   "(attribution, span tags, kernel ledger); use "
                   "scoped_submit/par_map for query-scoped work, or "
                   "pragma with a justification for process-lifetime "
                   "service threads")
        _emit(model, mods, "bare-submit", sp.path, sp.line, sp.col, msg)

    # ---- rule: worker-reinit -------------------------------------------
    mutated_states = {mu.state for mu in resolved}
    for m in mods:
        if not _in_dirs(m.path, _WORKER_DIRS):
            continue
        reinit_names: set = set()
        for names in m.reinits.values():
            reinit_names.update(names)
        for sid, kind in sorted(m.states.items()):
            if kind == "exempt" or sid not in mutated_states:
                continue
            local = sid.rsplit(".", 1)[-1]
            if local in reinit_names:
                continue
            _emit(model, mods, "worker-reinit", m.path,
                  m.state_lines.get(sid, 1), 0,
                  f"process-global '{sid}' is mutated at runtime but has "
                  "no re-init path: a cluster worker re-imports this "
                  "module and the state silently diverges from the "
                  "driver's — add a reset()/configure() that restores "
                  "it, or pragma if per-process divergence is the "
                  "intended semantics")

    model.violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return model


def _pragmas_of(mods: list, path: str) -> dict:
    for m in mods:
        if m.path == path:
            return m.pragmas
    return {}


def _resolve_annotation(name: str, locks: set) -> str:
    if name in locks:
        return name
    tails = [lid for lid in locks if lid.endswith("." + name)
             or lid.rsplit(".", 1)[-1] == name]
    if len(tails) == 1:
        return tails[0]
    return name


def _emit(model: RepoModel, mods: list, rule: str, path: str, line: int,
          col: int, message: str, force: bool = False) -> None:
    if not force and _is_suppressed(_pragmas_of(mods, path), line, rule):
        return
    lines = next((m.lines for m in mods if m.path == path), [])
    snippet = lines[line - 1].strip() if 0 < line <= len(lines) else ""
    model.violations.append(Violation(rule, path, line, col, snippet,
                                      message))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def _iter_py(root: str):
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def _rel(path: str, root: str) -> str:
    try:
        return os.path.relpath(path, root).replace(os.sep, "/")
    except ValueError:
        return path


def build_model_from_sources(sources: dict) -> RepoModel:
    """Build the repo model from in-memory {relpath: source} — the
    fixture surface the rule-engine unit tests drive."""
    mods = []
    for relpath, src in sorted(sources.items()):
        m = _scan_module(src, relpath)
        if m is not None:
            mods.append(m)
    return _build(mods)


def build_model(paths, repo_root: str | None = None) -> RepoModel:
    paths = [paths] if isinstance(paths, str) else list(paths)
    repo_root = repo_root or os.path.commonpath(
        [os.path.abspath(p) for p in paths])
    if os.path.isfile(repo_root):
        repo_root = os.path.dirname(repo_root)
    sources: dict = {}
    for p in paths:
        for path in _iter_py(p):
            try:
                sources[_rel(os.path.abspath(path), repo_root)] = open(
                    path, encoding="utf-8").read()
            except OSError:
                continue
    return build_model_from_sources(sources)


def lint_sources(sources: dict) -> list:
    return build_model_from_sources(sources).violations


def lint_paths(paths, repo_root: str | None = None) -> list:
    return build_model(paths, repo_root=repo_root).violations


# ---------------------------------------------------------------------------
# Baseline (same shape and semantics as tpulint's)
# ---------------------------------------------------------------------------

def baseline_counts(violations) -> dict:
    counts: dict = {}
    for v in violations:
        counts[v.bucket] = counts.get(v.bucket, 0) + 1
    return counts


def write_baseline(path: str, violations) -> dict:
    data = {"version": 1, "counts": baseline_counts(violations)}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=1, sort_keys=True)
        f.write("\n")
    return data


def load_baseline(path: str) -> dict:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return dict(data.get("counts", {}))


def new_violations(violations, baseline: dict) -> list:
    """Violations beyond the baselined count per (file, rule) bucket."""
    by_bucket: dict = {}
    for v in violations:
        by_bucket.setdefault(v.bucket, []).append(v)
    out: list = []
    for bucket, vs in sorted(by_bucket.items()):
        allowed = baseline.get(bucket, 0)
        if len(vs) > allowed:
            out.extend(vs[allowed:])
    return out
