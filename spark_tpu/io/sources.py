"""Data sources.

Role of the reference's DataSource V2 read SPI (sqlcatj/connector/read/*.java:
Table/ScanBuilder/Batch/PartitionReaderFactory with SupportsPushDownRequiredColumns)
and the vectorized file formats (sqlx/datasources/parquet/
VectorizedParquetRecordReader.java). pyarrow provides the columnar decoders;
partitions map to parquet row-group ranges / file splits, and column pruning
is pushed into the reader.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Optional, Sequence

import pyarrow as pa

from ..types import StructType
from ..columnar.arrow import schema_from_arrow


class DataSource:
    """Minimal source contract: schema + partitioned columnar reads."""

    name: str = "source"
    schema: StructType
    estimated_rows: Optional[int] = None

    def num_partitions(self) -> int:
        raise NotImplementedError

    def read_partition(self, i: int, columns: Sequence[str] | None) -> pa.Table:
        raise NotImplementedError

    def __getstate__(self):
        # device-resident batch caches never travel to other processes
        state = dict(self.__dict__)
        state.pop("_device_cache", None)
        return state


class InMemorySource(DataSource):
    """An Arrow table split into N partitions (role of LocalTableScan +
    parallelize)."""

    name = "memory"

    def __init__(self, table: pa.Table, num_partitions: int = 1):
        self.table = table
        self._n = max(1, min(num_partitions, max(table.num_rows, 1)))
        self.schema = schema_from_arrow(table.schema)
        self.estimated_rows = table.num_rows

    def num_partitions(self) -> int:
        return self._n

    def read_partition(self, i: int, columns=None) -> pa.Table:
        n = self.table.num_rows
        per = -(-n // self._n) if n else 0
        lo = min(i * per, n)
        hi = min(lo + per, n)
        t = self.table.slice(lo, hi - lo)
        if columns is not None:
            t = t.select(list(columns))
        return t


class ParquetSource(DataSource):
    """Parquet scan; a partition is a (file, row-group range) split
    (reference: FileSourceScanExec partitioning over row groups)."""

    name = "parquet"

    def __init__(self, paths: str | Sequence[str],
                 target_partition_bytes: int = 128 << 20):
        import pyarrow.parquet as pq

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.parquet"),
                               recursive=True)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no parquet files under {paths}")
        self.files = files
        self._pq = pq
        # hive-style partition columns from directory names k=v
        # (reference: PartitioningAwareFileIndex partition discovery)
        self._part_values: dict[str, dict[str, str]] = {}
        part_keys: list[str] = []
        for fpath in files:
            vals: dict[str, str] = {}
            for seg in fpath.split(os.sep)[:-1]:
                if "=" in seg:
                    k, _, v = seg.partition("=")
                    vals[k] = v
                    if k not in part_keys:
                        part_keys.append(k)
            self._part_values[fpath] = vals
        self._part_keys = [k for k in part_keys
                           if all(k in self._part_values[f] for f in files)]
        md0 = pq.ParquetFile(files[0])
        self.schema = schema_from_arrow(md0.schema_arrow)
        for k in self._part_keys:
            self.schema = self.schema.add(k, _infer_partition_type(
                [self._part_values[f][k] for f in files]))
        # build splits: (file, rg_start, rg_end)
        self._splits: list[tuple[str, int, int]] = []
        total_rows = 0
        for fpath in files:
            f = pq.ParquetFile(fpath)
            nrg = f.metadata.num_row_groups
            total_rows += f.metadata.num_rows
            acc_bytes = 0
            start = 0
            for rg in range(nrg):
                acc_bytes += f.metadata.row_group(rg).total_byte_size
                if acc_bytes >= target_partition_bytes:
                    self._splits.append((fpath, start, rg + 1))
                    start = rg + 1
                    acc_bytes = 0
            if start < nrg:
                self._splits.append((fpath, start, nrg))
            if nrg == 0:
                self._splits.append((fpath, 0, 0))
        self.estimated_rows = total_rows

    def num_partitions(self) -> int:
        return len(self._splits)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        from ..types import to_arrow_type

        fpath, lo, hi = self._splits[i]
        f = self._pq.ParquetFile(fpath)
        pvals = self._part_values.get(fpath, {})
        want_part = [k for k in self._part_keys
                     if columns is None or k in columns]
        file_cols = None
        if columns is not None:
            file_cols = [c for c in columns if c not in self._part_keys]
        if hi <= lo:
            t = f.schema_arrow.empty_table()
            if file_cols is not None:
                t = t.select(file_cols)
        else:
            t = f.read_row_groups(list(range(lo, hi)), columns=file_cols)
        for k in want_part:
            at = to_arrow_type(self.schema[k].dataType)
            raw = pvals.get(k)
            v = None if raw == "__HIVE_DEFAULT_PARTITION__" else raw
            if v is not None and pa.types.is_integer(at):
                v = int(v)
            elif v is not None and pa.types.is_floating(at):
                v = float(v)
            t = t.append_column(k, pa.array([v] * t.num_rows, type=at))
        if columns is not None:
            t = t.select(list(columns))
        return t


def _infer_partition_type(values: list[str]):
    from ..types import float64, int64, string

    def ok(fn):
        try:
            for v in values:
                if v != "__HIVE_DEFAULT_PARTITION__":
                    fn(v)
            return True
        except ValueError:
            return False

    if ok(int):
        return int64
    if ok(float):
        return float64
    return string


class CSVSource(DataSource):
    name = "csv"

    def __init__(self, paths: str | Sequence[str], header: bool = True,
                 schema: StructType | None = None, delimiter: str = ","):
        import pyarrow.csv as pacsv

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        self.files = list(paths)
        self._pacsv = pacsv
        self.header = header
        self.delimiter = delimiter
        t = self._read(self.files[0])
        self.schema = schema or schema_from_arrow(t.schema)
        self.estimated_rows = None

    def _read(self, path: str) -> pa.Table:
        ropt = self._pacsv.ReadOptions(
            autogenerate_column_names=not self.header)
        popt = self._pacsv.ParseOptions(delimiter=self.delimiter)
        return self._pacsv.read_csv(path, read_options=ropt,
                                    parse_options=popt)

    def num_partitions(self) -> int:
        return len(self.files)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        t = self._read(self.files[i])
        if columns is not None:
            t = t.select(list(columns))
        return t


class JSONSource(DataSource):
    name = "json"

    def __init__(self, paths: str | Sequence[str]):
        import pyarrow.json as pajson

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        self.files = list(paths)
        self._pajson = pajson
        t = pajson.read_json(self.files[0])
        self.schema = schema_from_arrow(t.schema)
        self.estimated_rows = None

    def num_partitions(self) -> int:
        return len(self.files)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        t = self._pajson.read_json(self.files[i])
        if columns is not None:
            t = t.select(list(columns))
        return t
