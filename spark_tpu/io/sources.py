"""Data sources.

Role of the reference's DataSource V2 read SPI (sqlcatj/connector/read/*.java:
Table/ScanBuilder/Batch/PartitionReaderFactory with SupportsPushDownRequiredColumns)
and the vectorized file formats (sqlx/datasources/parquet/
VectorizedParquetRecordReader.java). pyarrow provides the columnar decoders;
partitions map to parquet row-group ranges / file splits, and column pruning
is pushed into the reader.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Optional, Sequence

import pyarrow as pa

from ..types import StructType
from ..columnar.arrow import schema_from_arrow


class DataSource:
    """Minimal source contract: schema + partitioned columnar reads."""

    name: str = "source"
    schema: StructType
    estimated_rows: Optional[int] = None

    def num_partitions(self) -> int:
        raise NotImplementedError

    def read_partition(self, i: int, columns: Sequence[str] | None) -> pa.Table:
        raise NotImplementedError

    def __getstate__(self):
        # device-resident batch caches never travel to other processes
        state = dict(self.__dict__)
        state.pop("_device_cache", None)
        return state


class SupportsPushDownFilters:
    """DSv2 pushdown mixin (reference: sql/catalyst connector/read/
    SupportsPushDownFilters.java). Predicates arrive as the engine's
    source-filter currency — (col, op, value) with op in
    =,<,<=,>,>=,in — and the source returns (new_source, residual):
    a clone that applies what it accepted plus the predicates the
    ENGINE must still evaluate. Functional style (clone, don't mutate)
    so plan caching and retries stay safe."""

    def push_filters(self, predicates: list) -> tuple["DataSource", list]:
        raise NotImplementedError


class SupportsPushDownLimit:
    """reference: SupportsPushDownLimit.java. Returns a clone applying
    the PER-PARTITION limit, or None when it cannot."""

    def push_limit(self, n: int) -> "DataSource | None":
        raise NotImplementedError


class SupportsPushDownAggregation:
    """reference: SupportsPushDownAggregates.java. `groupings` is a list
    of column names; `aggs` a list of (fn, col|None, out_name) with fn
    in count/sum/min/max/avg (col None = count(*)). Returns a clone
    whose scan yields the FINAL aggregated rows (columns named
    groupings + out_names), or None to decline."""

    def push_aggregation(self, groupings: list, aggs: list) \
            -> "DataSource | None":
        raise NotImplementedError


UNKNOWN_PARTITION_VALUE = object()
"""Sentinel: a source cannot tell which partition-column value a split
holds (DPP must then read the split)."""


class InMemorySource(DataSource):
    """An Arrow table split into N partitions (role of LocalTableScan +
    parallelize)."""

    name = "memory"

    def __init__(self, table: pa.Table, num_partitions: int = 1):
        self.table = table
        self._n = max(1, min(num_partitions, max(table.num_rows, 1)))
        self.schema = schema_from_arrow(table.schema)
        self.estimated_rows = table.num_rows

    def num_partitions(self) -> int:
        return self._n

    def read_partition(self, i: int, columns=None) -> pa.Table:
        n = self.table.num_rows
        per = -(-n // self._n) if n else 0
        lo = min(i * per, n)
        hi = min(lo + per, n)
        t = self.table.slice(lo, hi - lo)
        if columns is not None:
            t = t.select(list(columns))
        return t


class ParquetSource(DataSource):
    """Parquet scan; a partition is a (file, row-group range) split
    (reference: FileSourceScanExec partitioning over row groups)."""

    name = "parquet"

    def __init__(self, paths: str | Sequence[str],
                 target_partition_bytes: int = 128 << 20):
        import pyarrow.parquet as pq

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.parquet"),
                               recursive=True)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no parquet files under {paths}")
        self.files = files
        self._pq = pq
        # hive-style partition columns from directory names k=v
        # (reference: PartitioningAwareFileIndex partition discovery)
        self._part_values: dict[str, dict[str, str]] = {}
        part_keys: list[str] = []
        for fpath in files:
            vals: dict[str, str] = {}
            for seg in fpath.split(os.sep)[:-1]:
                if "=" in seg:
                    k, _, v = seg.partition("=")
                    vals[k] = v
                    if k not in part_keys:
                        part_keys.append(k)
            self._part_values[fpath] = vals
        self._part_keys = [k for k in part_keys
                           if all(k in self._part_values[f] for f in files)]
        md0 = pq.ParquetFile(files[0])
        self.schema = schema_from_arrow(md0.schema_arrow)
        for k in self._part_keys:
            self.schema = self.schema.add(k, _infer_partition_type(
                [self._part_values[f][k] for f in files]))
        # build splits: (file, rg_start, rg_end)
        self._splits: list[tuple[str, int, int]] = []
        total_rows = 0
        for fpath in files:
            f = pq.ParquetFile(fpath)
            nrg = f.metadata.num_row_groups
            total_rows += f.metadata.num_rows
            acc_bytes = 0
            start = 0
            for rg in range(nrg):
                acc_bytes += f.metadata.row_group(rg).total_byte_size
                if acc_bytes >= target_partition_bytes:
                    self._splits.append((fpath, start, rg + 1))
                    start = rg + 1
                    acc_bytes = 0
            if start < nrg:
                self._splits.append((fpath, start, nrg))
            if nrg == 0:
                self._splits.append((fpath, 0, 0))
        self.estimated_rows = total_rows

    def num_partitions(self) -> int:
        return len(self._splits)

    # --- plan-time statistics ----------------------------------------------
    def _footer(self, fpath: str):
        cache = self.__dict__.setdefault("_md_cache", {})
        md = cache.get(fpath)
        if md is None:
            md = cache[fpath] = self._pq.ParquetFile(fpath).metadata
        return md

    def plan_time_rows(self) -> Optional[int]:
        """Exact row count of the CURRENT split set from footer metadata
        (row-group counts; no data read). Prune-aware — a `pruned()`
        clone reports only its kept splits. Ends the whole-tier's
        categorical exclusion of external scans
        (physical/whole_query._external_scan_rows)."""
        total = 0
        for (fpath, lo, hi) in self._splits:
            md = self._footer(fpath)
            for rg in range(lo, hi):
                total += md.row_group(rg).num_rows
        return total

    def plan_time_column_range(self, name: str) -> Optional[tuple]:
        """Footer (min, max) of a data column across the CURRENT splits,
        coerced to the engine's device domain (dates → epoch days).
        None when the column is a hive-partition column or any row
        group lacks statistics — never guess."""
        lo = hi = None
        for (fpath, a, b) in self._splits:
            if b <= a:
                continue
            md = self._footer(fpath)
            ci = next((i for i in range(md.num_columns)
                       if md.schema.column(i).name == name), None)
            if ci is None:
                return None
            for rg in range(a, b):
                st = md.row_group(rg).column(ci).statistics
                if st is None or not st.has_min_max:
                    return None
                mn, mx = _stat_coerce(st.min), _stat_coerce(st.max)
                lo = mn if lo is None else min(lo, mn)
                hi = mx if hi is None else max(hi, mx)
        return None if lo is None else (lo, hi)

    # --- predicate pruning -------------------------------------------------
    def pruned(self, predicates) -> "ParquetSource":
        """A clone reading only splits that can satisfy `predicates`
        (each: (col, op, value) with op in =,<,<=,>,>=,in).

        Partition columns prune whole files from the hive directory values
        (reference: PartitioningAwareFileIndex.listFiles pruning); data
        columns prune by row-group min/max statistics (reference:
        VectorizedParquetRecordReader / ParquetFileFormat row-group filter).
        Conservative: a split is kept unless a predicate proves it empty."""
        part_preds = [p for p in predicates if p[0] in self._part_keys]
        data_preds = [p for p in predicates if p[0] not in self._part_keys]
        keep: list[tuple[str, int, int]] = []
        dropped_files: set[str] = set()
        # footer metadata survives on the source: repeated plans of filtered
        # queries must not re-open every file
        stats_cache = self.__dict__.setdefault("_md_cache", {})
        for (fpath, lo, hi) in self._splits:
            if fpath in dropped_files:
                continue
            vals = self._part_values.get(fpath, {})
            if part_preds and not all(
                    self._part_match(vals.get(c), c, op, v)
                    for (c, op, v) in part_preds):
                dropped_files.add(fpath)
                continue
            if not data_preds or hi <= lo:
                keep.append((fpath, lo, hi))
                continue
            md = stats_cache.get(fpath)
            if md is None:
                md = stats_cache[fpath] = self._pq.ParquetFile(fpath).metadata
            name_to_idx = {md.schema.column(ci).name: ci
                           for ci in range(md.num_columns)}
            run_start = None  # merge contiguous kept row groups so a
            # non-selective predicate keeps the original split granularity
            for rg in range(lo, hi):
                rgm = md.row_group(rg)
                ok = True
                for (c, op, v) in data_preds:
                    ci = name_to_idx.get(c)
                    if ci is None:
                        continue
                    st = rgm.column(ci).statistics
                    if st is None or not st.has_min_max:
                        continue
                    if not _range_overlaps(st.min, st.max, op, v):
                        ok = False
                        break
                if ok and run_start is None:
                    run_start = rg
                elif not ok and run_start is not None:
                    keep.append((fpath, run_start, rg))
                    run_start = None
            if run_start is not None:
                keep.append((fpath, run_start, hi))
        if keep == self._splits:
            return self  # nothing pruned — keep the (cached) source
        import copy

        clone = copy.copy(self)
        clone._splits = keep or [(self.files[0], 0, 0)]
        # the shallow copy shares the device cache, but its keys are split
        # INDICES — different split lists must not alias each other's data
        clone.__dict__.pop("_device_cache", None)
        return clone

    def split_partition_value(self, i: int, col: str):
        """Typed hive-partition value of split i for `col`; None for the
        null partition; UNKNOWN_PARTITION_VALUE when not derivable."""
        if col not in self._part_keys:
            return UNKNOWN_PARTITION_VALUE
        fpath = self._splits[i][0]
        raw = self._part_values.get(fpath, {}).get(col)
        if raw is None:
            return UNKNOWN_PARTITION_VALUE
        if raw == "__HIVE_DEFAULT_PARTITION__":
            return None
        from ..types import float64, int64

        dt = self.schema[col].dataType
        return int(raw) if dt is int64 else \
            float(raw) if dt is float64 else raw

    def _part_match(self, raw: str | None, col: str, op: str, v) -> bool:
        if raw is None or raw == "__HIVE_DEFAULT_PARTITION__":
            return False  # null partition never equals a literal
        from ..types import float64, int64

        dt = self.schema[col].dataType
        pv = int(raw) if dt is int64 else float(raw) if dt is float64 else raw
        return _range_overlaps(pv, pv, op, v)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        from ..types import StringType, to_arrow_type

        fpath, lo, hi = self._splits[i]
        # keep parquet DICTIONARY PAGES encoded end to end: string
        # columns decode to pa.DictionaryArray (codes + dictionary)
        # straight from the file, and columnar ingest ships those codes
        # to HBM without ever materializing row values (compressed
        # execution; _chunked_to_numpy's is_dictionary branch)
        dict_cols = [f.name for f in self.schema.fields
                     if isinstance(f.dataType, StringType)
                     and f.name not in self._part_keys]
        f = self._pq.ParquetFile(fpath, read_dictionary=dict_cols or None)
        pvals = self._part_values.get(fpath, {})
        want_part = [k for k in self._part_keys
                     if columns is None or k in columns]
        file_cols = None
        if columns is not None:
            file_cols = [c for c in columns if c not in self._part_keys]
        if hi <= lo:
            t = f.schema_arrow.empty_table()
            if file_cols is not None:
                t = t.select(file_cols)
        else:
            t = f.read_row_groups(list(range(lo, hi)), columns=file_cols)
        for k in want_part:
            at = to_arrow_type(self.schema[k].dataType)
            raw = pvals.get(k)
            v = None if raw == "__HIVE_DEFAULT_PARTITION__" else raw
            if v is not None and pa.types.is_integer(at):
                v = int(v)
            elif v is not None and pa.types.is_floating(at):
                v = float(v)
            t = t.append_column(k, pa.array([v] * t.num_rows, type=at))
        if columns is not None:
            t = t.select(list(columns))
        return t


def _stat_coerce(x):
    """Normalize parquet-statistics values into the engine's device domain
    (dates → epoch days, timestamps → epoch micros) so they compare against
    Literal values."""
    import datetime as _dt

    if isinstance(x, _dt.datetime):
        epoch = _dt.datetime(1970, 1, 1, tzinfo=x.tzinfo)
        return int((x - epoch).total_seconds() * 1_000_000)
    if isinstance(x, _dt.date):
        return (x - _dt.date(1970, 1, 1)).days
    if isinstance(x, bytes):
        try:
            return x.decode("utf-8")
        except UnicodeDecodeError:
            return x
    return x


def _range_overlaps(lo, hi, op: str, v) -> bool:
    """Can a value in [lo, hi] satisfy `x op v`? Conservative true on any
    type mismatch (mirrors the reference's ParquetFilters nullability/type
    guards)."""
    lo, hi = _stat_coerce(lo), _stat_coerce(hi)
    v = [_stat_coerce(x) for x in v] if op == "in" else _stat_coerce(v)
    try:
        if op == "=":
            return lo <= v <= hi
        if op == "<":
            return lo < v
        if op == "<=":
            return lo <= v
        if op == ">":
            return hi > v
        if op == ">=":
            return hi >= v
        if op == "in":
            return any(lo <= x <= hi for x in v)
    except TypeError:
        return True
    return True


def _infer_partition_type(values: list[str]):
    from ..types import float64, int64, string

    def ok(fn):
        try:
            for v in values:
                if v != "__HIVE_DEFAULT_PARTITION__":
                    fn(v)
            return True
        except ValueError:
            return False

    if ok(int):
        return int64
    if ok(float):
        return float64
    return string


class CSVSource(DataSource):
    name = "csv"

    def __init__(self, paths: str | Sequence[str], header: bool = True,
                 schema: StructType | None = None, delimiter: str = ","):
        import pyarrow.csv as pacsv

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        self.files = list(paths)
        self._pacsv = pacsv
        self.header = header
        self.delimiter = delimiter
        t = self._read(self.files[0])
        self.schema = schema or schema_from_arrow(t.schema)
        self.estimated_rows = None

    def _read(self, path: str) -> pa.Table:
        ropt = self._pacsv.ReadOptions(
            autogenerate_column_names=not self.header)
        popt = self._pacsv.ParseOptions(delimiter=self.delimiter)
        return self._pacsv.read_csv(path, read_options=ropt,
                                    parse_options=popt)

    def num_partitions(self) -> int:
        return len(self.files)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        t = self._read(self.files[i])
        if columns is not None:
            t = t.select(list(columns))
        return t


class JSONSource(DataSource):
    name = "json"

    def __init__(self, paths: str | Sequence[str]):
        import pyarrow.json as pajson

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        self.files = list(paths)
        self._pajson = pajson
        t = pajson.read_json(self.files[0])
        self.schema = schema_from_arrow(t.schema)
        self.estimated_rows = None

    def num_partitions(self) -> int:
        return len(self.files)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        t = self._pajson.read_json(self.files[i])
        if columns is not None:
            t = t.select(list(columns))
        return t


class ORCSource(DataSource):
    """ORC scan; a partition is a (file, stripe range) split (reference:
    sqlx/datasources/orc/OrcFileFormat.scala + OrcColumnarBatchReader —
    pyarrow's ORC reader supplies the vectorized decode)."""

    name = "orc"

    def __init__(self, paths: str | Sequence[str]):
        import pyarrow.orc as po

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.orc"),
                               recursive=True)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no orc files under {paths}")
        self.files = files
        self._po = po
        f0 = po.ORCFile(files[0])
        self.schema = schema_from_arrow(f0.schema)
        self.estimated_rows = sum(po.ORCFile(f).nrows for f in files)
        # one split per (file, stripe): stripes are ORC's row groups
        self._splits: list[tuple[str, int]] = []
        for fpath in files:
            n = po.ORCFile(fpath).nstripes
            for s in range(max(n, 1)):
                self._splits.append((fpath, s))

    def num_partitions(self) -> int:
        return len(self._splits)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        fpath, stripe = self._splits[i]
        f = self._po.ORCFile(fpath)
        cols = list(columns) if columns is not None else None
        if f.nstripes == 0:
            return f.read(columns=cols)
        return f.read_stripe(stripe, columns=cols) if cols is not None \
            else f.read_stripe(stripe)


class AvroSource(DataSource):
    """Avro container-file scan, one partition per file (reference:
    connector/avro/AvroFileFormat.scala; decode in io/avro.py)."""

    name = "avro"

    def __init__(self, paths: str | Sequence[str]):
        from .avro import read_avro

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.avro"),
                               recursive=True)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no avro files under {paths}")
        self.files = files
        self._read = read_avro
        # schema from file 0 only; partitions decode on demand (no
        # whole-dataset cache — a directory larger than RAM must stream)
        self.schema = schema_from_arrow(read_avro(files[0]).schema)
        self.estimated_rows = None

    def num_partitions(self) -> int:
        return len(self.files)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        t = self._read(self.files[i])
        if columns is not None:
            t = t.select(list(columns))
        return t


class XMLSource(DataSource):
    """XML scan: one row per `rowTag` element; child elements become
    string columns (reference: connector/xml — XmlFileFormat, rowTag
    option). Types stay strings like the reference's schema-less mode;
    cast downstream."""

    name = "xml"

    def __init__(self, paths: str | Sequence[str], row_tag: str = "ROW"):
        import xml.etree.ElementTree as ET

        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*.xml"),
                               recursive=True)))
            else:
                files.append(p)
        if not files:
            raise FileNotFoundError(f"no xml files under {paths}")
        self.files = files
        self.row_tag = row_tag
        self._et = ET
        # schema inference spans ALL files (a tag present only in a
        # later file must still become a column, like the reference's
        # whole-input XML schema inference)
        names: list[str] = []
        seen = set()
        for f in files:
            for r in self._rows(f):
                for k in r:
                    if k not in seen:
                        seen.add(k)
                        names.append(k)
        self._names = names
        self.schema = schema_from_arrow(pa.schema(
            [(n, pa.string()) for n in names]))
        self.estimated_rows = None

    def _rows(self, path: str) -> list[dict]:
        root = self._et.parse(path).getroot()
        elems = root.iter(self.row_tag)
        out = []
        for el in elems:
            row: dict = {}
            # attributes as _attr columns, children as named columns
            for k, v in el.attrib.items():
                row[f"_{k}"] = v
            for child in el:
                row[child.tag] = (child.text or "").strip() or None
            if row:
                out.append(row)
        return out

    def num_partitions(self) -> int:
        return len(self.files)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        rows = self._rows(self.files[i])
        names = list(columns) if columns is not None else self._names
        return pa.table({n: pa.array([r.get(n) for r in rows],
                                     pa.string()) for n in names})


class JDBCSource(DataSource, SupportsPushDownFilters,
                 SupportsPushDownLimit, SupportsPushDownAggregation):
    """Database scan over a DB-API connection (reference:
    sqlx/datasources/jdbc/JDBCRDD.scala — column pruning and partitioned
    reads via `partitionColumn/lowerBound/upperBound/numPartitions`
    WHERE-range predicates; JDBCScanBuilder for the v2 pushdown SPI:
    WHERE conjuncts, LIMIT, and whole-query aggregation all execute
    REMOTELY in the database). URLs: `jdbc:sqlite:<path>` ships in-tree
    (stdlib driver); other DB-API drivers plug in via `connector`.
    `last_sql` records the most recent generated statement (tests
    assert remote execution on it)."""

    name = "jdbc"

    def __init__(self, url: str, table: str,
                 partition_column: str | None = None,
                 lower_bound=None, upper_bound=None,
                 num_partitions: int = 1, connector=None):
        self.url = url
        self.table = table
        self.partition_column = partition_column
        self._connector = connector
        self.num_parts = max(1, int(num_partitions)) \
            if partition_column else 1
        probe = self._query(f"SELECT * FROM {table} LIMIT 1")
        self.schema = schema_from_arrow(probe.schema)
        if partition_column and (lower_bound is None or upper_bound is None):
            bounds = self._query(
                f"SELECT min({partition_column}), max({partition_column}) "
                f"FROM {table}")
            lower_bound = bounds.column(0)[0].as_py() \
                if lower_bound is None else lower_bound
            upper_bound = bounds.column(1)[0].as_py() \
                if upper_bound is None else upper_bound
        if not (isinstance(lower_bound, (int, float))
                and isinstance(upper_bound, (int, float))):
            # empty table (NULL bounds) or non-numeric partition column:
            # a range split is impossible — read as one partition
            # (reference: JDBCRelation.columnPartition requires numeric/
            # date bounds)
            self.num_parts = 1
            lower_bound = upper_bound = None
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.estimated_rows = None
        self._where: list[str] = []     # pushed WHERE conjuncts
        self._limit: int | None = None  # pushed per-partition LIMIT
        self._agg_sql: str | None = None
        self.last_sql: str | None = None

    def _connect(self):
        if self._connector is not None:
            return self._connector()
        if self.url.startswith("jdbc:sqlite:") or \
                self.url.startswith("sqlite:"):
            import sqlite3

            path = self.url.split("sqlite:", 1)[1].lstrip("/")
            if not path.startswith(":"):
                path = "/" + path
            return sqlite3.connect(path)
        raise ValueError(f"no driver for {self.url!r}; pass connector=")

    def _query(self, sql: str) -> pa.Table:
        conn = self._connect()
        try:
            cur = conn.execute(sql)
            names = [d[0] for d in cur.description]
            rows = cur.fetchall()
        finally:
            conn.close()
        cols = list(zip(*rows)) if rows else [[] for _ in names]
        return pa.table({n: list(c) for n, c in zip(names, cols)})

    def num_partitions(self) -> int:
        return self.num_parts

    # -- DSv2 pushdown SPI ----------------------------------------------
    @staticmethod
    def _sql_literal(v) -> str | None:
        """SQL literal rendering; None = untranslatable (stays an
        engine-side residual)."""
        import math

        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        if isinstance(v, bool):
            return "1" if v else "0"
        if isinstance(v, int):
            return str(v)
        if isinstance(v, float):
            if math.isnan(v) or math.isinf(v):
                return None
            return repr(v)
        return None    # bytes, dates-as-objects, nested values …

    @staticmethod
    def _quote_ident(name: str) -> str:
        return '"' + str(name).replace('"', '""') + '"'

    def _clone(self) -> "JDBCSource":
        import copy

        c = copy.copy(self)
        c.__dict__.pop("_device_cache", None)
        c._where = list(self._where)
        return c

    def push_filters(self, predicates):
        """Translatable predicates execute in the database. For the
        in-tree sqlite driver remote comparison semantics are exact, so
        consumed predicates leave no residual; a PLUGGABLE connector's
        collation/comparison rules are unknown, so everything pushed is
        ALSO returned as residual and the engine re-checks (the
        conservative contract parquet's row-group stats use)."""
        c = self._clone()
        residual = []
        for pred in predicates:
            col, op, val = pred
            if op == "in":
                lits = [self._sql_literal(v) for v in val]
                if any(x is None for x in lits):
                    residual.append(pred)
                    continue
                c._where.append(
                    f"{self._quote_ident(col)} IN ({', '.join(lits)})")
            else:
                lit = self._sql_literal(val)
                if lit is None:
                    residual.append(pred)
                    continue
                c._where.append(f"{self._quote_ident(col)} {op} {lit}")
            if self._connector is not None:
                residual.append(pred)   # pushed for IO, re-checked
        return c, residual

    def push_limit(self, n: int):
        c = self._clone()
        c._limit = n if self._limit is None else min(self._limit, n)
        return c

    def push_aggregation(self, groupings, aggs):
        """Whole-query aggregation runs in the database; only for
        single-partition scans (a range-split scan would aggregate each
        split independently — wrong for non-decomposable finals). The
        result schema derives statically from the source schema — no
        probe query against the remote database at planning time."""
        from ..types import IntegralType, StructField, float64, int64

        if self.num_parts > 1 or self._limit is not None:
            return None
        out_names = [out for _, _, out in aggs]
        if len(set(out_names) | set(groupings)) != \
                len(out_names) + len(groupings):
            return None     # name collision would fold columns silently
        by_name = {f.name: f.dataType for f in self.schema.fields}
        cols, fields = [], []
        for g in groupings:
            if g not in by_name:
                return None
            cols.append(self._quote_ident(g))
            fields.append(StructField(str(g), by_name[g], True))
        for fn, col, out in aggs:
            if fn not in ("count", "sum", "min", "max", "avg"):
                return None
            if col is not None and col not in by_name:
                return None
            arg = "*" if col is None else self._quote_ident(col)
            cols.append(f"{fn}({arg}) AS {self._quote_ident(out)}")
            if fn == "count":
                dt = int64
            elif fn == "avg":
                dt = float64
            elif fn == "sum":
                dt = int64 if isinstance(by_name[col], IntegralType) \
                    else float64
            else:
                dt = by_name[col]
            fields.append(StructField(str(out), dt, True))
        sql = f"SELECT {', '.join(cols)} FROM {self.table}"
        if self._where:
            sql += " WHERE " + " AND ".join(self._where)
        if groupings:
            sql += " GROUP BY " + ", ".join(self._quote_ident(g)
                                            for g in groupings)
        from ..types import StructType

        c = self._clone()
        c._agg_sql = sql
        c.num_parts = 1
        c.schema = StructType(tuple(fields))
        c.estimated_rows = None
        return c

    def generated_sql(self, i: int, columns=None) -> str:
        """The exact statement partition `i` executes remotely."""
        if self._agg_sql is not None:
            return self._agg_sql
        proj = ", ".join(columns) if columns else "*"
        sql = f"SELECT {proj} FROM {self.table}"
        clauses = list(self._where)
        if self.partition_column and self.num_parts > 1:
            lo, hi = self.lower_bound, self.upper_bound
            step = (hi - lo) / self.num_parts
            a = lo + step * i
            b = lo + step * (i + 1)
            c = self.partition_column
            if i == 0:
                clauses.append(f"({c} < {b} OR {c} IS NULL)")
            elif i == self.num_parts - 1:
                clauses.append(f"{c} >= {a}")
            else:
                clauses.append(f"({c} >= {a} AND {c} < {b})")
        if clauses:
            sql += " WHERE " + " AND ".join(clauses)
        if self._limit is not None:
            sql += f" LIMIT {self._limit}"
        return sql

    def read_partition(self, i: int, columns=None) -> pa.Table:
        sql = self.generated_sql(i, columns)
        self.last_sql = sql
        t = self._query(sql)
        if columns is not None and t.column_names != list(columns) and \
                set(columns) <= set(t.column_names):
            t = t.select(list(columns))
        return t


class TextSource(DataSource):
    """Line-per-row text scan, one `value` string column (reference:
    sqlx/datasources/text/TextFileFormat.scala)."""

    name = "text"

    def __init__(self, paths: str | Sequence[str]):
        if isinstance(paths, str):
            paths = sorted(_glob.glob(paths)) if any(
                ch in paths for ch in "*?[") else [paths]
        files: list[str] = []
        for p in paths:
            if os.path.isdir(p):
                files.extend(sorted(
                    _glob.glob(os.path.join(p, "**", "*"), recursive=True)))
            else:
                files.append(p)
        self.files = [f for f in files if os.path.isfile(f)]
        if not self.files:
            raise FileNotFoundError(f"no text files under {paths}")
        from ..types import StructField, string

        self.schema = StructType([StructField("value", string, True)])
        self.estimated_rows = None

    def num_partitions(self) -> int:
        return len(self.files)

    def read_partition(self, i: int, columns=None) -> pa.Table:
        with open(self.files[i], "r", errors="replace") as f:
            lines = f.read().splitlines()
        t = pa.table({"value": pa.array(lines, pa.string())})
        if columns is not None:
            t = t.select(list(columns))
        return t
