"""File-output commit protocol with exactly-one-commit arbitration.

Role of the reference's OutputCommitCoordinator
(core/scheduler/OutputCommitCoordinator.scala — the driver-side arbiter
that lets exactly one attempt of each task commit) combined with the
HadoopMapReduceCommitProtocol file choreography
(core/internal/io/HadoopMapReduceCommitProtocol.scala): task attempts
write under `<path>/_temporary/<job_id>/<task>-<attempt>/`, ask the
coordinator for permission, and only the granted attempt's files are
renamed into the final layout at job commit; everything else is swept.

The arbitration must hold under concurrent ATTEMPTS — speculative
execution launches two attempts of one task and both may race
canCommit; rename(2) is atomic on one host, and in the multi-host
deployment the coordinator lives on the driver where all control RPC
already lands, exactly the reference's arrangement.
"""

from __future__ import annotations

import os
import shutil
import threading
import uuid


class CommitDeniedError(RuntimeError):
    """This attempt lost the commit race (reference:
    TaskCommitDenied → task retries are NOT counted as failures)."""


class OutputCommitCoordinator:
    """task_id → winning attempt_id; first canCommit wins, later
    attempts of the same task are denied (OutputCommitCoordinator.scala
    handleAskPermissionToCommit)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._winners: dict[int, str] = {}

    def can_commit(self, task_id: int, attempt_id: str) -> bool:
        with self._lock:
            winner = self._winners.setdefault(task_id, attempt_id)
            return winner == attempt_id

    def winner(self, task_id: int) -> str | None:
        with self._lock:
            return self._winners.get(task_id)


class FileCommitProtocol:
    """Job-scoped two-phase file commit over a directory output."""

    def __init__(self, path: str,
                 coordinator: OutputCommitCoordinator | None = None):
        self.path = path
        self.job_id = uuid.uuid4().hex[:12]
        self.coordinator = coordinator or OutputCommitCoordinator()
        self._staging = os.path.join(path, "_temporary", self.job_id)

    # -- task side ------------------------------------------------------
    def new_task_attempt(self, task_id: int) -> "TaskAttempt":
        return TaskAttempt(self, task_id, uuid.uuid4().hex[:8])

    # -- job side -------------------------------------------------------
    def setup_job(self) -> None:
        os.makedirs(self._staging, exist_ok=True)

    def commit_job(self) -> None:
        """Move every committed attempt's files into the final layout
        (atomic per-file rename), drop staging, stamp _SUCCESS."""
        committed = os.path.join(self._staging, "_committed")
        if os.path.isdir(committed):
            for task_dir in sorted(os.listdir(committed)):
                src_root = os.path.join(committed, task_dir)
                for root, _dirs, files in os.walk(src_root):
                    rel = os.path.relpath(root, src_root)
                    dst_dir = self.path if rel == "." else \
                        os.path.join(self.path, rel)
                    os.makedirs(dst_dir, exist_ok=True)
                    for f in files:
                        os.replace(os.path.join(root, f),
                                   os.path.join(dst_dir, f))
        shutil.rmtree(os.path.join(self.path, "_temporary"),
                      ignore_errors=True)
        with open(os.path.join(self.path, "_SUCCESS"), "w"):
            pass

    def abort_job(self) -> None:
        shutil.rmtree(os.path.join(self.path, "_temporary"),
                      ignore_errors=True)


class TaskAttempt:
    """One attempt's staging dir + the commit handshake."""

    def __init__(self, protocol: FileCommitProtocol, task_id: int,
                 attempt_id: str):
        self.protocol = protocol
        self.task_id = task_id
        self.attempt_id = attempt_id
        self.dir = os.path.join(protocol._staging,
                                f"task-{task_id}-attempt-{attempt_id}")
        os.makedirs(self.dir, exist_ok=True)

    def path_for(self, *rel: str) -> str:
        """Final-layout-relative path inside this attempt's staging dir
        (partition subdirs included)."""
        p = os.path.join(self.dir, *rel)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        return p

    def commit(self) -> None:
        """Ask the coordinator; the winning attempt's dir moves (one
        atomic rename) under _committed/, losers raise CommitDenied and
        sweep themselves."""
        if not self.protocol.coordinator.can_commit(self.task_id,
                                                    self.attempt_id):
            self.abort()
            raise CommitDeniedError(
                f"task {self.task_id}: attempt {self.attempt_id} lost to "
                f"{self.protocol.coordinator.winner(self.task_id)}")
        dst = os.path.join(self.protocol._staging, "_committed",
                           f"task-{self.task_id}")
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(self.dir, dst)

    def abort(self) -> None:
        shutil.rmtree(self.dir, ignore_errors=True)
