"""Avro container-file reader/writer (pure Python, no external codec).

Role of the reference's Avro connector (connector/avro/ —
AvroFileFormat, AvroSerializer/Deserializer). Scope: the Avro 1.x
object-container format with null or deflate codec, record schemas of
primitive fields (null/boolean/int/long/float/double/string/bytes) and
their nullable unions — the shape Spark writes for flat DataFrames.
Arrow tables in, Arrow tables out; the columnar engine never sees the
row-oriented wire format.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

import pyarrow as pa

_MAGIC = b"Obj\x01"


# -- binary primitives (Avro spec: zigzag varints) --------------------------

def _zigzag_encode(n: int) -> bytes:
    z = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _zigzag_decode(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated avro varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not v & 0x80:
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)


def _write_bytes(out: bytearray, b: bytes) -> None:
    out += _zigzag_encode(len(b))
    out += b


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _zigzag_decode(buf)
    return buf.read(n)


# -- schema mapping ---------------------------------------------------------

_ARROW_TO_AVRO = [
    (pa.types.is_boolean, "boolean"),
    (pa.types.is_int32, "int"),
    (pa.types.is_integer, "long"),
    (pa.types.is_float32, "float"),
    (pa.types.is_floating, "double"),
    (pa.types.is_binary, "bytes"),
    (pa.types.is_string, "string"),
    (pa.types.is_large_string, "string"),
    (pa.types.is_date32, "int"),
    (pa.types.is_timestamp, "long"),
]

_AVRO_TO_ARROW = {
    "boolean": pa.bool_(), "int": pa.int32(), "long": pa.int64(),
    "float": pa.float32(), "double": pa.float64(),
    "string": pa.string(), "bytes": pa.binary(), "null": pa.null(),
}


def _avro_type(t: pa.DataType):
    """Avro schema for one arrow type — a string primitive or a
    logical-typed dict (date / timestamp-micros, like the reference's
    AvroSerializer)."""
    if pa.types.is_date32(t):
        return {"type": "int", "logicalType": "date"}
    if pa.types.is_timestamp(t):
        return {"type": "long", "logicalType": "timestamp-micros"}
    for pred, name in _ARROW_TO_AVRO:
        if pred(t):
            return name
    raise ValueError(f"avro writer: unsupported arrow type {t}")


def _schema_json(schema: pa.Schema) -> str:
    fields = []
    for f in schema:
        at = _avro_type(f.type)
        fields.append({"name": f.name,
                       "type": ["null", at] if f.nullable else at})
    return json.dumps({"type": "record", "name": "topLevelRecord",
                       "fields": fields})


class _FieldSpec:
    __slots__ = ("name", "prim", "logical", "null_branch")

    def __init__(self, name, prim, logical, null_branch):
        self.name = name
        self.prim = prim            # avro primitive the bytes encode
        self.logical = logical      # None | 'date' | 'timestamp-micros'
        self.null_branch = null_branch  # union index of "null", or None

    @property
    def arrow_type(self):
        if self.logical == "date":
            return pa.date32()
        if self.logical == "timestamp-micros":
            return pa.timestamp("us")
        return _AVRO_TO_ARROW[self.prim]


def _one_type(t):
    """(primitive, logical) from a string or logical-typed dict."""
    if isinstance(t, dict):
        return t["type"], t.get("logicalType")
    return t, None


def _field_types(schema_json: str) -> list[_FieldSpec]:
    sch = json.loads(schema_json)
    if sch.get("type") != "record":
        raise ValueError("only record-typed avro files are supported")
    out = []
    for f in sch["fields"]:
        t = f["type"]
        null_branch = None
        if isinstance(t, list):     # union — support null + one type,
            # in EITHER order (the spec encodes the union INDEX)
            non_null = [(i, x) for i, x in enumerate(t) if x != "null"]
            nulls = [i for i, x in enumerate(t) if x == "null"]
            if len(non_null) != 1 or len(t) > 2:
                raise ValueError(f"unsupported avro union {t}")
            null_branch = nulls[0] if nulls else None
            t = non_null[0][1]
        prim, logical = _one_type(t)
        if prim not in _AVRO_TO_ARROW:
            raise ValueError(f"unsupported avro type {prim!r}")
        out.append(_FieldSpec(f["name"], prim, logical, null_branch))
    return out


# -- value codecs -----------------------------------------------------------

def _encode_value(out: bytearray, t: str, v) -> None:
    if t == "boolean":
        out.append(1 if v else 0)
    elif t in ("int", "long"):
        out += _zigzag_encode(int(v))
    elif t == "float":
        out += struct.pack("<f", float(v))
    elif t == "double":
        out += struct.pack("<d", float(v))
    elif t == "string":
        _write_bytes(out, str(v).encode("utf-8"))
    elif t == "bytes":
        _write_bytes(out, bytes(v))
    else:
        raise ValueError(t)


def _decode_value(buf: io.BytesIO, t: str):
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return _zigzag_decode(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "string":
        return _read_bytes(buf).decode("utf-8")
    if t == "bytes":
        return _read_bytes(buf)
    raise ValueError(t)


# -- container file ---------------------------------------------------------

def write_avro(path: str, table: pa.Table, codec: str = "deflate",
               block_rows: int = 4096) -> None:
    sync = os.urandom(16)
    schema_json = _schema_json(table.schema)
    fts = _field_types(schema_json)
    # logical types encode as their integer representation
    cols = []
    for i, f in enumerate(table.schema):
        col = table.column(i)
        if pa.types.is_date32(f.type):
            col = col.cast(pa.int32())
        elif pa.types.is_timestamp(f.type):
            col = col.cast(pa.timestamp("us")).cast(pa.int64())
        cols.append(col.to_pylist())
    with open(path, "wb") as f:
        f.write(_MAGIC)
        meta = bytearray()
        meta += _zigzag_encode(2)
        _write_bytes(meta, b"avro.schema")
        _write_bytes(meta, schema_json.encode())
        _write_bytes(meta, b"avro.codec")
        _write_bytes(meta, codec.encode())
        meta += _zigzag_encode(0)
        f.write(bytes(meta))
        f.write(sync)
        n = table.num_rows
        for lo in range(0, max(n, 1), block_rows):
            hi = min(lo + block_rows, n)
            if hi <= lo:
                break
            body = bytearray()
            for i in range(lo, hi):
                for ft, col in zip(fts, cols):
                    v = col[i]
                    if ft.null_branch is not None:
                        if v is None:
                            body += _zigzag_encode(ft.null_branch)
                            continue
                        body += _zigzag_encode(1 - ft.null_branch)
                    _encode_value(body, ft.prim, v)
            raw = bytes(body)
            if codec == "deflate":
                raw = zlib.compress(raw)[2:-4]  # avro: raw deflate stream
            block = bytearray()
            block += _zigzag_encode(hi - lo)
            block += _zigzag_encode(len(raw))
            block += raw
            f.write(bytes(block))
            f.write(sync)


def read_avro(path: str) -> pa.Table:
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != _MAGIC:
        raise ValueError(f"{path}: not an avro object container file")
    meta: dict[str, bytes] = {}
    while True:
        count = _zigzag_decode(buf)
        if count == 0:
            break
        if count < 0:
            # spec: negative block count = |count| entries preceded by
            # the block's byte size (which we can skip past the read)
            _zigzag_decode(buf)
            count = -count
        for _ in range(count):
            k = _read_bytes(buf).decode()
            meta[k] = _read_bytes(buf)
    codec = meta.get("avro.codec", b"null").decode()
    fts = _field_types(meta["avro.schema"].decode())
    sync = buf.read(16)
    cols: dict[str, list] = {ft.name: [] for ft in fts}
    while True:
        head = buf.read(1)
        if not head:
            break
        buf.seek(-1, io.SEEK_CUR)
        nrec = _zigzag_decode(buf)
        blen = _zigzag_decode(buf)
        raw = buf.read(blen)
        if codec == "deflate":
            raw = zlib.decompress(raw, wbits=-15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        if buf.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt)")
        body = io.BytesIO(raw)
        for _ in range(nrec):
            for ft in fts:
                if ft.null_branch is not None:
                    branch = _zigzag_decode(body)
                    if branch == ft.null_branch:
                        cols[ft.name].append(None)
                        continue
                cols[ft.name].append(_decode_value(body, ft.prim))
    arrays = {}
    for ft in fts:
        arr = pa.array(cols[ft.name], _AVRO_TO_ARROW[ft.prim])
        if ft.logical is not None:
            arr = arr.cast(ft.arrow_type)
        arrays[ft.name] = arr
    return pa.table(arrays)
