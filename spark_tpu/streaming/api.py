"""DataStreamReader / DataStreamWriter (reference: sql/core/.../streaming/
DataStreamReader.scala, DataStreamWriter.scala)."""

from __future__ import annotations

from typing import Any, Callable

from ..errors import AnalysisException
from .query import (
    ConsoleSink, ForeachBatchSink, MemorySink, StreamingQuery,
    StreamingRelation,
)
from .sources import FileStreamSource, MemoryStream, RateSource


class DataStreamReader:
    def __init__(self, session):
        self.session = session
        self._format = None
        self._options: dict[str, Any] = {}
        self._schema = None

    def format(self, fmt: str) -> "DataStreamReader":  # noqa: A003
        self._format = fmt
        return self

    def option(self, k, v) -> "DataStreamReader":
        self._options[k] = v
        return self

    def schema(self, s) -> "DataStreamReader":
        self._schema = s
        return self

    def load(self, path: str | None = None):
        from ..api.dataframe import DataFrame

        fmt = (self._format or "").lower()
        if fmt == "rate":
            src = RateSource(int(self._options.get("rowsPerSecond", 1)))
        elif fmt == "socket":
            from .sources import SocketSource

            src = SocketSource(
                self._options["host"], int(self._options["port"]),
                include_timestamp=str(self._options.get(
                    "includeTimestamp", "false")).lower() == "true")
        elif fmt in ("parquet", "csv", "json"):
            src = FileStreamSource(path or self._options["path"], fmt)
        elif fmt in ("segment-log", "segmentlog"):
            # the Kafka-contract source (streaming/segment_log.py)
            from .segment_log import SegmentLogSource

            src = SegmentLogSource(
                path or self._options["path"],
                starting_offsets=str(self._options.get(
                    "startingOffsets", "earliest")))
        else:
            raise AnalysisException(f"unknown streaming format {fmt}")
        return DataFrame(self.session, StreamingRelation(src))

    def parquet(self, path: str):
        return self.format("parquet").load(path)

    def csv(self, path: str):
        return self.format("csv").load(path)

    def json(self, path: str):
        return self.format("json").load(path)


class DataStreamWriter:
    def __init__(self, df):
        self.df = df
        self._format = "memory"
        self._output_mode = "append"
        self._options: dict[str, Any] = {}
        self._query_name: str | None = None
        self._trigger_interval: float | None = None
        self._once = False
        self._foreach_fn: Callable | None = None

    def format(self, fmt: str) -> "DataStreamWriter":  # noqa: A003
        self._format = fmt
        return self

    def outputMode(self, mode: str) -> "DataStreamWriter":
        self._output_mode = mode.lower()
        return self

    def option(self, k, v) -> "DataStreamWriter":
        self._options[k] = v
        return self

    def queryName(self, name: str) -> "DataStreamWriter":
        self._query_name = name
        return self

    def trigger(self, processingTime: str | None = None, once: bool = False,
                availableNow: bool = False,
                continuous: str | None = None) -> "DataStreamWriter":
        def seconds(spec: str) -> float:
            parts = spec.split()
            v = float(parts[0])
            unit = parts[1] if len(parts) > 1 else "seconds"
            return v / 1000.0 if unit.startswith("milli") else v

        given = sum(bool(x) for x in
                    (processingTime, continuous, once or availableNow))
        if given > 1:
            raise ValueError(
                "trigger() accepts exactly one of processingTime, "
                "continuous, once/availableNow")
        if processingTime:
            self._trigger_interval = seconds(processingTime)
        if continuous:
            # low-latency mode: the tuple marker carries the epoch
            # checkpoint interval (ContinuousExecution role)
            self._trigger_interval = ("continuous", seconds(continuous))
        self._once = once or availableNow
        return self

    def foreachBatch(self, fn: Callable) -> "DataStreamWriter":
        self._format = "foreachBatch"
        self._foreach_fn = fn
        return self

    def start(self, path: str | None = None) -> StreamingQuery:
        session = self.df.session
        fmt = self._format.lower()
        if fmt == "memory":
            name = self._query_name or "stream_output"
            sink = MemorySink(name, session)
        elif fmt == "console":
            sink = ConsoleSink()
        elif fmt == "foreachbatch":
            sink = ForeachBatchSink(self._foreach_fn, session)
        else:
            raise AnalysisException(f"unknown streaming sink {fmt}")
        wm = getattr(self.df, "_watermark", None)
        q = StreamingQuery(
            session, self.df.plan, sink, self._output_mode,
            self._trigger_interval, self._once,
            self._options.get("checkpointLocation"), self._query_name, wm)
        session._streams.append(q)
        return q
