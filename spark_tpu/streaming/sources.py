"""Streaming sources.

Role of the reference's streaming sources (sqlx/streaming/sources/ —
MemoryStream, RateStreamProvider, FileStreamSource). Offsets are
monotonically increasing JSON-serializable values; getBatch(start, end)
returns the rows in (start, end] as an Arrow table (the micro-batch
contract of MicroBatchExecution, sqlx/streaming/runtime/MicroBatchExecution.scala).
"""

from __future__ import annotations

import glob as _glob
import os
import threading
import time
from typing import Any, Optional

import pyarrow as pa

from ..columnar.arrow import schema_from_arrow
from ..types import StructType


class StreamSource:
    schema: StructType

    def latest_offset(self) -> Any:
        raise NotImplementedError

    def get_batch(self, start: Any, end: Any) -> pa.Table:
        raise NotImplementedError

    def initial_offset(self) -> Any:
        return None


class MemoryStream(StreamSource):
    """Test source fed by addData (reference: MemoryStream — the backbone of
    the StreamTest DSL, SURVEY.md §4)."""

    def __init__(self, schema: pa.Schema | None = None):
        self._rows: list[pa.Table] = []
        self._lock = threading.Lock()
        self._schema_arrow = schema
        self.schema = schema_from_arrow(schema) if schema else None

    def add_data(self, data) -> None:
        if isinstance(data, dict):
            table = pa.table(data)
        elif isinstance(data, pa.Table):
            table = data
        else:
            raise TypeError("add_data expects dict or pyarrow.Table")
        with self._lock:
            if self._schema_arrow is None:
                self._schema_arrow = table.schema
                self.schema = schema_from_arrow(table.schema)
            self._rows.append(table)

    addData = add_data

    def latest_offset(self):
        with self._lock:
            return len(self._rows)

    def initial_offset(self):
        return 0

    def get_batch(self, start, end) -> pa.Table:
        with self._lock:
            chunk = self._rows[(start or 0):end]
        if not chunk:
            return self._schema_arrow.empty_table()
        return pa.concat_tables(chunk)


class RateSource(StreamSource):
    """rows_per_second synthetic source (reference: RateStreamProvider).
    Columns: timestamp (us), value (int64)."""

    def __init__(self, rows_per_second: int = 1):
        self.rps = rows_per_second
        self.t0 = time.time()
        self.schema = schema_from_arrow(pa.schema([
            ("timestamp", pa.timestamp("us")), ("value", pa.int64())]))

    def initial_offset(self):
        return 0

    def latest_offset(self):
        return int((time.time() - self.t0) * self.rps)

    def get_batch(self, start, end) -> pa.Table:
        start = start or 0
        values = list(range(start, end))
        ts = [int((self.t0 + v / self.rps) * 1e6) for v in values]
        return pa.table({
            "timestamp": pa.array(ts, pa.timestamp("us")),
            "value": pa.array(values, pa.int64()),
        })


class FileStreamSource(StreamSource):
    """Watches a directory; offset = sorted list of seen files
    (reference: FileStreamSource + its seen-files log)."""

    def __init__(self, path: str, fmt: str = "parquet"):
        self.path = path
        self.fmt = fmt
        first = self._list_files()
        if not first:
            raise FileNotFoundError(
                f"file stream needs at least one file at start: {path}")
        self.schema = schema_from_arrow(self._read([first[0]]).schema)

    def _list_files(self) -> list[str]:
        pat = {"parquet": "*.parquet", "csv": "*.csv", "json": "*.json"}[self.fmt]
        return sorted(_glob.glob(os.path.join(self.path, pat)))

    def initial_offset(self):
        return []

    def latest_offset(self):
        return self._list_files()

    def get_batch(self, start, end) -> pa.Table:
        seen = set(start or [])
        new = [f for f in end if f not in seen]
        return self._read(new)

    def _read(self, files: list[str]) -> pa.Table:
        if not files:
            import pyarrow as pa2

            return pa2.schema([]).empty_table()
        if self.fmt == "parquet":
            import pyarrow.parquet as pq

            return pa.concat_tables([pq.read_table(f) for f in files])
        if self.fmt == "csv":
            import pyarrow.csv as pacsv

            return pa.concat_tables([pacsv.read_csv(f) for f in files])
        import pyarrow.json as pajson

        return pa.concat_tables([pajson.read_json(f) for f in files])


class SocketSource(StreamSource):
    """TCP text-line source (reference: TextSocketSourceProvider /
    TextSocketMicroBatchStream — `format("socket")` with host/port).
    A reader thread drains lines into a buffer; offset = lines consumed.
    Column: value (string). As in the reference, this source is NOT
    fault-tolerant (the socket does not replay), which is why the
    reference gates it to testing — same stance here."""

    def __init__(self, host: str, port: int,
                 include_timestamp: bool = False):
        import socket as _socket

        self.include_timestamp = include_timestamp
        fields = [("value", pa.string())]
        if include_timestamp:
            fields.append(("timestamp", pa.timestamp("us")))
        self.schema = schema_from_arrow(pa.schema(fields))
        self._rows: list[tuple[str, int]] = []
        self._base = 0  # offset of _rows[0]; consumed lines are trimmed
        self._lock = threading.Lock()
        self._sock = _socket.create_connection((host, port), timeout=10)
        self._closed = threading.Event()
        # race-lint: ignore[bare-submit] — socket ingest loop: source-
        # lifetime I/O pump, produces rows consumed by MANY batches
        threading.Thread(target=self._reader, daemon=True,
                         name="socket-source").start()

    def _reader(self) -> None:
        buf = b""
        sock = self._sock
        while not self._closed.is_set():
            try:
                chunk = sock.recv(64 << 10)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while b"\n" in buf:
                line, _, buf = buf.partition(b"\n")
                now = int(time.time() * 1e6)
                with self._lock:
                    self._rows.append(
                        (line.decode("utf-8", "replace"), now))

    def initial_offset(self):
        return 0

    def latest_offset(self):
        with self._lock:
            return self._base + len(self._rows)

    def get_batch(self, start, end) -> pa.Table:
        start = start or 0
        with self._lock:
            rows = self._rows[start - self._base:end - self._base]
            # everything below `start` is committed — trim so an
            # always-on stream doesn't hold every line ever received
            # (reference: TextSocketMicroBatchStream.commit pruning)
            if start > self._base:
                del self._rows[:start - self._base]
                self._base = start
        cols = {"value": pa.array([r[0] for r in rows], pa.string())}
        if self.include_timestamp:
            cols["timestamp"] = pa.array([r[1] for r in rows],
                                         pa.timestamp("us"))
        return pa.table(cols)

    def stop(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass
