"""Micro-batch streaming execution.

Role of the reference's StreamExecution/MicroBatchExecution
(sqlx/streaming/runtime/StreamExecution.scala — query thread + trigger loop;
MicroBatchExecution.scala — per-trigger incremental planning;
IncrementalExecution.scala:65 — stateful operator rewriting; offset/commit
WAL under sqlx/streaming/checkpointing/).

TPU-native stateful aggregation: state IS the partial-aggregation buffer
table. Each trigger computes device partials of the new rows, unions them
with the state scan, and runs the same associative final-merge kernel the
batch engine uses; the merged buffers become the next state version. No
separate state-update kernels exist.
"""

from __future__ import annotations

import json
import os
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Optional

import pyarrow as pa

from ..errors import AnalysisException, UnsupportedOperationError
from ..exec.context import ExecContext
from ..plan.logical import (
    Aggregate, LeafNode, LocalRelation, LogicalPlan,
)
from ..expr.expressions import AttributeReference
from .sources import StreamSource
from .state import StateStore


class StreamingRelation(LeafNode):
    """Logical leaf for a streaming source; replaced per micro-batch with a
    LocalRelation of the new rows (same attribute ids, so every compiled
    kernel is reused across triggers)."""

    def __init__(self, source: StreamSource,
                 attrs: list[AttributeReference] | None = None):
        self.source = source
        self.attrs = attrs or [
            AttributeReference(f.name, f.dataType, f.nullable)
            for f in source.schema.fields]

    @property
    def output(self):
        return self.attrs

    def _data_args(self):
        return (("ids", tuple(a.expr_id for a in self.attrs)),)


class _PhysicalHolder(LeafNode):
    """Logical leaf wrapping an already-executed physical result."""

    def __init__(self, exec_plan, attrs):
        self.exec_plan = exec_plan
        self.attrs = attrs

    @property
    def output(self):
        return self.attrs


class PrecomputedExec:
    """Physical leaf over materialized partitions."""

    def __init__(self, partitions, attrs):
        self.partitions = partitions
        self.attrs = attrs
        self.child_fields = ()

    @property
    def output(self):
        return self.attrs

    @property
    def children(self):
        return []

    def output_partitioning(self):
        from ..physical.partitioning import UnknownPartitioning

        return UnknownPartitioning(max(len(self.partitions), 1))

    def required_child_distribution(self):
        return []

    def map_children(self, f):
        return self

    def with_new_children(self, c):
        return self

    def execute(self, ctx):
        return self.partitions

    def tree_string(self, depth=0):
        return "  " * depth + "Precomputed"


class StreamingQuery:
    """Handle to a running query (reference: StreamingQuery API)."""

    def __init__(self, session, plan: LogicalPlan, sink, output_mode: str,
                 trigger_interval: float | None, once: bool,
                 checkpoint_dir: str | None, name: str | None,
                 watermark: tuple[str, float] | None):
        self.id = str(uuid.uuid4())
        self.name = name
        self.session = session
        self.plan = plan
        self.sink = sink
        self.output_mode = output_mode
        # continuous mode (reference: ContinuousExecution.scala — epoch-
        # based low-latency processing): poll as fast as data arrives and
        # write checkpoint epochs only every `continuous_epoch` seconds;
        # recovery replays from the last epoch (sources are replayable)
        self.continuous_epoch: float | None = None
        if isinstance(trigger_interval, tuple):
            self.continuous_epoch = trigger_interval[1]
            trigger_interval = 0.002
        self.trigger_interval = trigger_interval or 0.05
        self._last_epoch = 0.0  # first batch always writes an epoch
        self._wal_due = True
        self.once = once
        self.exception: Exception | None = None
        self._active = True
        self._stop_evt = threading.Event()
        self.batch_id = -1
        self.recent_progress: list[dict] = []
        self.watermark = watermark  # (column, delay_seconds)
        self.current_watermark_us: int | None = None

        # locate the streaming sources (1, or 2 for stream-stream joins)
        leaves = []
        for n in plan.iter_nodes():
            if isinstance(n, StreamingRelation) and \
                    not any(n is x for x in leaves):
                leaves.append(n)
        if len(leaves) not in (1, 2):
            raise UnsupportedOperationError(
                "at most two streaming sources per query are supported")
        self.stream_leaves = leaves
        self.stream_leaf = leaves[0]
        self.source: StreamSource = leaves[0].source

        self.checkpoint_dir = checkpoint_dir
        from ..config import STATE_STORE_PARTITIONS
        from .state import PartitionedStateStore

        self.state = PartitionedStateStore(
            checkpoint_dir,
            num_partitions=int(session.conf.get(STATE_STORE_PARTITIONS)))
        if len(leaves) == 2:
            from .join import StreamJoinRunner

            self._join_runner = StreamJoinRunner(session, plan, leaves,
                                                 checkpoint_dir)
            self.committed_offset = [l.source.initial_offset()
                                     for l in leaves]
        else:
            self.committed_offset = self.source.initial_offset()
        if checkpoint_dir:
            os.makedirs(os.path.join(checkpoint_dir, "offsets"), exist_ok=True)
            os.makedirs(os.path.join(checkpoint_dir, "commits"), exist_ok=True)
            self._recover()

        # race-lint: ignore[bare-submit] — micro-batch driver loop: each
        # batch ENTERS a fresh query scope itself (a stream outlives any
        # one query id; inheriting the starter's scope would be wrong)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"stream-{self.id[:8]}")
        self._thread.start()

    # --- checkpoint recovery ---------------------------------------------
    def _recover(self) -> None:
        cdir = os.path.join(self.checkpoint_dir, "commits")
        committed = sorted(int(f) for f in os.listdir(cdir) if f.isdigit())
        if not committed:
            return
        last = committed[-1]
        with open(os.path.join(self.checkpoint_dir, "offsets", str(last))) as f:
            self.committed_offset = json.load(f)["offset"]
        try:
            with open(os.path.join(cdir, str(last))) as f:
                self.current_watermark_us = json.load(f).get("watermark_us")
        except (OSError, ValueError):
            pass
        self.batch_id = last
        self.state.load(last)
        if len(self.stream_leaves) == 2:
            self._join_runner.load(last)

    def _epoch_due(self) -> bool:
        """Micro-batch mode checkpoints every batch; continuous mode only
        at epoch boundaries (ContinuousExecution's epoch coordinator)."""
        if self.continuous_epoch is None:
            return True
        now = time.monotonic()
        if now - self._last_epoch >= self.continuous_epoch:
            self._last_epoch = now
            return True
        return False

    # --- trigger loop ------------------------------------------------------
    def _run(self) -> None:
        try:
            while not self._stop_evt.is_set():
                self._in_trigger = True
                try:
                    progressed = self._run_one_batch()
                finally:
                    self._in_trigger = False
                if self.once:
                    if not progressed:
                        break
                    continue
                if not progressed:
                    self._stop_evt.wait(self.trigger_interval)
        except Exception as e:  # surfaced via .exception / awaitTermination
            self.exception = e
            traceback.print_exc()
        finally:
            self._active = False

    def _run_one_batch_join(self) -> bool:
        latest = [l.source.latest_offset() for l in self.stream_leaves]
        if latest == self.committed_offset:
            return False
        if self.output_mode != "append":
            raise UnsupportedOperationError(
                "stream-stream joins support append mode only")
        t0 = time.perf_counter()
        new_datas = [l.source.get_batch(c, lt)
                     for l, c, lt in zip(self.stream_leaves,
                                         self.committed_offset, latest)]
        wm_before = self.current_watermark_us
        self._join_batch_pass(new_datas, latest, t0)
        # watermark advanced → one finalize pass with no new input so
        # outer rows emit without waiting for more data (mirrors
        # MicroBatchExecution's extra batch on watermark change)
        if self.current_watermark_us != wm_before:
            from .join import _empty_like

            empties = [_empty_like(l.attrs) for l in self.stream_leaves]
            self._join_batch_pass(empties, latest, time.perf_counter())
        return True

    def _join_batch_pass(self, new_datas, latest, t0) -> None:
        batch_id = self.batch_id + 1
        if self.checkpoint_dir:
            with open(os.path.join(self.checkpoint_dir, "offsets",
                                   str(batch_id)), "w") as f:
                json.dump({"offset": [_json_safe(x) for x in latest]}, f)
        out_table, new_wm, merged = self._join_runner.run_batch(
            new_datas, self.current_watermark_us)
        self.sink.add_batch(batch_id, out_table, self.output_mode)
        self._join_runner.commit(batch_id, merged)
        if new_wm is not None:
            self.current_watermark_us = new_wm
        if self.checkpoint_dir:
            with open(os.path.join(self.checkpoint_dir, "commits",
                                   str(batch_id)), "w") as f:
                json.dump({"batch": batch_id,
                           "watermark_us": self.current_watermark_us}, f)
        self.batch_id = batch_id
        self.committed_offset = latest
        self.recent_progress.append({
            "batchId": batch_id,
            "numInputRows": sum(t.num_rows for t in new_datas),
            "durationMs": int((time.perf_counter() - t0) * 1000),
            "stateRows": list(self._join_runner.state_rows()),
        })
        del self.recent_progress[:-32]

    def _run_one_batch(self) -> bool:
        if len(self.stream_leaves) == 2:
            return self._run_one_batch_join()
        latest = self.source.latest_offset()
        if latest == self.committed_offset:
            return False
        t0 = time.perf_counter()
        batch_id = self.batch_id + 1
        new_data = self.source.get_batch(self.committed_offset, latest)
        self._wal_due = self._epoch_due()
        if self.checkpoint_dir and self._wal_due:
            with open(os.path.join(self.checkpoint_dir, "offsets",
                                   str(batch_id)), "w") as f:
                json.dump({"offset": _json_safe(latest)}, f)

        # Late-data filter (reference: stateful operators drop rows older
        # than the watermark so a finalized group is never re-created):
        # filter BEFORE the stateful aggregation against the watermark as
        # of the previous batch.
        wm_before = self.current_watermark_us
        if self.watermark is not None and self._plan_is_stateful():
            new_data = self._drop_late_rows(new_data)

        out_table = self._execute_batch(new_data, batch_id)
        self.sink.add_batch(batch_id, out_table, self.output_mode)

        # Advance the watermark at end-of-batch from this batch's max
        # event time (previous-batch semantics, as the reference does).
        if self.watermark is not None:
            self._advance_watermark_from_input(new_data)
        if self.checkpoint_dir and self._wal_due:
            with open(os.path.join(self.checkpoint_dir, "commits",
                                   str(batch_id)), "w") as f:
                # end-of-batch watermark rides the commit log so recovery
                # restores late-data protection (reference keeps it in
                # offset metadata)
                json.dump({"batch": batch_id,
                           "watermark_us": self.current_watermark_us}, f)
        self.batch_id = batch_id

        # Like MicroBatchExecution — which constructs an extra batch when
        # the watermark changed — run a no-new-data pass so append-mode
        # finalization emits without waiting for more input. The pass is a
        # real batch: its own id, offsets/commits WAL entries, and state
        # version, so foreachBatch keeps its one-id-one-payload contract.
        # Runs before committed_offset flips so processAllAvailable can't
        # observe the sink mid-finalization.
        if (self.watermark is not None
                and self.output_mode == "append"
                and self.current_watermark_us is not None
                and self.current_watermark_us != wm_before
                and self._plan_is_stateful()):
            fid = batch_id + 1
            if self.checkpoint_dir:
                with open(os.path.join(self.checkpoint_dir, "offsets",
                                       str(fid)), "w") as f:
                    json.dump({"offset": _json_safe(latest)}, f)
            out2 = self._execute_batch(new_data.slice(0, 0), fid)
            self.sink.add_batch(fid, out2, self.output_mode)
            if self.checkpoint_dir:
                with open(os.path.join(self.checkpoint_dir, "commits",
                                       str(fid)), "w") as f:
                    json.dump({"batch": fid,
                               "watermark_us": self.current_watermark_us}, f)
            self.batch_id = fid
        self.committed_offset = latest
        self.recent_progress.append({
            "batchId": batch_id,
            "numInputRows": new_data.num_rows,
            "durationMs": int((time.perf_counter() - t0) * 1000),
        })
        del self.recent_progress[:-32]
        return True

    # --- incremental execution --------------------------------------------
    def _execute_batch(self, new_data: pa.Table, batch_id: int) -> pa.Table:
        from ..api.dataframe import DataFrame
        from .stateful_map import StatefulMapGroups

        if isinstance(self.plan, StatefulMapGroups):
            return self._execute_stateful_map(new_data)

        def substitute(node):
            if isinstance(node, StreamingRelation) and node is self.stream_leaf:
                return LocalRelation(node.attrs, new_data)
            if isinstance(node, StreamingRelation):
                return LocalRelation(node.attrs, new_data)
            return node

        batch_plan = self.plan.transform_up(substitute)
        qe_probe = DataFrame(self.session, batch_plan).query_execution
        optimized = qe_probe.optimized
        aggs = [n for n in optimized.iter_nodes() if isinstance(n, Aggregate)]

        if not aggs:
            if self.output_mode not in ("append", "update"):
                raise AnalysisException(
                    "complete mode requires an aggregation")
            return qe_probe.to_arrow()

        if len(aggs) > 1:
            raise UnsupportedOperationError(
                "multiple streaming aggregations not supported")
        if self.output_mode == "append":
            if self._is_dedup(aggs[0]):
                # dropDuplicates/distinct (reference:
                # StreamingDeduplicateExec): first-sight emission is
                # append-safe — a key's buffer never changes after its
                # first appearance
                return self._execute_stateful(optimized, aggs[0],
                                              dedup_append=True)
            if self.watermark is not None and self.watermark[0] in {
                    getattr(g, "name", None)
                    for g in aggs[0].grouping_exprs}:
                # watermark-gated finalization (reference:
                # StatefulAggregationStrategy append mode): emit a group
                # only once the watermark passes its event-time key
                return self._execute_stateful(optimized, aggs[0],
                                              append_watermark=True)
            raise AnalysisException(
                "append mode on aggregated streams requires a watermark on "
                "the grouping keys — use complete/update")
        return self._execute_stateful(optimized, aggs[0])

    @staticmethod
    def _is_dedup(agg: Aggregate) -> bool:
        from ..expr.expressions import AggregateFunction, Alias, First

        for e in agg.aggregate_exprs:
            inner = e.child if isinstance(e, Alias) else e
            fns = [n for n in inner.iter_nodes()
                   if isinstance(n, AggregateFunction)]
            if fns and not all(isinstance(f, First) for f in fns):
                return False
        return True

    def _execute_stateful_map(self, new_data: pa.Table) -> pa.Table:
        """applyInPandasWithState micro-batch (reference:
        FlatMapGroupsWithStateExec): the stateless child plan runs on the
        engine; the user fn runs per key with its recovered state."""
        from ..api.dataframe import DataFrame
        from ..types import to_arrow_type
        from .stateful_map import run_stateful_map

        node = self.plan

        def sub(n):
            if isinstance(n, StreamingRelation):
                return LocalRelation(n.attrs, new_data)
            return n

        child_table = DataFrame(self.session,
                                node.child.transform_up(sub)).toArrow()
        out_schema = pa.schema([(a.name, to_arrow_type(a.dtype))
                                for a in node.out_attrs])
        out, new_state = run_stateful_map(node, child_table,
                                          self.state.table, out_schema)
        self.state.commit(self.batch_id + 1, new_state)
        return out

    def _execute_stateful(self, optimized: LogicalPlan,
                          agg: Aggregate,
                          dedup_append: bool = False,
                          append_watermark: bool = False) -> pa.Table:
        from ..physical.operators import (
            HashAggregateExec, LocalTableScanExec, UnionExec,
        )
        from ..physical.planner import Planner
        from ..columnar.ops import concat_batches
        from ..physical.operators import attrs_schema

        session = self.session
        planner = Planner(session.conf)
        ctx = ExecContext(conf=session.conf, metrics=session._metrics)

        # partial aggregation of new rows (device)
        partial_plan = planner._convert(agg)  # ComputeExec(Final(Partial)) or
        finish = partial_plan                 # ComputeExec(Partial) when the
        maybe = finish.child                  # planner skipped the merge
        if isinstance(maybe, HashAggregateExec) and maybe.mode == "final":
            final: HashAggregateExec = maybe
            partial: HashAggregateExec = final.child
        else:
            partial = maybe
            final = HashAggregateExec(partial.grouping, partial.specs,
                                      "final", partial)

        if any(not sp.mergeable for sp in partial.specs):
            raise UnsupportedOperationError(
                "non-mergeable aggregates (percentile/median) are not "
                "supported in streaming state")
        buffer_attrs = list(partial.output)
        prev_state = self.state.table  # pre-batch state (dedup emission)
        partial_ready = planner._ensure_requirements(partial)
        new_parts = partial_ready.execute(ctx)
        new_partial_exec = PrecomputedExec(new_parts, buffer_attrs)

        # union with state scan
        children = [new_partial_exec]
        if self.state.table is not None and self.state.table.num_rows:
            children.append(LocalTableScanExec(buffer_attrs, self.state.table))
        union = UnionExec(children, buffer_attrs)
        merged = HashAggregateExec(final.grouping, final.specs, "final", union)
        merged_ready = planner._ensure_requirements(merged)
        merged_parts = merged_ready.execute(ctx)

        # persist new state (buffers, pre-finishing). The touched keys —
        # exactly the new batch's partial-agg keys — make the commit an
        # O(delta) changelog write (state.py, RocksDB-changelog role).
        state_batches = [b for p in merged_parts for b in p]
        state_table = pa.concat_tables(
            [b.to_arrow() for b in state_batches],
            promote_options="permissive") if state_batches else None
        from .state import _key_tuples

        key_names = [a.name for a in partial.grouping]
        new_batches = [b for p in new_parts for b in p]
        newt = None
        new_keys: set = set()
        need_keys = key_names and (
            self.state.dir is not None or self.output_mode == "update"
            or dedup_append)
        if new_batches and need_keys:
            newt = pa.concat_tables([b.to_arrow() for b in new_batches],
                                    promote_options="permissive")
            new_keys = set(_key_tuples(newt, key_names))
        delta_kw = ({"upsert_keys": new_keys, "key_names": key_names}
                    if key_names else {})

        if append_watermark and state_table is not None:
            from ..physical.operators import LocalTableScanExec as _LTS

            finalized, retained = self._split_watermark(state_table)
            deletes = (_key_tuples(finalized, key_names)
                       if key_names else [])
            self.state.commit(self.batch_id + 1, retained,
                              delete_keys=deletes, **delta_kw)
            out_exec = finish.copy(child=_LTS(list(buffer_attrs), finalized))
            out_parts = out_exec.execute(ctx)
            out_batches = [b for p in out_parts for b in p]
            return pa.concat_tables([b.to_arrow() for b in out_batches],
                                    promote_options="permissive")
        if state_table is not None:
            state_table, evicted = self._evict(state_table, buffer_attrs)
            deletes = (_key_tuples(evicted, key_names)
                       if key_names and evicted is not None else [])
            self.state.commit(self.batch_id + 1, state_table,
                              delete_keys=deletes, **delta_kw)

        # finishing projection over merged buffers
        out_exec = finish.copy(child=PrecomputedExec(merged_parts,
                                                     buffer_attrs))
        out_parts = out_exec.execute(ctx)
        out_batches = [b for p in out_parts for b in p]
        out = pa.concat_tables([b.to_arrow() for b in out_batches],
                               promote_options="permissive")

        if self.output_mode == "update" or dedup_append:
            # update: only groups touched by this batch;
            # dedup append: touched AND unseen before this batch
            if newt is not None and key_names:
                old_keys = set()
                if dedup_append and prev_state is not None \
                        and prev_state.num_rows:
                    old_keys = set(zip(*[prev_state.column(k).to_pylist()
                                         for k in key_names]))
                cols = list(zip(*[out.column(k).to_pylist()
                                  for k in key_names])) if out.num_rows else []
                mask = [c in new_keys and c not in old_keys for c in cols]
                out = out.filter(pa.array(mask)) if cols else out
        return out

    def _split_watermark(self, state_table: pa.Table):
        """(finalized, retained) split of the merged state by the current
        watermark (as of the previous batch — the reference's semantics):
        groups whose event-time key fell behind it emit once and leave the
        state."""
        col, _delay = self.watermark
        wm = self.current_watermark_us
        if wm is None:
            return state_table.slice(0, 0), state_table
        done = [v is not None and _to_us(v) < wm
                for v in state_table.column(col).to_pylist()]
        mask = pa.array(done)
        import pyarrow.compute as pc

        return state_table.filter(mask), state_table.filter(pc.invert(mask))

    def _plan_is_stateful(self) -> bool:
        """True when the query plan carries state the late-data filter must
        protect (an aggregation / dedup / stateful map). Distinct counts:
        the optimizer rewrites it to Aggregate in the plan the batch
        executor checks."""
        from ..plan.logical import Distinct
        from .stateful_map import StatefulMapGroups

        if isinstance(self.plan, StatefulMapGroups):
            return True
        return any(isinstance(n, (Aggregate, Distinct))
                   for n in self.plan.iter_nodes())

    def _drop_late_rows(self, new_data: pa.Table) -> pa.Table:
        """Drop input rows whose event time is older than the current
        watermark (null event times pass through)."""
        wm = self.current_watermark_us
        col, _delay = self.watermark
        if wm is None or col not in new_data.column_names \
                or not new_data.num_rows:
            return new_data
        keep = [v is None or _to_us(v) >= wm
                for v in new_data.column(col).to_pylist()]
        if all(keep):
            return new_data
        return new_data.filter(pa.array(keep))

    def _advance_watermark_from_input(self, new_data: pa.Table) -> None:
        """End-of-batch watermark advance from this batch's max event time
        (monotonic)."""
        col, delay_s = self.watermark
        if col not in new_data.column_names or not new_data.num_rows:
            return
        try:
            import pyarrow.compute as pc

            mx = pc.max(new_data.column(col)).as_py()
        except Exception:
            return
        if mx is None:
            return
        wm = _to_us(mx) - int(delay_s * 1e6)
        if self.current_watermark_us is not None:
            wm = max(wm, self.current_watermark_us)
        self.current_watermark_us = wm

    def _evict(self, state_table: pa.Table, buffer_attrs):
        """Watermark-based state eviction when a grouping key is the
        watermark (event-time) column. Returns (kept, evicted-or-None);
        evicted keys become changelog delete tombstones."""
        if self.watermark is None:
            return state_table, None
        col, _delay_s = self.watermark
        if col not in state_table.column_names:
            return state_table, None
        wm = self.current_watermark_us
        if wm is None:
            return state_table, None
        keep = [v is None or _to_us(v) >= wm
                for v in state_table.column(col).to_pylist()]
        mask = pa.array(keep)
        import pyarrow.compute as pc

        return state_table.filter(mask), state_table.filter(pc.invert(mask))

    # --- public API --------------------------------------------------------
    @property
    def isActive(self) -> bool:
        return self._active

    def processAllAvailable(self, timeout: float = 30.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.exception:
                raise self.exception
            if len(self.stream_leaves) == 2:
                caught = [l.source.latest_offset()
                          for l in self.stream_leaves] == \
                    self.committed_offset
            else:
                caught = self.source.latest_offset() == self.committed_offset
            # a trigger may still be mid-flight (e.g. the watermark
            # finalize pass) after offsets catch up — wait it out
            if caught and not getattr(self, "_in_trigger", False):
                return
            time.sleep(0.01)
        raise TimeoutError("processAllAvailable timed out")

    def awaitTermination(self, timeout: float | None = None) -> bool:
        self._thread.join(timeout)
        if self.exception:
            raise self.exception
        return not self._thread.is_alive()

    def stop(self) -> None:
        self._stop_evt.set()
        self._thread.join(timeout=10)
        self._active = False

    def lastProgress(self) -> dict | None:
        return self.recent_progress[-1] if self.recent_progress else None


def _json_safe(offset):
    return offset


def _to_us(v) -> int:
    import datetime

    if isinstance(v, datetime.datetime):
        return int(v.timestamp() * 1e6)
    if isinstance(v, datetime.date):
        return int(time.mktime(v.timetuple()) * 1e6)
    # numeric event-time columns are interpreted as SECONDS, matching the
    # seconds-denominated watermark delay
    return int(v * 1e6)


# ---------------------------------------------------------------------------
# Sinks
# ---------------------------------------------------------------------------

class MemorySink:
    """Queryable in-memory sink (reference: memory sink for tests)."""

    def __init__(self, name: str, session):
        self.name = name
        self.session = session
        self.batches: list[pa.Table] = []
        self._lock = threading.Lock()

    def add_batch(self, batch_id: int, table: pa.Table, mode: str) -> None:
        with self._lock:
            if mode == "complete":
                self.batches = [table]
            else:
                self.batches.append(table)
            if self.batches:
                merged = pa.concat_tables(self.batches,
                                          promote_options="permissive")
                df = self.session.createDataFrame(merged)
                self.session.catalog_.register(self.name, df.plan)


class ConsoleSink:
    def __init__(self):
        pass

    def add_batch(self, batch_id, table, mode):
        print(f"-------------------------------------------\n"
              f"Batch: {batch_id}\n"
              f"-------------------------------------------")
        print(table.to_pandas().to_string())


class ForeachBatchSink:
    def __init__(self, fn: Callable, session):
        self.fn = fn
        self.session = session

    def add_batch(self, batch_id, table, mode):
        self.fn(self.session.createDataFrame(table), batch_id)
