"""Stream-stream joins: inner AND outer, with watermark state management.

Role of the reference's StreamingSymmetricHashJoinExec
(sqlx/streaming/operators/stateful/ join operators) redesigned for this
engine's micro-batch model:

  * State per side = the accumulated JOIN-INPUT rows (the side's subplan
    applied once on ingest), plus three bookkeeping columns — `__id`
    (process-unique row id), `__ts` (event-time in µs from the side's
    watermark column, or null), `__matched` (has this row ever joined).
  * Inner results emit incrementally via the delta decomposition
    newL ⋈ (oldR ∪ newR)  +  oldL ⋈ newR — nothing emits twice.
  * The global watermark = min over watermarked sides of
    (max event time seen − delay), advanced at end of batch
    (previous-batch semantics, like the reference).
  * Outer finalization: once a stored outer-side row's event time falls
    below the watermark and it has never matched, it emits null-extended
    — exactly once, because the row is evicted with everything else
    below the watermark.
  * State trimming: rows below the watermark are evicted on BOTH sides
    (bounded state — the reference achieves this via the time-interval
    condition bound; here the watermark itself is the documented bound:
    a match arriving after the partner fell below the watermark is
    dropped as late data).

Late input rows (event time < watermark) are dropped on ingest, so a
finalized row can never re-emit.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from ..errors import UnsupportedOperationError
from ..expr.expressions import AttributeReference
from ..plan import logical as L
from ..types import LongType, int64
from .state import StateStore

_OUTER_TYPES = ("left_outer", "right_outer", "full_outer")
_SUPPORTED = ("inner", "cross") + _OUTER_TYPES


def _contains(node, leaf) -> bool:
    return any(x is leaf for x in node.iter_nodes())


def _find_stream_join(plan: L.LogicalPlan, leaves) -> L.Join:
    """The Join node where the two streaming leaves meet."""
    for n in plan.iter_nodes():
        if isinstance(n, L.Join):
            lhas = [_contains(n.left, lf) for lf in leaves]
            rhas = [_contains(n.right, lf) for lf in leaves]
            if (lhas[0] and rhas[1] and not lhas[1] and not rhas[0]) or \
                    (lhas[1] and rhas[0] and not lhas[0] and not rhas[1]):
                return n
    raise UnsupportedOperationError(
        "two streaming sources must meet at a join")


class StreamJoinRunner:
    """Per-query symmetric join state machine (owned by StreamingQuery)."""

    def __init__(self, session, plan: L.LogicalPlan, leaves,
                 checkpoint_dir: str | None):
        self.session = session
        self.plan = plan
        self.join = _find_stream_join(plan, leaves)
        if self.join.join_type not in _SUPPORTED:
            raise UnsupportedOperationError(
                f"{self.join.join_type} stream-stream joins are not "
                "supported (inner/left_outer/right_outer/full_outer)")

        # side i holds leaves[i]; sides[0] = join.left's leaf index
        self.left_leaf_idx = 0 if _contains(self.join.left, leaves[0]) else 1
        self.leaves = leaves
        self.below = [self.join.left, self.join.right]  # per JOIN side
        self.leaf_for_side = [leaves[self.left_leaf_idx],
                              leaves[1 - self.left_leaf_idx]]

        # per-side watermark: nearest EventTimeWatermark above the leaf,
        # with the event-time column surviving into the join input
        self.side_wm: list[tuple[str, int] | None] = [None, None]
        for n in plan.iter_nodes():
            if isinstance(n, L.EventTimeWatermark):
                for s in (0, 1):
                    if _contains(n, self.leaf_for_side[s]):
                        names = [a.name for a in self.below[s].output]
                        if n.column in names:
                            self.side_wm[s] = (n.column, n.delay_us)

        jt = self.join.join_type
        if jt in ("left_outer", "full_outer") and self.side_wm[0] is None:
            raise UnsupportedOperationError(
                f"{jt} stream-stream join needs withWatermark on the left "
                "side's event-time column (it must survive into the join)")
        if jt in ("right_outer", "full_outer") and self.side_wm[1] is None:
            raise UnsupportedOperationError(
                f"{jt} stream-stream join needs withWatermark on the right "
                "side's event-time column")

        self.state = [StateStore(checkpoint_dir, "state_left"),
                      StateStore(checkpoint_dir, "state_right")]
        self.next_id = [0, 0]
        self.max_ts: list[int | None] = [None, None]

    # -- persistence helpers ------------------------------------------------
    def load(self, version: int) -> None:
        for s, st in enumerate(self.state):
            st.load(version)
            if st.table is not None and st.table.num_rows:
                self.next_id[s] = int(
                    pa.compute.max(st.table["__id"]).as_py()) + 1
                # -1 is the null-event-time sentinel, not a real maximum
                ts = pa.compute.max(st.table["__ts"]).as_py()
                self.max_ts[s] = int(ts) if ts is not None and ts >= 0 \
                    else None

    # -- per-batch ----------------------------------------------------------
    def _run_plan(self, plan: L.LogicalPlan) -> pa.Table:
        from ..api.dataframe import DataFrame

        return DataFrame(self.session, plan).toArrow()

    def _ingest(self, side: int, raw: pa.Table,
                wm_us: int | None) -> pa.Table:
        """Apply the side's subplan to the new raw rows, attach
        bookkeeping columns, and drop late rows."""
        leaf = self.leaf_for_side[side]

        def sub(node):
            if node is leaf:
                return L.LocalRelation(leaf.attrs, raw)
            return node

        t = self._run_plan(self.below[side].transform_up(sub))
        n = t.num_rows
        ids = np.arange(self.next_id[side], self.next_id[side] + n,
                        dtype=np.int64)
        self.next_id[side] += n
        wm_col = self.side_wm[side]
        if wm_col is not None:
            ts = _event_time_us(t, wm_col[0])
            mx = int(ts.max()) if len(ts) and not np.all(ts < 0) else None
            if mx is not None:
                self.max_ts[side] = mx if self.max_ts[side] is None \
                    else max(self.max_ts[side], mx)
        else:
            ts = np.full(n, -1, np.int64)
        t = t.append_column("__id", pa.array(ids))
        t = t.append_column("__ts", pa.array(ts))
        t = t.append_column("__matched", pa.array(np.zeros(n, bool)))
        if wm_col is not None:
            # on a watermarked side a null event time (__ts = -1) cannot
            # participate in watermark bookkeeping — drop it on ingest so
            # it can never leak in state unevictable; late rows drop too
            keep = ts >= (wm_us if wm_us is not None else 0)
            if not keep.all():
                t = t.filter(pa.array(keep))
        return t

    def _side_state(self, side: int) -> pa.Table:
        st = self.state[side].table
        if st is not None:
            return st
        return self._empty_state(side)

    def _empty_state(self, side: int) -> pa.Table:
        t = _empty_like(self.below[side].output)
        t = t.append_column("__id", pa.array([], pa.int64()))
        t = t.append_column("__ts", pa.array([], pa.int64()))
        t = t.append_column("__matched", pa.array([], pa.bool_()))
        return t

    def _delta_join(self, lt: pa.Table, rt: pa.Table):
        """Inner join of two id-carrying tables through the engine.
        Returns (result rows conforming to join.output + id columns)."""
        lid = AttributeReference("__sj_lid", int64, False)
        rid = AttributeReference("__sj_rid", int64, False)
        lattrs = list(self.join.left.output) + [lid]
        rattrs = list(self.join.right.output) + [rid]
        lrel = L.LocalRelation(
            lattrs, _rename(lt.drop_columns(["__ts", "__matched"]),
                            "__id", "__sj_lid"))
        rrel = L.LocalRelation(
            rattrs, _rename(rt.drop_columns(["__ts", "__matched"]),
                            "__id", "__sj_rid"))
        j = L.Join(lrel, rrel, "inner", self.join.condition)
        proj = L.Project(
            list(self.join.left.output) + list(self.join.right.output)
            + [lid, rid], j)
        return self._run_plan(proj)

    def run_batch(self, new_raw: list[pa.Table], wm_start: int | None) \
            -> "tuple[pa.Table, int | None, list[pa.Table]]":
        """One micro-batch. new_raw is per-LEAF; returns (output rows in
        the FULL plan's schema, end-of-batch watermark, merged per-side
        state to pass to commit())."""
        jt = self.join.join_type
        new_side = [self._ingest(0, new_raw[self.left_leaf_idx], wm_start),
                    self._ingest(1, new_raw[1 - self.left_leaf_idx],
                                 wm_start)]
        old = [self._side_state(0), self._side_state(1)]

        # delta decomposition (inner rows)
        all_r = pa.concat_tables([old[1], new_side[1]],
                                 promote_options="permissive")
        d1 = self._delta_join(new_side[0], all_r)
        d2 = self._delta_join(old[0], new_side[1])
        inner = pa.concat_tables([d1, d2], promote_options="permissive")

        matched_l = set(inner["__sj_lid"].to_pylist())
        matched_r = set(inner["__sj_rid"].to_pylist())
        inner = inner.drop_columns(["__sj_lid", "__sj_rid"])

        # merge state: append new rows, fold in matched flags
        merged = []
        for s, (o, nw, mset) in enumerate(
                zip(old, new_side, (matched_l, matched_r))):
            t = pa.concat_tables([o, nw], promote_options="permissive")
            if mset:
                ids = np.asarray(t["__id"].to_pylist() or [], np.int64)
                m = np.asarray(t["__matched"].to_pylist() or [], bool)
                hit = np.isin(ids, np.fromiter(mset, np.int64,
                                               len(mset)))
                m = m | hit
                t = t.set_column(t.schema.get_field_index("__matched"),
                                 "__matched", pa.array(m))
            merged.append(t)

        # outer finalization + eviction below the batch-start watermark
        outer_parts = []
        if wm_start is not None:
            for s, outer_here in ((0, jt in ("left_outer", "full_outer")),
                                  (1, jt in ("right_outer", "full_outer"))):
                t = merged[s]
                if self.side_wm[s] is None or t.num_rows == 0:
                    continue
                ts = np.asarray(t["__ts"].to_pylist(), np.int64)
                below = (ts >= 0) & (ts < wm_start)
                if outer_here:
                    m = np.asarray(t["__matched"].to_pylist(), bool)
                    un = t.filter(pa.array(below & ~m))
                    if un.num_rows:
                        outer_parts.append(self._null_extend(s, un))
                merged[s] = t.filter(pa.array(~below))

        out_inner = inner
        out = [out_inner] + outer_parts
        combined = pa.concat_tables(out, promote_options="permissive") \
            if len(out) > 1 else out_inner
        result = self._apply_above(combined)

        # end-of-batch watermark from per-side maxima
        wms = []
        for s in (0, 1):
            if self.side_wm[s] is not None:
                if self.max_ts[s] is None:
                    wms.append(None)
                else:
                    wms.append(self.max_ts[s] - self.side_wm[s][1])
        new_wm = None
        if wms and all(w is not None for w in wms):
            new_wm = min(wms)
            if wm_start is not None:
                new_wm = max(new_wm, wm_start)

        return result, new_wm, merged

    def commit(self, version: int, merged: list[pa.Table]) -> None:
        self.state[0].commit(version, merged[0])
        self.state[1].commit(version, merged[1])

    def state_rows(self) -> tuple[int, int]:
        return tuple(0 if st.table is None else st.table.num_rows
                     for st in self.state)

    # -- output shaping ----------------------------------------------------
    def _null_extend(self, side: int, t: pa.Table) -> pa.Table:
        """Unmatched side-`side` rows padded with nulls for the other side,
        conforming to join.output + id columns (ids dropped by caller's
        schema — we just drop them here)."""
        t = t.drop_columns(["__id", "__ts", "__matched"])
        n = t.num_rows
        cols, names = [], []
        from ..types import to_arrow_type

        for s, attrs in ((0, self.join.left.output),
                         (1, self.join.right.output)):
            for i, a in enumerate(attrs):
                names.append(a.name)
                if s == side:
                    cols.append(t.column(i))
                else:
                    cols.append(pa.nulls(n, to_arrow_type(a.dtype)))
        return pa.table(cols, names=names)

    def _apply_above(self, joined: pa.Table) -> pa.Table:
        out_attrs = [a.with_nullability(True) for a in self.join.output]
        rel = L.LocalRelation(out_attrs, joined)

        def sub(node):
            if node is self.join:
                return rel
            return node

        return self._run_plan(self.plan.transform_up(sub))


def _event_time_us(t: pa.Table, column: str) -> np.ndarray:
    col = t[column]
    typ = col.type
    if pa.types.is_timestamp(typ):
        us = col.cast(pa.timestamp("us")).cast(pa.int64())
    elif pa.types.is_integer(typ):
        us = col.cast(pa.int64())
    else:
        raise UnsupportedOperationError(
            f"watermark column {column} must be timestamp or integer µs")
    vals = us.to_pylist()
    return np.asarray([v if v is not None else -1 for v in vals], np.int64)


def _empty_like(attrs) -> pa.Table:
    from ..types import to_arrow_type

    return pa.table(
        [pa.array([], to_arrow_type(a.dtype)) for a in attrs],
        names=[a.name for a in attrs])


def _rename(t: pa.Table, old: str, new: str) -> pa.Table:
    names = [new if n == old else n for n in t.column_names]
    return t.rename_columns(names)
