"""Streaming state store.

Role of the reference's StateStore SPI (sqlx/streaming/state/StateStore.scala:285)
with the HDFSBackedStateStoreProvider role played by Arrow/Parquet snapshots
per committed batch. State for streaming aggregation is the PARTIAL
AGGREGATION BUFFER table (grouping keys + buffer columns) — merging new
micro-batch partials into it is the same associative final-agg kernel the
batch engine uses, so streaming adds no new device code.
"""

from __future__ import annotations

import os
from typing import Optional

import pyarrow as pa


class StateStore:
    """Versioned key→buffer state with optional file persistence."""

    def __init__(self, checkpoint_dir: str | None = None,
                 name: str = "state"):
        self.table: pa.Table | None = None
        self.dir = None
        if checkpoint_dir:
            self.dir = os.path.join(checkpoint_dir, name)
            os.makedirs(self.dir, exist_ok=True)

    def load(self, version: int) -> None:
        if self.dir is None:
            return
        path = os.path.join(self.dir, f"{version}.parquet")
        if os.path.exists(path):
            import pyarrow.parquet as pq

            self.table = pq.read_table(path)

    def commit(self, version: int, table: pa.Table) -> None:
        self.table = table
        if self.dir is not None:
            import pyarrow.parquet as pq

            pq.write_table(table, os.path.join(self.dir, f"{version}.parquet"))
            # retain only the last two snapshots
            for f in os.listdir(self.dir):
                try:
                    v = int(f.split(".")[0])
                except ValueError:
                    continue
                if v < version - 1:
                    os.remove(os.path.join(self.dir, f))
