"""Streaming state store with changelog checkpointing.

Role of the reference's StateStore SPI
(sqlx/streaming/state/StateStore.scala:285) with the RocksDB provider's
changelog checkpointing (sqlx/streaming/state/RocksDBStateStoreProvider.scala,
StateStoreChangelog.scala) — redesigned for the columnar model: state for
streaming aggregation is the partial-aggregation buffer table (grouping
keys + buffer columns), kept authoritative in memory as one Arrow table.

Commit cost is O(delta), not O(state): when the operator supplies the
touched keys, a commit writes only an Arrow-IPC changelog file holding
the upserted buffer rows plus delete tombstones; a full Parquet snapshot
is written every ``snapshot_interval`` commits (compaction) or whenever
no delta information is available. Recovery = latest snapshot ≤ version
+ ordered changelog replay.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional, Sequence

import pyarrow as pa

SNAPSHOT_INTERVAL = 10


def _key_tuples(table: pa.Table, key_names: Sequence[str]) -> list[tuple]:
    if table is None or table.num_rows == 0:
        return []
    return list(zip(*[table.column(k).to_pylist() for k in key_names]))


class StateStore:
    """Versioned key→buffer state with snapshot + changelog persistence."""

    def __init__(self, checkpoint_dir: str | None = None,
                 name: str = "state",
                 snapshot_interval: int = SNAPSHOT_INTERVAL):
        self.table: pa.Table | None = None
        self.dir = None
        self.snapshot_interval = max(1, snapshot_interval)
        self._last_snapshot: int | None = None
        if checkpoint_dir:
            self.dir = os.path.join(checkpoint_dir, name)
            os.makedirs(self.dir, exist_ok=True)

    # --- recovery ---------------------------------------------------------
    def _versions(self, suffix: str) -> list[int]:
        if self.dir is None:
            return []
        out = []
        for f in os.listdir(self.dir):
            if f.endswith(suffix):
                try:
                    out.append(int(f.split(".")[0]))
                except ValueError:
                    pass
        return sorted(out)

    def load(self, version: int) -> None:
        if self.dir is None:
            return
        import pyarrow.parquet as pq

        snaps = [v for v in self._versions(".parquet") if v <= version]
        base = None
        base_v = None
        if snaps:
            base_v = snaps[-1]
            base = pq.read_table(
                os.path.join(self.dir, f"{base_v}.parquet"))
            self._last_snapshot = base_v
        deltas = [v for v in self._versions(".delta.arrow")
                  if v <= version and (base_v is None or v > base_v)]
        if not deltas:
            self.table = base
            return
        # replay: key→row map over python rows, then rebuild the table
        metas = {v: json.load(open(
            os.path.join(self.dir, f"{v}.delta.json"))) for v in deltas}
        key_names = metas[deltas[0]]["key_names"]
        schema = base.schema if base is not None else None
        rows: dict[tuple, dict] = {}
        if base is not None and base.num_rows:
            for r in base.to_pylist():
                rows[tuple(r[k] for k in key_names)] = r
        for v in deltas:
            with pa.ipc.open_file(
                    os.path.join(self.dir, f"{v}.delta.arrow")) as rd:
                ups = rd.read_all()
            if schema is None:
                schema = ups.schema
            for r in ups.to_pylist():
                rows[tuple(r[k] for k in key_names)] = r
            tomb_path = os.path.join(self.dir, f"{v}.tomb.arrow")
            if os.path.exists(tomb_path):
                with pa.ipc.open_file(tomb_path) as rd:
                    tomb = rd.read_all()
                for dk in _key_tuples(tomb, key_names):
                    rows.pop(dk, None)
        if schema is None:
            self.table = None
            return
        self.table = pa.Table.from_pylist(list(rows.values()),
                                          schema=schema)

    # --- commit -----------------------------------------------------------
    def commit(self, version: int, table: pa.Table,
               upsert_keys: Optional[set] = None,
               delete_keys: Optional[Iterable[tuple]] = None,
               key_names: Optional[Sequence[str]] = None) -> None:
        """Persist version. With ``upsert_keys``/``key_names`` supplied the
        commit writes an O(delta) changelog (upserted rows filtered from
        ``table`` + delete tombstones); otherwise, or at the compaction
        interval, a full snapshot."""
        self.table = table
        if self.dir is None:
            return
        incremental = (upsert_keys is not None and key_names is not None
                       and self._last_snapshot is not None
                       and version - self._last_snapshot
                       < self.snapshot_interval)
        if incremental and table is not None:
            ups = self._filter_upserts(table, upsert_keys, key_names)
            with pa.OSFile(os.path.join(self.dir,
                                        f"{version}.delta.arrow"), "wb") as f:
                with pa.ipc.new_file(f, table.schema) as w:
                    w.write_table(ups)
            # delete tombstones travel as an Arrow table of the key
            # columns — JSON cannot round-trip timestamp/date/decimal
            # keys (event-time windows) and would corrupt replay equality
            dk = list(delete_keys or [])
            tomb = pa.table({
                k: pa.array([t[i] for t in dk],
                            type=table.schema.field(k).type)
                for i, k in enumerate(key_names)})
            with pa.OSFile(os.path.join(self.dir,
                                        f"{version}.tomb.arrow"), "wb") as f:
                with pa.ipc.new_file(f, tomb.schema) as w:
                    w.write_table(tomb)
            json.dump({"key_names": list(key_names)},
                      open(os.path.join(self.dir,
                                        f"{version}.delta.json"), "w"))
            return
        import pyarrow.parquet as pq

        if table is None:
            table = pa.table({})
        pq.write_table(table,
                       os.path.join(self.dir, f"{version}.parquet"))
        self._last_snapshot = version
        self._gc(version)

    @staticmethod
    def _filter_upserts(table: pa.Table, upsert_keys: set,
                        key_names: Sequence[str]) -> pa.Table:
        """Rows of ``table`` whose key is in ``upsert_keys``. Single-key
        states filter vectorized (pc.is_in); composite keys take the
        python-tuple path."""
        if table.num_rows == 0:
            return table
        if len(key_names) == 1:
            import pyarrow.compute as pc

            vals = [k[0] for k in upsert_keys]
            col = table.column(key_names[0])
            try:
                return table.filter(
                    pc.is_in(col, value_set=pa.array(
                        vals, type=col.type)))
            except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                pass
        kt = _key_tuples(table, key_names)
        return table.filter(pa.array([k in upsert_keys for k in kt],
                                     type=pa.bool_()))

    def _gc(self, version: int) -> None:
        """Drop snapshots/changelogs older than the previous snapshot (two
        snapshots retained for safety, like the reference's
        minVersionsToRetain)."""
        snaps = self._versions(".parquet")
        keep_from = snaps[-2] if len(snaps) >= 2 else (
            snaps[-1] if snaps else version)
        for f in os.listdir(self.dir):
            try:
                v = int(f.split(".")[0])
            except ValueError:
                continue
            if v < keep_from:
                try:
                    os.remove(os.path.join(self.dir, f))
                except OSError:
                    pass


def _partition_of(key: tuple, num_partitions: int) -> int:
    """Deterministic, process-independent key→partition assignment
    (crc32 over the repr — stable across runs, unlike hash())."""
    import zlib

    return zlib.crc32(repr(key).encode()) % num_partitions


class PartitionedStateStore:
    """Hash-partitioned state: N independent StateStores, each with its
    own snapshot + changelog lineage under ``name/part=K``.

    Role of the reference's per-partition stores
    (sqlx/streaming/state/StateStore.scala:285 — one store per (operator,
    partition), RocksDBStateStoreProvider instances keyed by
    StateStoreId.partitionId): a batch that touches few key ranges
    commits O(touched-partition deltas); a partition with no upserts and
    no deletes writes NOTHING for that version, so recovery replays only
    the partitions each batch actually touched. Drop-in for StateStore:
    same load/commit/table surface, so every stateful operator gains
    partitioning without change."""

    def __init__(self, checkpoint_dir: str | None = None,
                 name: str = "state", num_partitions: int = 4,
                 snapshot_interval: int = SNAPSHOT_INTERVAL):
        self.num_partitions = max(1, int(num_partitions))
        self.parts = [
            StateStore(checkpoint_dir, os.path.join(name, f"part={i}"),
                       snapshot_interval)
            for i in range(self.num_partitions)]
        self.table: pa.Table | None = None
        self.dir = self.parts[0].dir

    # --- recovery ---------------------------------------------------------
    def load(self, version: int) -> None:
        tabs = []
        for p in self.parts:
            p.load(version)
            if p.table is not None and p.table.num_rows:
                tabs.append(p.table)
        self.table = pa.concat_tables(tabs) if tabs else (
            self.parts[0].table if self.parts[0].table is not None else None)

    # --- commit -----------------------------------------------------------
    def commit(self, version: int, table: pa.Table,
               upsert_keys: Optional[set] = None,
               delete_keys: Optional[Iterable[tuple]] = None,
               key_names: Optional[Sequence[str]] = None) -> None:
        self.table = table
        if key_names is None or table is None:
            # no key information: full split + snapshot per partition
            for i, p in enumerate(self.parts):
                p.commit(version, self._slice(table, key_names, i))
            return
        slices = self._split(table, key_names)
        ups_by_part: dict[int, set] = {}
        for k in (upsert_keys or ()):
            ups_by_part.setdefault(
                _partition_of(k, self.num_partitions), set()).add(k)
        del_by_part: dict[int, list] = {}
        for k in (delete_keys or ()):
            del_by_part.setdefault(
                _partition_of(k, self.num_partitions), []).append(k)
        for i, p in enumerate(self.parts):
            ups = ups_by_part.get(i)
            dels = del_by_part.get(i)
            if upsert_keys is not None and not ups and not dels:
                p.table = slices[i]  # untouched: nothing to persist
                continue
            p.commit(version, slices[i], upsert_keys=ups or set(),
                     delete_keys=dels, key_names=key_names)

    def _split(self, table: pa.Table,
               key_names: Sequence[str]) -> list[pa.Table]:
        if table is None or table.num_rows == 0:
            empty = table if table is not None else None
            return [empty] * self.num_partitions
        pids = [_partition_of(k, self.num_partitions)
                for k in _key_tuples(table, key_names)]
        arr = pa.array(pids, type=pa.int32())
        import pyarrow.compute as pc

        return [table.filter(pc.equal(arr, i))
                for i in range(self.num_partitions)]

    def _slice(self, table, key_names, i):
        if table is None:
            return None
        if key_names:
            return self._split(table, key_names)[i]
        # keyless state cannot hash-partition: partition 0 owns it
        return table if i == 0 else table.slice(0, 0)
