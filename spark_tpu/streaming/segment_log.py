"""Partitioned segment-log streaming source — the Kafka contract,
offline.

Role of the reference's Kafka connector (connector/kafka-0-10-sql/ —
KafkaMicroBatchStream, KafkaOffsetReader, KafkaSourceOffset): a topic is
a directory of per-partition append-only segment files; records are
addressed by (partition, offset); consumers replay any offset range;
partitions appear at any time and are discovered between batches (the
rebalance-on-discovery shape); offsets serialize to JSON so the
streaming checkpoint's offset WAL gives exactly-once delivery through
the commit protocol.

Layout:  <root>/partition=<p>/<base-offset 20 digits>.log
Record:  one JSON object per line: {"k": key|null, "v": value,
         "ts": epoch micros}
Offsets: {"<partition>": next_offset} — string keys so a JSON
         round-trip through the checkpoint compares equal.
"""

from __future__ import annotations

import glob
import json
import os
import threading
import time
from typing import Any

import pyarrow as pa

from ..columnar.arrow import schema_from_arrow
from .sources import StreamSource

_SCHEMA = pa.schema([
    ("key", pa.string()),
    ("value", pa.string()),
    ("partition", pa.int32()),
    ("offset", pa.int64()),
    ("timestamp", pa.timestamp("us")),
])


def _partition_dir(root: str, p: int) -> str:
    return os.path.join(root, f"partition={p}")


class SegmentLogWriter:
    """Producer analog (KafkaProducer shape): appends records to a
    partition's active segment, rolling at segment_max_records."""

    def __init__(self, root: str, segment_max_records: int = 1000):
        self.root = root
        self.segment_max = segment_max_records
        self._lock = threading.Lock()
        # partition → (active segment path, base offset, records in it)
        self._active: dict[int, tuple[str, int, int]] = {}
        os.makedirs(root, exist_ok=True)

    def _open_partition(self, p: int) -> tuple[str, int, int]:
        pdir = _partition_dir(self.root, p)
        os.makedirs(pdir, exist_ok=True)
        segs = sorted(glob.glob(os.path.join(pdir, "*.log")))
        if not segs:
            return os.path.join(pdir, f"{0:020d}.log"), 0, 0
        last = segs[-1]
        base = int(os.path.basename(last)[:-4])
        with open(last) as f:
            n = sum(1 for _ in f)
        return last, base, n

    def send(self, partition: int, value: str, key: str | None = None,
             timestamp_us: int | None = None) -> int:
        """Append one record; returns its offset."""
        with self._lock:
            if partition not in self._active:
                self._active[partition] = self._open_partition(partition)
            path, base, n = self._active[partition]
            if n >= self.segment_max:
                base, n = base + n, 0
                path = os.path.join(_partition_dir(self.root, partition),
                                    f"{base:020d}.log")
            off = base + n
            rec = json.dumps({
                "k": key, "v": value,
                "ts": timestamp_us if timestamp_us is not None
                else int(time.time() * 1e6)})
            with open(path, "a") as f:
                f.write(rec + "\n")
            self._active[partition] = (path, base, n + 1)
            return off


class SegmentLogSource(StreamSource):
    """Consumer analog: per-partition offset ranges, arbitrary replay,
    partition discovery between batches."""

    def __init__(self, root: str, starting_offsets: str = "earliest"):
        self.root = root
        self.schema = schema_from_arrow(_SCHEMA)
        self.starting = starting_offsets
        # (path, st_size) → record count; re-counted only on growth
        self._count_cache: dict[tuple[str, int], int] = {}

    # -- log introspection ----------------------------------------------
    def _partitions(self) -> list[int]:
        out = []
        for d in glob.glob(os.path.join(self.root, "partition=*")):
            try:
                out.append(int(os.path.basename(d).split("=", 1)[1]))
            except ValueError:
                continue
        return sorted(out)

    def _segments(self, p: int) -> list[tuple[int, str]]:
        """[(base_offset, path)] sorted."""
        segs = []
        for f in glob.glob(os.path.join(_partition_dir(self.root, p),
                                        "*.log")):
            segs.append((int(os.path.basename(f)[:-4]), f))
        return sorted(segs)

    def _seg_count(self, path: str) -> int:
        size = os.path.getsize(path)
        key = (path, size)
        n = self._count_cache.get(key)
        if n is None:
            with open(path) as f:
                n = sum(1 for _ in f)
            self._count_cache[key] = n
        return n

    def _end_offset(self, p: int) -> int:
        segs = self._segments(p)
        if not segs:
            return 0
        base, path = segs[-1]
        return base + self._seg_count(path)

    # -- StreamSource contract ------------------------------------------
    def initial_offset(self) -> dict:
        if self.starting == "latest":
            return {str(p): self._end_offset(p)
                    for p in self._partitions()}
        if self.starting == "earliest":
            return {}
        # explicit JSON offsets: replay from arbitrary positions
        # (KafkaSourceOffset shape)
        return {str(k): int(v)
                for k, v in json.loads(self.starting).items()}

    def latest_offset(self) -> dict:
        return {str(p): self._end_offset(p) for p in self._partitions()}

    def get_batch(self, start: Any, end: dict) -> pa.Table:
        start = start or {}
        keys, vals, parts, offs, tss = [], [], [], [], []
        for pk, hi in sorted(end.items()):
            p = int(pk)
            lo = int(start.get(pk, 0))  # new partition → from earliest
            if hi <= lo:
                continue
            for base, path in self._segments(p):
                n = self._seg_count(path)
                if base + n <= lo or base >= hi:
                    continue
                with open(path) as f:
                    for i, line in enumerate(f):
                        off = base + i
                        if off < lo or off >= hi:
                            continue
                        rec = json.loads(line)
                        keys.append(rec.get("k"))
                        vals.append(rec.get("v"))
                        parts.append(p)
                        offs.append(off)
                        tss.append(int(rec.get("ts", 0)))
        return pa.table({
            "key": pa.array(keys, pa.string()),
            "value": pa.array(vals, pa.string()),
            "partition": pa.array(parts, pa.int32()),
            "offset": pa.array(offs, pa.int64()),
            "timestamp": pa.array(tss, pa.timestamp("us")),
        })
