from .sources import MemoryStream, RateSource, FileStreamSource  # noqa: F401
from .query import StreamingQuery, StreamingRelation  # noqa: F401
from .api import DataStreamReader, DataStreamWriter  # noqa: F401
