"""Arbitrary stateful processing: applyInPandasWithState.

Role of the reference's FlatMapGroupsWithStateExec /
ApplyInPandasWithStatePythonRunner (sqlx/streaming/
FlatMapGroupsWithStateExec.scala): the user function sees each key's
micro-batch rows as a pandas frame plus a GroupState handle; updated
states persist in the state store as pickled payloads keyed by the
group's JSON-encoded key tuple. Host-side by construction — arbitrary
Python state has no device representation; the columnar engine handles
everything below (the stateless child plan) and above (re-ingestion)."""

from __future__ import annotations

import json
import pickle
from typing import Callable

import pyarrow as pa

from ..plan.logical import LogicalPlan, UnaryNode
from ..expr.expressions import AttributeReference


class GroupState:
    """Per-key state handle (reference: GroupState API)."""

    def __init__(self, raw: bytes | None):
        self._value = pickle.loads(raw) if raw is not None else None
        self._exists = raw is not None
        self._removed = False

    @property
    def exists(self) -> bool:
        return self._exists

    def get(self):
        return self._value

    def update(self, value) -> None:
        self._value = value
        self._exists = True
        self._removed = False

    def remove(self) -> None:
        self._removed = True
        self._exists = False
        self._value = None


class StatefulMapGroups(UnaryNode):
    """Logical node for applyInPandasWithState; must sit at the ROOT of a
    streaming query (arbitrary state forbids operators above it)."""

    equality_excluded_fields = ("fn",)

    def __init__(self, key_names: list[str], fn: Callable,
                 out_attrs: list[AttributeReference], child: LogicalPlan):
        self.key_names = list(key_names)
        self.fn = fn
        self.out_attrs = list(out_attrs)
        self.child = child

    @property
    def output(self):
        return self.out_attrs

    @property
    def resolved(self):
        return self.child.resolved


def run_stateful_map(node: StatefulMapGroups, child_table: pa.Table,
                     state_table: pa.Table | None,
                     out_schema: pa.Schema):
    """One pass: group child rows by key, call fn per key (including keys
    with state but no new rows — timeout-style wakeups are NOT modeled),
    return (output table, new state table)."""
    import pandas as pd

    states: dict[str, bytes] = {}
    if state_table is not None and state_table.num_rows:
        for k, v in zip(state_table.column("__key").to_pylist(),
                        state_table.column("__state").to_pylist()):
            states[k] = v

    pdf = child_table.to_pandas()
    outs = []
    if len(pdf):
        for key, grp in pdf.groupby(node.key_names, dropna=False,
                                    sort=False):
            kt = key if isinstance(key, tuple) else (key,)
            kjson = json.dumps([None if pd.isna(x) else x for x in kt],
                               default=str)
            st = GroupState(states.get(kjson))
            out = node.fn(kt, grp.reset_index(drop=True), st)
            if st._removed:
                states.pop(kjson, None)
            elif st._exists:
                states[kjson] = pickle.dumps(st._value)
            if out is not None and len(out):
                outs.append(pa.Table.from_pandas(
                    out, schema=out_schema, preserve_index=False))

    out_table = pa.concat_tables(outs) if outs else out_schema.empty_table()
    new_state = pa.table({
        "__key": pa.array(list(states.keys()), pa.string()),
        "__state": pa.array(list(states.values()), pa.binary()),
    })
    return out_table, new_state
