"""RDD: resilient distributed dataset over host partitions.

Role of the reference's core RDD API (core/rdd/RDD.scala, 2290 LoC;
PairRDDFunctions.scala; Dependency.scala narrow vs ShuffleDependency;
core/Partitioner.scala). Design stance: arbitrary-Python-closure datasets
cannot run on the TPU (the reference has the same split — RDD lambdas never
enter Tungsten codegen either); the RDD layer is the host-side escape hatch,
executed by a lineage-driven stage runner with hash shuffles at wide
dependencies, while columnar/SQL work takes the device path. `to_df` /
`DataFrame.rdd` bridge the two.

Execution: narrow chains fuse into one pass per partition (pipelining, the
role of Spark's task pipelining); wide ops cut stages and materialize a
host hash shuffle (MapOutputTracker analog is the in-memory `_shuffle`
output dict). A thread pool runs partitions concurrently.
"""

from __future__ import annotations

import bisect
import builtins
import hashlib
import itertools
import os
import pickle
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Sequence, TypeVar

T = TypeVar("T")
U = TypeVar("U")


def _stable_hash(x: Any) -> int:
    """Deterministic cross-run hash for shuffle partitioning (python's
    builtin hash is salted for str)."""
    if isinstance(x, int):
        return x
    if isinstance(x, str):
        return int.from_bytes(
            hashlib.blake2b(x.encode(), digest_size=8).digest(), "little")
    try:
        return int.from_bytes(
            hashlib.blake2b(pickle.dumps(x), digest_size=8).digest(), "little")
    except Exception:
        return hash(x)


class Partitioner:
    """core/Partitioner.scala analog."""

    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions

    def partition(self, key: Any) -> int:
        return _stable_hash(key) % self.num_partitions

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.num_partitions == other.num_partitions)

    def __hash__(self):
        return hash((type(self).__name__, self.num_partitions))


class RangePartitionerHost(Partitioner):
    def __init__(self, bounds: list):
        super().__init__(len(bounds) + 1)
        self.bounds = bounds

    def partition(self, key: Any) -> int:
        return bisect.bisect_right(self.bounds, key)


class RDD:
    """Lazy lineage node."""

    def __init__(self, context: "RDDContext", num_partitions: int,
                 parents: Sequence["RDD"] = ()):
        self.context = context
        self._num_partitions = num_partitions
        self.parents = list(parents)
        self.id = context._next_rdd_id()
        self._cache: list[list] | None = None
        self._cached_flag = False
        self._checkpoint_dir: str | None = None

    # --- to be implemented by subclasses ---------------------------------
    def compute(self, split: int) -> Iterator:
        raise NotImplementedError

    def num_partitions(self) -> int:
        return self._num_partitions

    getNumPartitions = num_partitions

    # --- iteration with cache (BlockManager role) ------------------------
    def iterator(self, split: int) -> Iterator:
        if self._cache is not None and self._cache[split] is not None:
            return iter(self._cache[split])
        if self._checkpoint_dir is not None:
            path = os.path.join(self._checkpoint_dir, f"part-{split:05d}.pkl")
            if os.path.exists(path):
                with open(path, "rb") as f:
                    return iter(pickle.load(f))
        it = self.compute(split)
        if self._cached_flag:
            data = list(it)
            if self._cache is None:
                self._cache = [None] * self.num_partitions()
            self._cache[split] = data
            return iter(data)
        return it

    # --- persistence ------------------------------------------------------
    def cache(self) -> "RDD":
        self._cached_flag = True
        if self._cache is None:
            self._cache = [None] * self.num_partitions()
        return self

    persist = cache

    def unpersist(self) -> "RDD":
        self._cached_flag = False
        self._cache = None
        return self

    def checkpoint(self, directory: str | None = None) -> "RDD":
        """Materialize partitions to reliable storage and truncate lineage
        (reference: core/rdd/RDD.scala:1736, ReliableCheckpointRDD:41)."""
        d = directory or self.context.checkpoint_dir
        if d is None:
            raise ValueError("no checkpoint dir set")
        cdir = os.path.join(d, f"rdd-{self.id}")
        os.makedirs(cdir, exist_ok=True)
        for i in range(self.num_partitions()):
            with open(os.path.join(cdir, f"part-{i:05d}.pkl"), "wb") as f:
                pickle.dump(list(self.iterator(i)), f)
        self._checkpoint_dir = cdir
        self.parents = []  # lineage truncation
        return self

    # --- narrow transformations ------------------------------------------
    def map(self, f: Callable[[T], U]) -> "RDD":
        return MapPartitionsRDD(self, lambda it, _s: builtins.map(f, it))

    def flatMap(self, f: Callable[[T], Iterable[U]]) -> "RDD":
        return MapPartitionsRDD(
            self, lambda it, _s: itertools.chain.from_iterable(
                builtins.map(f, it)))

    def filter(self, f: Callable[[T], bool]) -> "RDD":
        return MapPartitionsRDD(self, lambda it, _s: builtins.filter(f, it))

    def mapPartitions(self, f: Callable[[Iterator], Iterable]) -> "RDD":
        return MapPartitionsRDD(self, lambda it, _s: f(it))

    def mapPartitionsWithIndex(self, f) -> "RDD":
        return MapPartitionsRDD(self, lambda it, s: f(s, it))

    def glom(self) -> "RDD":
        return MapPartitionsRDD(self, lambda it, _s: iter([list(it)]))

    def keyBy(self, f) -> "RDD":
        return self.map(lambda x: (f(x), x))

    def zipWithIndex(self) -> "RDD":
        counts = self.mapPartitionsWithIndex(
            lambda s, it: iter([(s, sum(1 for _ in it))])).collect()
        offsets = {}
        acc = 0
        for s, c in sorted(counts):
            offsets[s] = acc
            acc += c

        def zipper(s, it):
            return ((x, offsets[s] + i) for i, x in enumerate(it))

        return self.mapPartitionsWithIndex(zipper)

    def union(self, other: "RDD") -> "RDD":
        return UnionRDD(self.context, [self, other])

    def zip(self, other: "RDD") -> "RDD":
        assert self.num_partitions() == other.num_partitions()
        return ZipRDD(self, other)

    def sample(self, withReplacement: bool, fraction: float,
               seed: int = 42) -> "RDD":
        import random

        def sampler(s, it):
            rnd = random.Random(seed + s)
            if withReplacement:
                items = list(it)
                k = int(len(items) * fraction)
                return iter([rnd.choice(items) for _ in range(k)] if items else [])
            return (x for x in it if rnd.random() < fraction)

        return self.mapPartitionsWithIndex(sampler)

    def pipe(self, command: str) -> "RDD":
        """Pipe partition elements through a shell command
        (reference: core/rdd/PipedRDD.scala)."""
        import subprocess

        def run(it, _s):
            inp = "\n".join(str(x) for x in it)
            out = subprocess.run(command, shell=True, input=inp, text=True,
                                 capture_output=True, check=True)
            return iter(out.stdout.splitlines())

        return MapPartitionsRDD(self, run)

    def coalesce(self, n: int) -> "RDD":
        return CoalescedRDD(self, max(1, n))

    def repartition(self, n: int) -> "RDD":
        return self.map(lambda x: (None, x)) \
                   ._shuffled(Partitioner(n), spread=True) \
                   .map(lambda kv: kv[1])

    def distinct(self, numPartitions: int | None = None) -> "RDD":
        n = numPartitions or self.num_partitions()
        return (self.map(lambda x: (x, None))
                .reduceByKey(lambda a, b: a, n)
                .map(lambda kv: kv[0]))

    # --- pair (shuffle) transformations -----------------------------------
    def _shuffled(self, partitioner: Partitioner, spread=False) -> "ShuffledRDD":
        return ShuffledRDD(self, partitioner, spread=spread)

    def partitionBy(self, numPartitions: int) -> "RDD":
        return self._shuffled(Partitioner(numPartitions))

    def groupByKey(self, numPartitions: int | None = None) -> "RDD":
        n = numPartitions or self.num_partitions()

        def group(it, _s):
            d: dict = {}
            for k, v in it:
                d.setdefault(k, []).append(v)
            return iter(d.items())

        return MapPartitionsRDD(self._shuffled(Partitioner(n)), group)

    def reduceByKey(self, f, numPartitions: int | None = None) -> "RDD":
        n = numPartitions or self.num_partitions()

        def combine(it, _s):
            d: dict = {}
            for k, v in it:
                d[k] = f(d[k], v) if k in d else v
            return iter(d.items())

        # map-side combine, then shuffle, then reduce-side combine
        pre = MapPartitionsRDD(self, combine)
        return MapPartitionsRDD(pre._shuffled(Partitioner(n)), combine)

    def combineByKey(self, createCombiner, mergeValue, mergeCombiners,
                     numPartitions: int | None = None) -> "RDD":
        n = numPartitions or self.num_partitions()

        def precombine(it, _s):
            d: dict = {}
            for k, v in it:
                d[k] = mergeValue(d[k], v) if k in d else createCombiner(v)
            return iter(d.items())

        def merge(it, _s):
            d: dict = {}
            for k, c in it:
                d[k] = mergeCombiners(d[k], c) if k in d else c
            return iter(d.items())

        pre = MapPartitionsRDD(self, precombine)
        return MapPartitionsRDD(pre._shuffled(Partitioner(n)), merge)

    def aggregateByKey(self, zero, seqFunc, combFunc,
                       numPartitions: int | None = None) -> "RDD":
        import copy

        return self.combineByKey(
            lambda v: seqFunc(copy.deepcopy(zero), v),
            seqFunc, combFunc, numPartitions)

    def mapValues(self, f) -> "RDD":
        return self.map(lambda kv: (kv[0], f(kv[1])))

    def flatMapValues(self, f) -> "RDD":
        return self.flatMap(lambda kv: ((kv[0], v) for v in f(kv[1])))

    def keys(self) -> "RDD":
        return self.map(lambda kv: kv[0])

    def values(self) -> "RDD":
        return self.map(lambda kv: kv[1])

    def cogroup(self, other: "RDD", numPartitions: int | None = None) -> "RDD":
        n = numPartitions or max(self.num_partitions(), other.num_partitions())
        tagged = self.mapValues(lambda v: (0, v)).union(
            other.mapValues(lambda v: (1, v)))

        def group(it, _s):
            d: dict = {}
            for k, (tag, v) in it:
                d.setdefault(k, ([], []))[tag].append(v)
            return iter(d.items())

        return MapPartitionsRDD(tagged._shuffled(Partitioner(n)), group)

    def join(self, other: "RDD", numPartitions: int | None = None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for a in kv[1][0] for b in kv[1][1]))

    def leftOuterJoin(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for a in kv[1][0]
                        for b in (kv[1][1] or [None])))

    def rightOuterJoin(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for b in kv[1][1]
                        for a in (kv[1][0] or [None])))

    def fullOuterJoin(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for a in (kv[1][0] or [None])
                        for b in (kv[1][1] or [None])))

    def subtractByKey(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], v) for v in kv[1][0] if not kv[1][1]))

    def sortByKey(self, ascending: bool = True,
                  numPartitions: int | None = None) -> "RDD":
        n = numPartitions or self.num_partitions()
        sample = self.map(lambda kv: kv[0]).takeSample(min(n * 20, 1000))
        sample.sort()
        if len(sample) > 1 and n > 1:
            idx = [int(round(i * (len(sample) - 1) / n)) for i in range(1, n)]
            bounds = sorted(set(sample[i] for i in idx))
            part = RangePartitionerHost(bounds)
        else:
            part = Partitioner(1)
        shuffled = self._shuffled(part)

        def sort_part(it, _s):
            data = sorted(it, key=lambda kv: kv[0], reverse=not ascending)
            return iter(data)

        out = MapPartitionsRDD(shuffled, sort_part)
        out._ordered_desc = not ascending
        return out

    def sortBy(self, keyfunc, ascending: bool = True,
               numPartitions: int | None = None) -> "RDD":
        return (self.keyBy(keyfunc)
                .sortByKey(ascending, numPartitions)
                .map(lambda kv: kv[1]))

    # --- actions -----------------------------------------------------------
    def collect(self) -> list:
        parts = self.context._run(self)
        if getattr(self, "_ordered_desc", False):
            parts = parts[::-1]
        return [x for p in parts for x in p]

    def count(self) -> int:
        return sum(self.context._run_map(
            self, lambda it: sum(1 for _ in it)))

    def reduce(self, f):
        parts = [p for p in self.context._run_map(
            self, lambda it: _reduce_or_none(f, it)) if p is not _EMPTY]
        if not parts:
            raise ValueError("reduce on empty RDD")
        out = parts[0]
        for p in parts[1:]:
            out = f(out, p)
        return out

    def fold(self, zero, f):
        parts = self.context._run_map(
            self, lambda it: _fold(zero, f, it))
        out = zero
        for p in parts:
            out = f(out, p)
        return out

    def aggregate(self, zero, seqOp, combOp):
        import copy

        parts = self.context._run_map(
            self, lambda it: _fold(copy.deepcopy(zero), seqOp, it))
        out = zero
        for p in parts:
            out = combOp(out, p)
        return out

    def take(self, n: int) -> list:
        out: list = []
        for i in range(self.num_partitions()):
            for x in self.iterator(i):
                out.append(x)
                if len(out) >= n:
                    return out
        return out

    def first(self):
        got = self.take(1)
        if not got:
            raise ValueError("empty RDD")
        return got[0]

    def takeSample(self, n: int, seed: int = 42) -> list:
        import random

        data = self.collect()
        rnd = random.Random(seed)
        if len(data) <= n:
            return data
        return rnd.sample(data, n)

    def foreach(self, f) -> None:
        self.context._run_map(self, lambda it: [f(x) for x in it] and None)

    def foreachPartition(self, f) -> None:
        self.context._run_map(self, lambda it: f(it))

    def countByKey(self) -> dict:
        out: dict = {}
        for k, _v in self.collect():
            out[k] = out.get(k, 0) + 1
        return out

    def countByValue(self) -> dict:
        out: dict = {}
        for x in self.collect():
            out[x] = out.get(x, 0) + 1
        return out

    def top(self, n: int) -> list:
        import heapq

        parts = self.context._run_map(
            self, lambda it: heapq.nlargest(n, it))
        return heapq.nlargest(n, itertools.chain.from_iterable(parts))

    def max(self):  # noqa: A003
        return self.reduce(lambda a, b: a if a >= b else b)

    def min(self):  # noqa: A003
        return self.reduce(lambda a, b: a if a <= b else b)

    def sum(self):  # noqa: A003
        return self.fold(0, lambda a, b: a + b)

    def mean(self):
        n, s = self.aggregate((0, 0),
                              lambda z, x: (z[0] + 1, z[1] + x),
                              lambda a, b: (a[0] + b[0], a[1] + b[1]))
        return s / n

    def isEmpty(self) -> bool:
        return not self.take(1)

    def saveAsTextFile(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        for i in range(self.num_partitions()):
            with open(os.path.join(path, f"part-{i:05d}"), "w") as f:
                for x in self.iterator(i):
                    f.write(str(x) + "\n")

    # --- DataFrame bridge ---------------------------------------------------
    def toDF(self, session, schema=None):
        data = self.collect()
        return session.createDataFrame(data, schema)


_EMPTY = object()


def _reduce_or_none(f, it):
    out = _EMPTY
    for x in it:
        out = x if out is _EMPTY else f(out, x)
    return out


def _fold(zero, f, it):
    out = zero
    for x in it:
        out = f(out, x)
    return out


# ---------------------------------------------------------------------------
# Concrete RDDs
# ---------------------------------------------------------------------------

class ParallelCollectionRDD(RDD):
    def __init__(self, context, data: Sequence, num_partitions: int):
        super().__init__(context, num_partitions)
        self.data = list(data)

    def compute(self, split: int) -> Iterator:
        n = len(self.data)
        per = -(-n // self._num_partitions) if n else 0
        lo = min(split * per, n)
        hi = min(lo + per, n)
        return iter(self.data[lo:hi])


class TextFileRDD(RDD):
    def __init__(self, context, paths: list[str]):
        super().__init__(context, max(1, len(paths)))
        self.paths = paths

    def compute(self, split: int) -> Iterator:
        with open(self.paths[split]) as f:
            for line in f:
                yield line.rstrip("\n")


class MapPartitionsRDD(RDD):
    def __init__(self, parent: RDD, fn: Callable[[Iterator, int], Iterator]):
        super().__init__(parent.context, parent.num_partitions(), [parent])
        self.fn = fn

    def compute(self, split: int) -> Iterator:
        return self.fn(self.parents[0].iterator(split), split)


class UnionRDD(RDD):
    def __init__(self, context, rdds: list[RDD]):
        super().__init__(context, sum(r.num_partitions() for r in rdds), rdds)

    def compute(self, split: int) -> Iterator:
        for r in self.parents:
            if split < r.num_partitions():
                return r.iterator(split)
            split -= r.num_partitions()
        raise IndexError(split)


class ZipRDD(RDD):
    def __init__(self, a: RDD, b: RDD):
        super().__init__(a.context, a.num_partitions(), [a, b])

    def compute(self, split: int) -> Iterator:
        return zip(self.parents[0].iterator(split),
                   self.parents[1].iterator(split))


class CoalescedRDD(RDD):
    def __init__(self, parent: RDD, n: int):
        super().__init__(parent.context, min(n, parent.num_partitions()),
                         [parent])

    def compute(self, split: int) -> Iterator:
        parent = self.parents[0]
        pn = parent.num_partitions()
        mine = range(split, pn, self._num_partitions)
        return itertools.chain.from_iterable(
            parent.iterator(i) for i in mine)


class ShuffledRDD(RDD):
    """Wide dependency: materializes the map side grouped by reducer
    (reference: core/rdd/ShuffledRDD.scala + SortShuffleManager write path).
    `spread` distributes non-keyed rows round-robin (repartition)."""

    def __init__(self, parent: RDD, partitioner: Partitioner,
                 spread: bool = False):
        import threading

        super().__init__(parent.context, partitioner.num_partitions, [parent])
        self.partitioner = partitioner
        self.spread = spread
        self._fetched: list[list] | None = None
        self._lock = threading.Lock()

    def __getstate__(self):
        state = dict(self.__dict__)
        state.pop("_lock", None)
        return state

    def __setstate__(self, state):
        import threading

        self.__dict__.update(state)
        self._lock = threading.Lock()

    def _materialize(self) -> list[list]:
        if self._fetched is not None:
            return self._fetched
        with self._lock:
            return self._materialize_locked()

    def _materialize_locked(self) -> list[list]:
        if self._fetched is not None:
            return self._fetched
        parent = self.parents[0]
        n = self.partitioner.num_partitions

        def map_task(split: int) -> list[list]:
            buckets: list[list] = [[] for _ in range(n)]
            if self.spread:
                for i, kv in enumerate(parent.iterator(split)):
                    buckets[(split + i) % n].append(kv)
            else:
                for kv in parent.iterator(split):
                    buckets[self.partitioner.partition(kv[0])].append(kv)
            return buckets

        results = self.context._parallel(
            map_task, range(parent.num_partitions()))
        out: list[list] = [[] for _ in range(n)]
        for buckets in results:
            for i, b in enumerate(buckets):
                out[i].extend(b)
        self._fetched = out
        return out

    def compute(self, split: int) -> Iterator:
        return iter(self._materialize()[split])


# ---------------------------------------------------------------------------
# Context
# ---------------------------------------------------------------------------

class Broadcast:
    """Read-only shared value (reference: core/broadcast/TorrentBroadcast.scala
    — in-process, the torrent distribution is a no-op locally)."""

    def __init__(self, value):
        self._value = value

    @property
    def value(self):
        return self._value

    def unpersist(self):
        self._value = None


class Accumulator:
    """Commutative counter aggregated at the driver (reference:
    core/util/AccumulatorV2.scala)."""

    def __init__(self, value, op=lambda a, b: a + b):
        import threading

        self._value = value
        self._op = op
        self._lock = threading.Lock()

    def add(self, v):
        with self._lock:
            self._value = self._op(self._value, v)

    __iadd__ = None

    @property
    def value(self):
        return self._value


class RDDContext:
    """Driver context (role of SparkContext for the RDD layer)."""

    def __init__(self, parallelism: int = 8,
                 checkpoint_dir: str | None = None, cluster=None):
        import threading

        self.parallelism = parallelism
        self.checkpoint_dir = checkpoint_dir
        self.cluster = cluster  # exec/cluster.LocalCluster for process mode
        self._rdd_counter = itertools.count()
        self._pool_inst = None  # lazy: no threads until the first job
        self._pool_lock = threading.Lock()
        self._in_task = threading.local()

    # workers receive the lineage graph; runtime state stays driver-side
    # (the reference marks SparkContext @transient in closures)
    def __getstate__(self):
        state = dict(self.__dict__)
        for k in ("_pool_inst", "_pool_lock", "_in_task", "cluster",
                  "_rdd_counter"):
            state.pop(k, None)
        return state

    def __setstate__(self, state):
        import threading

        self.__dict__.update(state)
        self.cluster = None
        self._rdd_counter = itertools.count(1 << 20)
        self._pool_inst = None
        self._pool_lock = threading.Lock()
        self._in_task = threading.local()

    @property
    def _pool(self) -> ThreadPoolExecutor:
        with self._pool_lock:
            if self._pool_inst is None:
                self._pool_inst = ThreadPoolExecutor(
                    max_workers=self.parallelism)
            return self._pool_inst

    def _next_rdd_id(self) -> int:
        return next(self._rdd_counter)

    def setCheckpointDir(self, d: str) -> None:
        self.checkpoint_dir = d

    def parallelize(self, data: Sequence, numSlices: int | None = None) -> RDD:
        return ParallelCollectionRDD(self, data,
                                     numSlices or self.parallelism)

    def range(self, start, end=None, step=1, numSlices=None) -> RDD:
        if end is None:
            start, end = 0, start
        return self.parallelize(builtins.range(start, end, step), numSlices)

    def textFile(self, path: str) -> RDD:
        import glob as g

        paths = sorted(g.glob(path)) if any(c in path for c in "*?[") \
            else ([os.path.join(path, p) for p in sorted(os.listdir(path))]
                  if os.path.isdir(path) else [path])
        return TextFileRDD(self, paths)

    def broadcast(self, value) -> Broadcast:
        return Broadcast(value)

    def accumulator(self, value, op=lambda a, b: a + b) -> Accumulator:
        return Accumulator(value, op)

    def union(self, rdds: list[RDD]) -> RDD:
        return UnionRDD(self, rdds)

    # --- execution ---------------------------------------------------------
    def _parallel(self, fn, splits) -> list:
        # nested jobs (a shuffle materializing inside a pool task) run
        # inline — submitting to the same bounded pool from a worker
        # deadlocks (the reference's DAGScheduler avoids this by running
        # shuffle map stages as separate task sets, not nested calls)
        if getattr(self._in_task, "flag", False):
            return [fn(s) for s in splits]
        if self.cluster is not None:
            return self.cluster.map(fn, list(splits))

        def wrapped(s):
            self._in_task.flag = True
            try:
                return fn(s)
            finally:
                self._in_task.flag = False

        # scoped_submit (NOT pool.submit): each split task re-enters the
        # caller's contextvar scope, so RDD jobs running inside a traced
        # query keep their kernel-ledger/span attribution on pool threads
        from ..obs.metrics import scoped_submit

        futures = [scoped_submit(self._pool, wrapped, s) for s in splits]
        return [f.result() for f in futures]

    def _run(self, rdd: RDD) -> list[list]:
        return self._parallel(lambda s: list(rdd.iterator(s)),
                              range(rdd.num_partitions()))

    def _run_map(self, rdd: RDD, agg) -> list:
        return self._parallel(lambda s: agg(rdd.iterator(s)),
                              range(rdd.num_partitions()))

    def stop(self):
        if self._pool_inst is not None:
            self._pool_inst.shutdown(wait=True)
            self._pool_inst = None
