from .rdd import (  # noqa: F401
    RDD, RDDContext, Broadcast, Accumulator, Partitioner,
)
