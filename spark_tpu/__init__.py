"""spark_tpu — a TPU-native large-scale analytics engine with Apache Spark's
capabilities, built on JAX/XLA (see SURVEY.md for the architecture map against
the reference)."""

__version__ = "0.1.0"

from .api.session import SparkSession, TpuSession  # noqa: F401
from .api.dataframe import DataFrame, Row  # noqa: F401
from .api.column import Column  # noqa: F401
from .errors import AnalysisException, ParseException, SparkTpuError  # noqa: F401
from . import types  # noqa: F401
