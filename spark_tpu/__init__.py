"""spark_tpu — a TPU-native large-scale analytics engine with Apache Spark's
capabilities, built on JAX/XLA (see SURVEY.md for the architecture map against
the reference).

Exports resolve lazily (PEP 562) so engine-free subpackages — the Connect
thin client (`spark_tpu.connect.client`) and the network transport
(`spark_tpu.net`) — can be imported without dragging in jax or the SQL
engine, mirroring the reference's sql/api vs sql/core split where the
Connect client depends only on the interface layer."""

__version__ = "0.1.0"

_EXPORTS = {
    "SparkSession": ".api.session",
    "TpuSession": ".api.session",
    "DataFrame": ".api.dataframe",
    "Row": ".api.dataframe",
    "Column": ".api.column",
    "AnalysisException": ".errors",
    "ParseException": ".errors",
    "SparkTpuError": ".errors",
}

__all__ = [*_EXPORTS, "types"]


def __getattr__(name):
    import importlib

    if name == "types":
        mod = importlib.import_module(".types", __name__)
        globals()[name] = mod
        return mod
    home = _EXPORTS.get(name)
    if home is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    val = getattr(importlib.import_module(home, __name__), name)
    globals()[name] = val
    return val


def __dir__():
    return sorted(set(globals()) | set(__all__))
