"""Runtime observability: always-on query tracing + per-operator metrics.

Role of the reference's SQLMetrics / SQL-tab plan graph / event-log
pipeline (sqlx/metric/SQLMetrics.scala, sqlx/execution/ui/SparkPlanGraph
.scala, core/scheduler/EventLoggingListener.scala), extended with the
numbers that matter on a TPU: per-operator kernel-launch and compile-ms
attribution (scoped KernelCache counters, re-attributed through
whole-stage fusion) and a span timeline exportable as Perfetto/Chrome
trace JSON.

Design constraint (enforced by tests/test_observability.py): collection
adds ZERO kernel launches and ZERO mid-query device syncs — row counts
come from host-side batch metadata, and unresolved live-row masks are
pulled once per distinct mask identity at query end (parked under a
per-query byte budget so metrics-on never pins unbounded HBM).
"""

from .tracing import (  # noqa: F401
    Tracer, current_flow, current_query, pop_query, push_query,
    to_chrome_trace,
)
from .metrics import (  # noqa: F401
    AnalyzedReport, current_op_name, export_op_records,
    finalize_plan_metrics, fused_members, merge_op_records, new_op_record,
    pop_op, push_op, record_kernel_compile, record_kernel_launch,
    scoped_submit,
)
from .history import (  # noqa: F401
    ProfileStore, detect_regressions, plan_fingerprint, query_key,
)
