"""Service metrics plane: one process-wide registry of typed metric
instruments with mergeable histograms and Prometheus-style export.

Role of the reference's MetricsSystem + sinks (core/metrics/
MetricsSystem.scala routing Codahale registries into the
PrometheusServlet / JmxSink / CSV sinks), re-shaped for a serving
engine whose operational signals already exist but are scattered:
KernelCache launch/compile/disk-hit counters, transport retry stats,
result/compile cache hits, DeviceLedger HBM occupancy, fair-pool
queue depths, straggler/regression finding counts. This module unifies
them under stable dotted names with ``{pool, session, executor}``
labels and exports them three ways:

  * **Prometheus text format** — ``render_prometheus()`` backs the
    history server's ``/metrics`` endpoint and the SQL endpoint's
    ``{"metrics": true}`` request. ``parse_prometheus()`` is the
    round-trip reader the gates and bench scrape with.

  * **a bounded time-series ring** — a ticker thread samples the gauge
    surface every ``spark.tpu.metrics.tickInterval`` seconds into a
    fixed ring (``spark.tpu.metrics.ringSize``), feeding sparkline data
    into serve status and the drain-time snapshot.

  * **per-executor deltas on the heartbeat** — workers attach
    ``executor_payload()`` (cumulative counter snapshots: lost beats
    lose nothing, the next one carries the totals) to the existing obs
    heartbeat; the driver stores them per executor id and its scrape
    renders worker-labeled series — the same merge path a fleet broker
    aggregating N replicas will use (ROADMAP direction 2).

**Mergeable histograms.** Latency distributions use FIXED log-spaced
bucket bounds shared by every process (``BUCKET_BOUNDS``): merging two
histograms is element-wise bucket addition, so a two-process merge
reproduces the single-registry quantile buckets EXACTLY — the property
sample-ring percentiles fundamentally lack (you cannot merge two p99s).
``quantile()`` answers from the cumulative bucket counts and is
therefore identical before and after any merge of the same
observations.

Obs contract (same as the rest of obs/): everything here is pure host
bookkeeping — zero kernel launches, no device syncs — and the plane is
structurally zero-overhead when ``spark.tpu.metrics.export`` is off:
call sites gate on the module bool ``ENABLED`` (one attribute read, the
utils/faults.py discipline), the ticker thread never starts, heartbeats
carry no metrics field, and source collection only ever runs at scrape
time. Locked instruments follow the utils/counters.LockedCounter
discipline: mutation under an internal lock, the lock slot
lockwatch-registered, ``check_guard`` probes inside the critical
section.
"""

from __future__ import annotations

import re
import sys
import threading
import time
from bisect import bisect_left
from collections import deque

from ..utils import lockwatch

__all__ = [
    "BUCKET_BOUNDS", "ENABLED", "Histogram", "MetricsRegistry",
    "REGISTRY", "configure", "executor_payload", "parse_prometheus",
    "register_default_sources", "render_prometheus", "start_ticker",
    "stop_ticker", "timeseries_snapshot",
]

# fast-path flag (utils/faults.py discipline): instrumented call sites
# read ONE module attribute before doing anything — export off means no
# registry work, no ticker, no heartbeat field, structurally
ENABLED = False

# ---------------------------------------------------------------------------
# fixed log-spaced histogram buckets
# ---------------------------------------------------------------------------

# Bucket bounds are a PROCESS-INDEPENDENT constant: every histogram in
# every process uses these exact upper edges (ms), so cross-process
# merge is element-wise addition and quantiles are merge-invariant.
# 0.05ms * sqrt(2)^i for 44 buckets spans 0.05ms .. ~154s — sub-ms
# cache hits through multi-minute drains at ~41% bucket resolution.
_BUCKET_BASE_MS = 0.05
_BUCKET_RATIO = 2.0 ** 0.5
_NUM_BUCKETS = 44
BUCKET_BOUNDS: tuple = tuple(
    _BUCKET_BASE_MS * _BUCKET_RATIO ** i for i in range(_NUM_BUCKETS))


class Histogram:
    """Fixed log-bucket mergeable histogram (counts per BUCKET_BOUNDS
    upper edge plus one overflow bucket). Thread-safe behind its own
    per-instance lock (wrapped by lockwatch when watching is live at
    creation — the per-instance `maybe_wrap` path)."""

    __slots__ = ("_lock", "counts", "count", "sum", "min", "max")

    def __init__(self):
        self._lock = lockwatch.maybe_wrap("obs.export.Histogram._lock",
                                          threading.Lock())
        self.counts = [0] * (_NUM_BUCKETS + 1)
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect_left(BUCKET_BOUNDS, v)
        with self._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    # -- merge (the cross-process leg) ------------------------------------
    def merge(self, other: "Histogram") -> "Histogram":
        """Fold `other` into self (element-wise bucket addition: exact,
        order-independent). Returns self for chaining. snapshot() takes
        other's lock; never both locks at once (no ordering to get
        wrong between two instances of the same class)."""
        return self.merge_snapshot(other.snapshot())

    def merge_snapshot(self, snap: dict) -> "Histogram":
        counts = snap.get("counts") or []
        if len(counts) != _NUM_BUCKETS + 1:
            raise ValueError(
                f"histogram merge: {len(counts)} buckets != "
                f"{_NUM_BUCKETS + 1} — bucket layouts must be identical")
        with self._lock:
            for i, c in enumerate(counts):
                self.counts[i] += int(c)
            self.count += int(snap.get("count", 0))
            self.sum += float(snap.get("sum", 0.0))
            for k, pick in (("min", min), ("max", max)):
                v = snap.get(k)
                if v is not None:
                    cur = getattr(self, k)
                    setattr(self, k,
                            v if cur is None else pick(cur, v))
        return self

    @classmethod
    def from_snapshot(cls, snap: dict) -> "Histogram":
        return cls().merge_snapshot(snap)

    # -- reads ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"counts": list(self.counts), "count": self.count,
                    "sum": self.sum, "min": self.min, "max": self.max}

    def quantile(self, q: float):
        """Upper edge of the bucket holding the q-quantile (a bound, not
        an interpolation: merge-invariant by construction). Overflow
        observations answer with the observed max. None when empty."""
        lo, hi = self.quantile_bounds(q)
        return hi

    def quantile_bounds(self, q: float) -> tuple:
        """(lower, upper) edges of the q-quantile's bucket: the true
        sample quantile of the observed values is always inside."""
        with self._lock:
            if self.count == 0:
                return (None, None)
            target = max(1, int(q * self.count + 0.999999))
            cum = 0
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= target:
                    lo = BUCKET_BOUNDS[i - 1] if i > 0 else 0.0
                    hi = (BUCKET_BOUNDS[i] if i < _NUM_BUCKETS
                          else self.max)
                    return (lo, hi)
            return (0.0, self.max)       # unreachable; guards drift

    def percentile_ms(self, q: float):
        """Display form: the quantile bucket's upper edge rounded for
        status payloads (the serve status p50/p95/p99 surface)."""
        v = self.quantile(q)
        return None if v is None else round(float(v), 3)


# ---------------------------------------------------------------------------
# registry of typed instruments
# ---------------------------------------------------------------------------

class _Counter:
    """A registry counter: mutation under the owning registry's lock
    (LockedCounter discipline — the registry lock is the registered,
    guard-checked slot shared by the instrument family)."""

    __slots__ = ("name", "labels", "_registry", "_value")

    def __init__(self, name: str, labels: tuple, registry):
        self.name = name
        self.labels = labels
        self._registry = registry
        self._value = 0

    def inc(self, n: int = 1) -> int:
        reg = self._registry
        with reg._lock:
            if lockwatch.ENABLED and reg._guard:
                lockwatch.check_guard(f"obs.export.counter.{self.name}",
                                      reg._guard)
            self._value += int(n)
            return self._value

    @property
    def value(self) -> int:
        with self._registry._lock:
            return self._value


class _Gauge:
    """Lazily-sampled gauge: holds a zero-argument callable evaluated
    only at collect/scrape/tick time — never on the query hot path."""

    __slots__ = ("name", "labels", "fn")

    def __init__(self, name: str, labels: tuple, fn):
        self.name = name
        self.labels = labels
        self.fn = fn

    def sample(self):
        try:
            v = self.fn()
        except Exception:
            return None
        return None if v is None else float(v)


class MetricsRegistry:
    """Process-wide table of typed instruments plus pluggable external
    sources (scrape-time pulls of counters that already live elsewhere:
    the KernelCache, RETRY_STATS, the device ledger, pool states...).

    `slot` names the lockwatch registration for the registry lock; only
    the module-global REGISTRY registers (secondary instances in tests
    stay unwatched — their mutations are still locked, just not
    guard-probed)."""

    def __init__(self, slot: str | None = None):
        self._lock = threading.Lock()
        self._guard = None
        if slot:
            lockwatch.register(slot, self, "_lock")
            self._guard = slot
        self._counters: dict = {}     # (name, labels) -> _Counter
        self._gauges: dict = {}       # (name, labels) -> _Gauge
        self._hists: dict = {}        # (name, labels) -> Histogram
        self._sources: dict = {}      # key -> fn() -> [sample, ...]

    # -- instrument access (get-or-create) --------------------------------
    @staticmethod
    def _label_key(labels: dict) -> tuple:
        return tuple(sorted((str(k), str(v)) for k, v in labels.items()))

    def counter(self, name: str, **labels) -> _Counter:
        key = (name, self._label_key(labels))
        with self._lock:
            c = self._counters.get(key)
            if c is None:
                c = self._counters[key] = _Counter(name, key[1], self)
            return c

    def gauge(self, name: str, fn, **labels) -> _Gauge:
        key = (name, self._label_key(labels))
        with self._lock:
            g = self._gauges.get(key)
            if g is None:
                g = self._gauges[key] = _Gauge(name, key[1], fn)
            else:
                g.fn = fn             # re-bind: newest provider wins
            return g

    def histogram(self, name: str, **labels) -> Histogram:
        key = (name, self._label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram()
            return h

    def add_source(self, key: str, fn) -> None:
        """Register (idempotently, newest wins) a scrape-time pull:
        `fn()` returns [(kind, name, labels_tuple, value_or_snapshot)].
        Sources run ONLY at collect time — a source for a hot counter
        costs the hot path nothing."""
        with self._lock:
            self._sources[key] = fn

    def remove_source(self, key: str) -> None:
        with self._lock:
            self._sources.pop(key, None)

    # -- collection -------------------------------------------------------
    def collect(self) -> list:
        """Every sample the registry can produce right now:
        [(kind, name, labels_tuple, value)] with histogram values as
        snapshot dicts. Gauges and sources are evaluated HERE (lazy);
        a failing gauge/source is skipped, never raised."""
        with self._lock:
            counters = [(c.name, c.labels, c._value)
                        for c in self._counters.values()]
            gauges = list(self._gauges.values())
            hists = list(self._hists.items())
            sources = list(self._sources.values())
        out = [("counter", n, lbl, v) for n, lbl, v in counters]
        for g in gauges:
            v = g.sample()
            if v is not None:
                out.append(("gauge", g.name, g.labels, v))
        for (name, labels), h in hists:
            out.append(("histogram", name, labels, h.snapshot()))
        for fn in sources:
            try:
                out.extend(fn())
            except Exception:
                continue
        return out

    def render_prometheus(self) -> str:
        return _render(self.collect())

    def reset(self) -> None:
        """Per-test re-init (worker-reinit rule): drop instruments and
        sources; the registered lock slot stays."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            self._sources.clear()


REGISTRY = MetricsRegistry(slot="obs.export.MetricsRegistry._lock")


# ---------------------------------------------------------------------------
# Prometheus text exposition (render + round-trip parse)
# ---------------------------------------------------------------------------

_NAME_PREFIX = "spark_tpu_"


def _prom_name(name: str) -> str:
    return _NAME_PREFIX + re.sub(r"[^a-zA-Z0-9_]", "_", name)


def _escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _prom_labels(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _render(samples: list) -> str:
    """Prometheus text format v0.0.4: one TYPE header per metric name,
    histogram expansion into _bucket{le=...}/_sum/_count."""
    by_name: dict = {}
    for kind, name, labels, value in samples:
        by_name.setdefault((name, kind), []).append((labels, value))
    lines = []
    for (name, kind) in sorted(by_name):
        pname = _prom_name(name)
        lines.append(f"# TYPE {pname} {kind}")
        for labels, value in by_name[(name, kind)]:
            labels = tuple(labels or ())
            if kind == "histogram":
                snap = value
                cum = 0
                for i, c in enumerate(snap["counts"]):
                    cum += int(c)
                    le = ("+Inf" if i >= _NUM_BUCKETS
                          else repr(round(BUCKET_BOUNDS[i], 6)))
                    lines.append(
                        f"{pname}_bucket"
                        f"{_prom_labels(labels + (('le', le),))} {cum}")
                lines.append(f"{pname}_sum{_prom_labels(labels)} "
                             f"{snap['sum']!r}")
                lines.append(f"{pname}_count{_prom_labels(labels)} "
                             f"{int(snap['count'])}")
            else:
                v = int(value) if float(value).is_integer() else value
                lines.append(f"{pname}{_prom_labels(labels)} {v}")
    return "\n".join(lines) + "\n"


_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})?\s+(\S+)$')
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text: str) -> dict:
    """Round-trip reader for the text format: returns
    {"types": {name: kind}, "samples": {(name, labels_tuple): float}}.
    Histogram series come back as their expanded _bucket/_sum/_count
    sample names — exactly what a real scraper stores."""
    types: dict = {}
    samples: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"unparseable exposition line: {line!r}")
        name, _, rawlabels, rawval = m.groups()
        labels = tuple(sorted(
            (k, v.replace('\\"', '"').replace("\\\\", "\\"))
            for k, v in _LABEL_RE.findall(rawlabels or "")))
        samples[(name, labels)] = float(rawval)
    return {"types": types, "samples": samples}


# ---------------------------------------------------------------------------
# configuration + module-level export surface
# ---------------------------------------------------------------------------

_TICK_INTERVAL_S = 5.0
_RING_SIZE = 120


def configure(conf) -> None:
    """Apply a session/worker conf to the process-global switches
    (spark.tpu.metrics.export / tickInterval / ringSize). Called by
    TpuSession.__init__ and the worker-side begin_stage_obs — the
    registry itself stays process-global like the KernelCache."""
    global ENABLED, _TICK_INTERVAL_S, _RING_SIZE

    from ..config import (
        METRICS_EXPORT, METRICS_RING_SIZE, METRICS_TICK_INTERVAL,
    )

    # conf values are host data — never touches a device
    ENABLED = bool(conf.get(METRICS_EXPORT))  # tpulint: ignore[host-sync]
    _TICK_INTERVAL_S = max(
        float(conf.get(METRICS_TICK_INTERVAL)), 0.05)
    _RING_SIZE = max(int(conf.get(METRICS_RING_SIZE)), 8)
    if not ENABLED:
        stop_ticker()


def render_prometheus() -> str:
    """The process scrape (history server /metrics, SQL endpoint
    {"metrics": true}, bench end-of-load scrape)."""
    return REGISTRY.render_prometheus()


def register_default_sources(session=None, scheduler=None) -> None:
    """Wire the scrape-time pulls over the counter families that
    already exist (idempotent; newest session/scheduler wins). Pure
    host reads — each pull is a locked snapshot of host counters."""
    REGISTRY.add_source("kernel_cache", _kernel_cache_source)
    REGISTRY.add_source("transport", _transport_source)
    REGISTRY.add_source("ledger", _ledger_source)
    if session is not None:
        name = getattr(session, "name", "") or "session"
        REGISTRY.add_source(
            "session", lambda s=session, n=name: _session_source(s, n))
        live = getattr(session, "live_obs", None)
        if live is not None:
            REGISTRY.add_source(
                "live", lambda lv=live: _live_source(lv))
            REGISTRY.add_source(
                "executors", lambda lv=live: _executor_source(lv))
    if scheduler is not None:
        REGISTRY.add_source(
            "pools", lambda sc=scheduler: sc.metrics_samples())


def _kernel_cache_source() -> list:
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    out = [
        ("counter", "kernel.launches", (), int(KC.launches)),
        ("counter", "kernel.cache_hits", (), int(KC.hits)),
        ("counter", "kernel.compiles", (), int(KC.misses)),
        ("counter", "kernel.compile_ms", (), float(KC.compile_ms)),
        ("counter", "kernel.disk_hit_compiles", (),
         int(KC.disk_hit_compiles)),
    ]
    for kind, n in sorted(dict(KC.launches_by_kind).items()):
        out.append(("counter", "kernel.launches_by_kind",
                    (("kind", kind),), int(n)))
    return out


def _transport_source() -> list:
    from ..net.transport import RETRY_STATS

    snap = RETRY_STATS.snapshot()
    return [("counter", "net.retry." + k, (), int(v))
            for k, v in sorted(snap.items())]


def _ledger_source() -> list:
    from .resources import GLOBAL_LEDGER

    snap = GLOBAL_LEDGER.snapshot()
    return [
        ("gauge", "hbm.bytes", (), float(snap["bytes"])),
        ("gauge", "hbm.peak_bytes", (), float(snap["peak"])),
        ("gauge", "hbm.arrays", (), float(snap["arrays"])),
    ]


def _session_source(session, name: str) -> list:
    """Session Metrics counters (result_cache.*, cache.*, compile.*)
    under a {session} label."""
    try:
        counters = session._metrics.snapshot()["counters"]
    except Exception:
        return []
    keep = ("result_cache.", "compile.", "cache.", "obs.")
    return [("counter", "session." + k, (("session", name),), int(v))
            for k, v in sorted(counters.items())
            if k.startswith(keep)]


def _live_source(live) -> list:
    """Straggler / regression / SLO finding counts from the live store
    plus its own health counters."""
    try:
        by_kind: dict = {}
        with live._lock:
            for q in live._queries.values():
                for f in q["findings"]:
                    k = f.get("kind", "?")
                    by_kind[k] = by_kind.get(k, 0) + 1
            late = live.late_dropped
            errs = live.telemetry_errors
            ev = getattr(live, "evictions", 0)
    except Exception:
        return []
    out = [("counter", "obs.findings", (("kind", k),), int(n))
           for k, n in sorted(by_kind.items())]
    out.append(("counter", "obs.heartbeat.late_dropped", (), int(late)))
    out.append(("counter", "obs.telemetry_errors", (), int(errs)))
    # finished-query ring evictions: a non-zero series is the signal
    # that serving load outran the 64-query live window and telemetry
    # (findings, progress) for the evicted queries is gone
    out.append(("counter", "obs.live.evictions", (), int(ev)))
    return out


def _executor_source(live) -> list:
    """Worker-labeled series from the per-executor registry payloads
    that rode the heartbeat (LiveObs.executors[eid]["metrics"]) — the
    driver scrape's merge of N worker processes."""
    out = []
    with live._lock:
        rows = [(eid, dict(e.get("metrics") or {}),
                 e.get("hbm_bytes"), e.get("hbm_peak"))
                for eid, e in sorted(live.executors.items())]
    for eid, metrics, hbm_bytes, hbm_peak in rows:
        lbl = (("executor", eid),)
        for name, v in sorted(metrics.items()):
            out.append(("counter", "executor." + name, lbl, v))
        if hbm_bytes is not None:
            out.append(("gauge", "executor.hbm.bytes", lbl,
                        float(hbm_bytes)))
        if hbm_peak is not None:
            out.append(("gauge", "executor.hbm.peak_bytes", lbl,
                        float(hbm_peak)))
    return out


def executor_payload() -> dict:
    """Cumulative counter snapshot a WORKER attaches to its heartbeat
    (exec/worker_main.heartbeat_loop). Snapshots, not increments: a
    lost beat loses nothing, the next one carries the totals — the
    at-least-once discipline the rest of the heartbeat already uses."""
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC
    from ..net.transport import RETRY_STATS

    out = {
        "kernel.launches": int(KC.launches),
        "kernel.compiles": int(KC.misses),
        "kernel.compile_ms": round(float(KC.compile_ms), 3),
        "kernel.disk_hit_compiles": int(KC.disk_hit_compiles),
    }
    for k, v in RETRY_STATS.snapshot().items():
        out["net.retry." + k] = int(v)
    try:
        from ..exec.worker_main import FLUSH_OVERFLOWS
        out["obs.flush_overflows"] = int(FLUSH_OVERFLOWS.value)
    except Exception:
        pass
    return out


# ---------------------------------------------------------------------------
# time-series ring + ticker thread
# ---------------------------------------------------------------------------

_TS_LOCK = threading.Lock()
lockwatch.register("obs.export._TS_LOCK", sys.modules[__name__],
                   "_TS_LOCK")
_TS_RING: deque = deque(maxlen=_RING_SIZE)
_TICKER = None


class _Ticker:
    """Interval sampler of the gauge/counter surface into the bounded
    ring. One daemon thread per process, started only when export is on
    (start_ticker) and joined on stop_ticker — the drain path."""

    def __init__(self, interval_s: float):
        self.interval_s = interval_s
        self._stop = threading.Event()
        # race-lint: ignore[bare-submit] — process-lifetime service
        # thread: samples host counters on a wall-clock interval and
        # must NOT pin any query's contextvar scope (a scoped thread
        # would charge its reads to whatever query started the ticker)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="metrics-ticker")
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            tick_once()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2.0)


def tick_once(now: float | None = None) -> None:
    """Sample the current scalar surface into the ring (the ticker's
    body; callable directly by tests and the drain snapshot)."""
    point: dict = {}
    for kind, name, labels, value in REGISTRY.collect():
        if kind == "histogram":
            # scalar view of a distribution: its count (rate via ring
            # deltas) — full buckets stay on the scrape surface
            point[_series_key(name + ".count", labels)] = \
                int(value["count"])
        else:
            point[_series_key(name, labels)] = value
    with _TS_LOCK:
        _TS_RING.append((time.time() if now is None else now, point))


def _series_key(name: str, labels) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in labels)
    return f"{name}{{{inner}}}"


def start_ticker() -> None:
    """Start (or resize) the interval sampler. No-op when export is
    off — the off path never creates the thread."""
    global _TICKER, _TS_RING
    if not ENABLED:
        return
    with _TS_LOCK:
        if _TS_RING.maxlen != _RING_SIZE:
            _TS_RING = deque(_TS_RING, maxlen=_RING_SIZE)
    if _TICKER is None or not _TICKER._thread.is_alive():
        _TICKER = _Ticker(_TICK_INTERVAL_S)


def stop_ticker() -> None:
    global _TICKER
    t, _TICKER = _TICKER, None
    if t is not None:
        t.stop()


def timeseries_snapshot(series_prefix: str | None = None,
                        limit: int | None = None) -> dict:
    """The ring as {"interval_s", "series": {key: [[t, v], ...]}} —
    the drain-time snapshot and the sparkline feed for serve status."""
    with _TS_LOCK:
        points = list(_TS_RING)
    if limit:
        points = points[-int(limit):]
    series: dict = {}
    for t, point in points:
        for key, v in point.items():
            if series_prefix and not key.startswith(series_prefix):
                continue
            series.setdefault(key, []).append([round(t, 3), v])
    return {"interval_s": _TICK_INTERVAL_S, "series": series}


def sparklines(series_prefix: str = "serve.",
               limit: int = 32) -> dict:
    """Just the recent values per series (no timestamps) — the compact
    sparkline payload serve status embeds."""
    snap = timeseries_snapshot(series_prefix=series_prefix, limit=limit)
    return {k: [v for _t, v in pts]
            for k, pts in snap["series"].items()}
