"""Span-based query tracing with a Perfetto/Chrome-trace JSON exporter.

Role of the reference's SQL-tab timeline + task-event timeline (the
AppStatusListener-fed execution timeline the UI renders): every phase of
the query lifecycle (parse → analyze → optimize → plan → per-stage
per-partition execute → shuffle/exchange → collect) records a completed
span. Spans are plain host bookkeeping — two perf_counter reads and one
list append each — so tracing stays ON by default; async partition
pipelining is visible because `ExecContext.par_map` lanes record their
spans from their own threads (distinct `tid` tracks in the trace).

Export is the Chrome trace-event format ("traceEvents" complete events,
microsecond timestamps), loadable in Perfetto (ui.perfetto.dev) or
chrome://tracing.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

__all__ = ["Tracer", "to_chrome_trace"]


class _NullSpan:
    """Disabled-tracer span: context-manager no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_args(self, args) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "cat", "args", "t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = 0.0

    def set_args(self, args) -> None:
        """Attach/merge args before exit (per-span kernel attribution)."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        t = threading.current_thread()
        self.tracer._record(self.name, self.cat, self.t0, dur,
                            t.ident, t.name, self.args)
        return False


class Tracer:
    """Thread-safe accumulator of completed spans.

    `conf`-backed tracers re-read spark.tpu.trace.enabled per span() so a
    session can flip tracing without rebuilding the tracer (maxSpans is
    refreshed on the same read — span close never touches conf, so the
    hot _record path takes no lock but the tracer's own). The buffer is a
    RING of the latest maxSpans spans: a long-lived session (connect
    server, streaming, shell) keeps tracing its most recent queries
    instead of going permanently dark once a cap fills; evicted-oldest
    spans count in `dropped`, and mark()/since() use monotonic sequence
    numbers so slices stay correct across eviction.

    Per-QUERY span slices (mark()/since()) assume queries on one session
    run sequentially; concurrent collects on a shared session interleave
    in the buffer and cross-attribute event spans (ROADMAP: tag spans
    with a query-scope contextvar).
    """

    def __init__(self, conf=None, enabled: bool = True,
                 max_spans: int = 100_000):
        import collections

        self._conf = conf
        self._enabled = enabled
        self._max_spans = max_spans
        # ring of (name, cat, t0, dur, tid, tname, args)
        self._spans: "collections.deque" = collections.deque()
        self._seq = 0              # total spans ever recorded
        self._lock = threading.Lock()
        self.dropped = 0

    @property
    def enabled(self) -> bool:
        if self._conf is not None:
            from ..config import TRACE_ENABLED, TRACE_MAX_SPANS

            on = bool(self._conf.get(TRACE_ENABLED))
            if on:  # piggyback the cap refresh on the same conf visit
                self._max_spans = int(self._conf.get(TRACE_MAX_SPANS))
            return on
        return self._enabled

    @property
    def max_spans(self) -> int:
        return self._max_spans

    def span(self, name: str, cat: str = "exec",
             args: Optional[dict] = None):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    def _record(self, name, cat, t0, dur, tid, tname, args) -> None:
        with self._lock:
            self._spans.append((name, cat, t0, dur, tid, tname, args))
            self._seq += 1
            while len(self._spans) > self._max_spans:
                self._spans.popleft()  # ring: evict oldest, keep tracing
                self.dropped += 1

    # -- reading ----------------------------------------------------------
    def mark(self) -> int:
        """Monotonic sequence number — pass to since() to slice one
        query's spans out of a session-lived tracer (valid across ring
        eviction)."""
        with self._lock:
            return self._seq

    def since(self, mark: int) -> list[dict]:
        """Spans recorded after mark(), as JSON-friendly dicts (spans the
        ring already evicted are gone — only the tail can be lost)."""
        with self._lock:
            first = self._seq - len(self._spans)  # seq of oldest buffered
            spans = list(self._spans)[max(0, mark - first):]
        return [{"name": n, "cat": c, "ts": round(t0, 6),
                 "dur_ms": round(dur * 1000, 3), "thread": tname,
                 **({"args": args} if args else {})}
                for n, c, t0, dur, _tid, tname, args in spans]

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # -- export -----------------------------------------------------------
    def to_chrome_trace(self, process_name: str = "spark_tpu") -> dict:
        return to_chrome_trace(self.spans(), process_name=process_name)

    def write_chrome_trace(self, path: str,
                           process_name: str = "spark_tpu") -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(process_name), f)
        return path


def to_chrome_trace(spans: list, process_name: str = "spark_tpu",
                    pid: int = 1) -> dict:
    """Raw tracer spans → Chrome trace-event JSON dict.

    Complete ("ph": "X") events with microsecond timestamps relative to
    the earliest span; one tid track per recording thread, labeled with
    the thread name via metadata events (par_map lanes show as their own
    pipelined tracks).
    """
    events = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
               "args": {"name": process_name}}]
    if not spans:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    tmin = min(s[2] for s in spans)
    # key tracks by (ident, name): lane threads are ephemeral and Python
    # reuses idents, so ident alone would merge distinct threads into one
    # mislabeled track
    tid_map: dict = {}
    for name, cat, t0, dur, ident, tname, args in spans:
        tid = tid_map.get((ident, tname))
        if tid is None:
            tid = tid_map[(ident, tname)] = len(tid_map) + 1
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name", "args": {"name": tname}})
        ev = {"ph": "X", "pid": pid, "tid": tid, "name": name, "cat": cat,
              "ts": round((t0 - tmin) * 1e6, 3),
              "dur": round(dur * 1e6, 3)}
        if args:
            ev["args"] = args
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
