"""Span-based query tracing with a Perfetto/Chrome-trace JSON exporter.

Role of the reference's SQL-tab timeline + task-event timeline (the
AppStatusListener-fed execution timeline the UI renders): every phase of
the query lifecycle (parse → analyze → optimize → plan → per-stage
per-partition execute → shuffle/exchange → collect) records a completed
span. Spans are plain host bookkeeping — two perf_counter reads and one
list append each — so tracing stays ON by default; async partition
pipelining is visible because `ExecContext.par_map` lanes record their
spans from their own threads (distinct `tid` tracks in the trace).

Three cross-cutting mechanisms ride every span:

  * query scope — a contextvar tag (`push_query`/`pop_query`) stamps
    each span with the query it belongs to at record time. Because
    contextvars follow the work into `par_map` lanes (copied Context per
    lane) and into cluster tasks (the tag ships with the task), two
    concurrent collects on one shared session get DISJOINT span sets —
    the buffer-offset mark()/since() slicing that assumed sequential
    queries is kept only as a compatibility surface.

  * flow graph — spans opened with `flow=True` allocate a process-unique
    flow id and parent themselves to the enclosing flow span via a
    second contextvar, which crosses thread (copied Context) and process
    (shipped span args) boundaries. The exporter turns every resolved
    parent→child pair into Perfetto flow arrows ("s"/"f" events), so the
    rendered timeline draws query → stage → partition-lane/worker arrows
    plus shuffle map-task → reduce-fetch edges.

  * cross-process ingest — `Tracer.ingest` merges spans recorded by a
    worker-process tracer into this one, rebasing perf_counter
    timestamps through paired (wall, perf) anchors and prefixing thread
    tracks with the worker's identity so worker spans render as their
    own named tracks.

Export is the Chrome trace-event format ("traceEvents" complete events,
microsecond timestamps), loadable in Perfetto (ui.perfetto.dev) or
chrome://tracing.
"""

from __future__ import annotations

import contextvars
import json
import threading
import time
import uuid
from typing import Optional

__all__ = ["Tracer", "current_flow", "current_query", "pop_query",
           "push_query", "to_chrome_trace"]


# ---------------------------------------------------------------------------
# Query scope: which query's collect is executing on this thread/lane
# ---------------------------------------------------------------------------

# contextvars (not thread-locals) so scheduler.par_map's copied lane
# contexts and the cluster task payload both carry the tag — spans from
# concurrent queries on one session stay disjoint (ROADMAP follow-on)
_QUERY: "contextvars.ContextVar" = contextvars.ContextVar(
    "spark_tpu_query_scope", default=None)

# the innermost flow-enabled span: children opened under it (same thread,
# copied lane context, or shipped worker task) parent their flow arrow here
_FLOW: "contextvars.ContextVar" = contextvars.ContextVar(
    "spark_tpu_flow_scope", default=None)


def push_query(query_id: str):
    """Enter a query scope; returns the reset token for pop_query."""
    return _QUERY.set(query_id)


def pop_query(token) -> None:
    _QUERY.reset(token)


def current_query() -> str | None:
    return _QUERY.get()


def current_flow() -> str | None:
    """Flow id of the innermost flow span (for handing across an
    explicit boundary, e.g. into a cluster task payload)."""
    return _FLOW.get()


class _NullSpan:
    """Disabled-tracer span: context-manager no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_args(self, args) -> None:
        pass


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("tracer", "name", "cat", "args", "t0", "flow", "_ftoken",
                 "_qid")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args,
                 flow: bool = False):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self.t0 = 0.0
        self.flow = flow
        self._ftoken = None
        self._qid = None

    def set_args(self, args) -> None:
        """Attach/merge args before exit (per-span kernel attribution)."""
        if self.args is None:
            self.args = args
        else:
            self.args.update(args)

    def __enter__(self):
        if self.flow:
            # explicit flow_id (deterministic cross-process ids, e.g. a
            # shuffle's map-task span) wins over a fresh allocation
            fid = (self.args or {}).get("flow_id") \
                or self.tracer._next_flow_id()
            parent = (self.args or {}).get("flow_parent") or _FLOW.get()
            args = {"flow_id": fid}
            if parent is not None:
                args["flow_parent"] = parent
            self.set_args(args)
            self._ftoken = _FLOW.set(fid)
        self.t0 = time.perf_counter()
        self._qid = _QUERY.get()
        # live telemetry reads in-flight spans: register open, drop on
        # close (two dict ops per span — still pure host bookkeeping)
        self.tracer._open_add(self)
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self.t0
        if self._ftoken is not None:
            _FLOW.reset(self._ftoken)
        self.tracer._open_remove(self)
        t = threading.current_thread()
        self.tracer._record(self.name, self.cat, self.t0, dur,
                            t.ident, t.name, self.args, _QUERY.get())
        return False


class Tracer:
    """Thread-safe accumulator of completed spans.

    `conf`-backed tracers re-read spark.tpu.trace.enabled per span() so a
    session can flip tracing without rebuilding the tracer (maxSpans is
    refreshed on the same read — span close never touches conf, so the
    hot _record path takes no lock but the tracer's own). The buffer is a
    RING of the latest maxSpans spans: a long-lived session (connect
    server, streaming, shell) keeps tracing its most recent queries
    instead of going permanently dark once a cap fills; evicted-oldest
    spans count in `dropped`, and mark()/since() use monotonic sequence
    numbers so slices stay correct across eviction.

    Per-QUERY spans come from the query-scope contextvar tag
    (`spans_for`); mark()/since() buffer slicing is kept for sequential
    callers but concurrent collects should read their own query tag.
    """

    def __init__(self, conf=None, enabled: bool = True,
                 max_spans: int = 100_000):
        import collections

        self._conf = conf
        self._enabled = enabled
        self._max_spans = max_spans
        # ring of (name, cat, t0, dur, tid, tname, args, query_id)
        self._spans: "collections.deque" = collections.deque()
        self._seq = 0              # total spans ever recorded
        self._lock = threading.Lock()
        self.dropped = 0
        # flow ids must stay unique across processes (worker spans are
        # ingested into the driver tracer verbatim)
        self._uid = uuid.uuid4().hex[:8]
        self._flow_n = 0
        # paired clocks for cross-process timestamp rebasing: a worker's
        # perf_counter domain maps into ours through the wall clock
        self.anchor = (time.time(), time.perf_counter())
        # spans currently inside __enter__/__exit__ (live telemetry view)
        self._open: dict[int, "_Span"] = {}

    @property
    def enabled(self) -> bool:
        if self._conf is not None:
            from ..config import TRACE_ENABLED, TRACE_MAX_SPANS

            on = bool(self._conf.get(TRACE_ENABLED))
            if on:  # piggyback the cap refresh on the same conf visit
                self._max_spans = int(self._conf.get(TRACE_MAX_SPANS))
            return on
        return self._enabled

    @property
    def max_spans(self) -> int:
        return self._max_spans

    def span(self, name: str, cat: str = "exec",
             args: Optional[dict] = None, flow: bool = False):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args, flow=flow)

    def _next_flow_id(self) -> str:
        with self._lock:
            self._flow_n += 1
            return f"{self._uid}:{self._flow_n}"

    def _open_add(self, span: "_Span") -> None:
        with self._lock:
            self._open[id(span)] = span

    def _open_remove(self, span: "_Span") -> None:
        with self._lock:
            self._open.pop(id(span), None)

    def open_spans(self) -> list[dict]:
        """Snapshot of spans currently in flight, as JSON-friendly dicts
        with elapsed-so-far (the live-telemetry 'what is this task doing
        RIGHT NOW' view). Pure host bookkeeping."""
        with self._lock:
            spans = list(self._open.values())
        now = time.perf_counter()
        out = []
        for s in spans:
            out.append({"name": s.name, "cat": s.cat,
                        "elapsed_ms": round((now - s.t0) * 1000, 3),
                        **({"query": s._qid} if s._qid is not None
                           else {})})
        return out

    def _record(self, name, cat, t0, dur, tid, tname, args,
                qid=None) -> None:
        with self._lock:
            self._spans.append((name, cat, t0, dur, tid, tname, args, qid))
            self._seq += 1
            while len(self._spans) > self._max_spans:
                self._spans.popleft()  # ring: evict oldest, keep tracing
                self.dropped += 1

    def ingest(self, spans: list, anchor: tuple | None = None,
               track: str | None = None, query_id: str | None = None) -> int:
        """Merge spans recorded by ANOTHER process's tracer (a cluster
        worker) into this buffer: timestamps rebase through the paired
        (wall, perf) anchors, thread tracks get `track/` prefixed so
        worker spans render as their own named tracks, and every span is
        re-tagged to `query_id` (the driver's query scope — the worker's
        own tag is task-local). Pure host bookkeeping."""
        if not spans:
            return 0
        off = 0.0
        if anchor is not None:
            # worker wall time of a span = w_wall + (t0 - w_perf); map it
            # into our perf domain: t0' = t0 + (w_wall - w_perf) -
            # (our_wall - our_perf)
            off = (anchor[0] - anchor[1]) - (self.anchor[0] - self.anchor[1])
        n = 0
        with self._lock:
            for s in spans:
                name, cat, t0, dur, ident, tname, args = s[:7]
                qid = s[7] if len(s) > 7 else None
                self._spans.append((
                    name, cat, t0 + off, dur, ident,
                    f"{track}/{tname}" if track else tname, args,
                    query_id if query_id is not None else qid))
                self._seq += 1
                while len(self._spans) > self._max_spans:
                    self._spans.popleft()
                    self.dropped += 1
                n += 1
        return n

    # -- reading ----------------------------------------------------------
    def mark(self) -> int:
        """Monotonic sequence number — pass to since() to slice one
        query's spans out of a session-lived tracer (valid across ring
        eviction). Assumes sequential queries; concurrent collects should
        use spans_for(query_id)."""
        with self._lock:
            return self._seq

    @staticmethod
    def _span_dict(s) -> dict:
        name, cat, t0, dur, _tid, tname, args = s[:7]
        qid = s[7] if len(s) > 7 else None
        return {"name": name, "cat": cat, "ts": round(t0, 6),
                "dur_ms": round(dur * 1000, 3), "thread": tname,
                **({"args": args} if args else {}),
                **({"query": qid} if qid is not None else {})}

    def since(self, mark: int) -> list[dict]:
        """Spans recorded after mark(), as JSON-friendly dicts (spans the
        ring already evicted are gone — only the tail can be lost)."""
        with self._lock:
            first = self._seq - len(self._spans)  # seq of oldest buffered
            spans = list(self._spans)[max(0, mark - first):]
        return [self._span_dict(s) for s in spans]

    def spans_for(self, query_id: str) -> list[dict]:
        """All buffered spans tagged with one query scope, as
        JSON-friendly dicts — the concurrency-safe per-query slice.
        The lock covers only the ring snapshot (same profile as
        since()); the tag filter runs outside it so a full 100k-span
        ring never stalls concurrent span recording."""
        with self._lock:
            spans = list(self._spans)
        return [self._span_dict(s) for s in spans
                if len(s) > 7 and s[7] == query_id]

    def spans(self) -> list:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self.dropped = 0

    # -- export -----------------------------------------------------------
    def to_chrome_trace(self, process_name: str = "spark_tpu") -> dict:
        return to_chrome_trace(self.spans(), process_name=process_name)

    def write_chrome_trace(self, path: str,
                           process_name: str = "spark_tpu") -> str:
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(process_name), f)
        return path


def _flow_events(complete: list) -> list:
    """Perfetto flow arrows from span args: every span carrying a
    `flow_parent` that resolves to another span's `flow_id` emits one
    "s" (start, anchored inside the parent slice) + "f" (finish, binding
    to the enclosing child slice) pair with a fresh numeric id. Parents
    that did not make it into the trace (disabled worker tracer, ring
    eviction) emit nothing — the exporter never leaves a dangling arrow,
    which is exactly what dev/validate_trace.py checks."""
    by_fid = {}
    for ev in complete:
        fid = (ev.get("args") or {}).get("flow_id")
        if fid is not None:
            by_fid[fid] = ev
    out = []
    edge = 0
    for ev in complete:
        parents = (ev.get("args") or {}).get("flow_parent")
        if parents is None:
            continue
        if not isinstance(parents, (list, tuple)):
            parents = [parents]
        for parent in parents:
            src = by_fid.get(parent)
            if src is None or src is ev:
                continue
            edge += 1
            out.append({"ph": "s", "id": edge, "pid": src["pid"],
                        "tid": src["tid"], "ts": src["ts"],
                        "name": "flow", "cat": "flow"})
            out.append({"ph": "f", "bp": "e", "id": edge, "pid": ev["pid"],
                        "tid": ev["tid"], "ts": ev["ts"],
                        "name": "flow", "cat": "flow"})
    return out


def to_chrome_trace(spans: list, process_name: str = "spark_tpu",
                    pid: int = 1) -> dict:
    """Raw tracer spans → Chrome trace-event JSON dict.

    Complete ("ph": "X") events with microsecond timestamps relative to
    the earliest span; one tid track per recording thread, labeled with
    the thread name via metadata events (par_map lanes show as their own
    pipelined tracks; ingested worker spans as `worker:<id>/...`
    tracks). Spans carrying flow_id/flow_parent args additionally emit
    Perfetto flow arrows ("s"/"f" events) linking query → stage →
    lane/worker spans and shuffle map → reduce-fetch edges across
    threads and processes."""
    events = [{"ph": "M", "pid": pid, "tid": 0, "name": "process_name",
               "args": {"name": process_name}}]
    if not spans:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    tmin = min(s[2] for s in spans)
    # key tracks by (ident, name): lane threads are ephemeral and Python
    # reuses idents, so ident alone would merge distinct threads into one
    # mislabeled track
    tid_map: dict = {}
    complete = []
    for s in spans:
        name, cat, t0, dur, ident, tname, args = s[:7]
        qid = s[7] if len(s) > 7 else None
        tid = tid_map.get((ident, tname))
        if tid is None:
            tid = tid_map[(ident, tname)] = len(tid_map) + 1
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name", "args": {"name": tname}})
        ev = {"ph": "X", "pid": pid, "tid": tid, "name": name, "cat": cat,
              "ts": round((t0 - tmin) * 1e6, 3),
              "dur": round(dur * 1e6, 3)}
        if args or qid is not None:
            ev["args"] = dict(args or {})
            if qid is not None:
                ev["args"]["query"] = qid
        events.append(ev)
        complete.append(ev)
    events.extend(_flow_events(complete))
    return {"traceEvents": events, "displayTimeUnit": "ms"}
