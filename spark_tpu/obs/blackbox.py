"""Query black box: anomaly-triggered diagnostic bundles.

Role of the reference's event log + history server postmortem story
(core/scheduler/EventLoggingListener.scala replaying an application's
lifecycle into the SHS), inverted for a serving engine: instead of
logging EVERYTHING always (the ship-always event log whose volume is
the first thing a fleet operator turns off), the engine keeps the
healthy path at structural zero cost and captures a complete,
self-contained diagnostic bundle only WHEN SOMETHING BREAKS — the
tail-sampled capture-on-anomaly discipline fleet-scale serving needs
(ROADMAP direction 2), and the only debuggability story compatible
with whole-query compilation, where a single opaque fused dispatch is
inexplicable without its surrounding evidence.

**Triggers.** Any severity-warning/error finding in the trigger set —
``obs.slo`` breach (PR 18), ``obs.regression`` (PR 12),
``obs.straggler`` (PR 6), ``tier.degraded`` (PR 10),
``exec.excluded`` (PR 11), admission rejection (``serve.rejected``),
query failure incl. chaos retry exhaustion (``query.failed``) — or an
explicit ``session.capture_diagnostics()``. Findings raised DURING a
query are swept at query close (QueryExecution.execute's close hook);
findings raised AFTER close (the SLO verdict lands on ticket release)
reach the LiveObs finding sink, which captures against the recently
closed QueryExecution. A deterministic 1-in-N
``spark.tpu.obs.bundle.sampleHealthy`` (default off) tail-samples
trigger-free queries as comparison baselines.

**Bundle contents** (one directory per bundle under
``spark.tpu.obs.bundleDir``, flock-safe bounded retention ring):

  * ``bundle.json`` — the manifest: triggering finding + full finding
    chain, non-default config, the PR 12 QueryProfile WITH its same-key
    baseline history (embedded — the bundle must render with no access
    to the profile store), DeviceLedger/executor state, the live-store
    snapshot, the metrics time-series ring window, and the pulled
    per-worker diagnostic rings.
  * ``trace.json`` — Chrome trace of the query's spans (driver tracks
    plus the ingested ``worker:<eid>/...`` tracks) with the pulled
    worker post-task rings appended as their own processes.
  * ``explain_simple.txt`` / ``explain_analysis.txt`` /
    ``explain_analyze.txt`` — plan reports; the analyze report is
    rendered from the ALREADY-RECORDED operator metrics (never by
    re-executing — capture launches zero kernels).
  * ``metrics.prom`` — the Prometheus scrape at capture time.

**Pull-on-anomaly.** Cross-process state is PULLED at bundle time via
the workers' ``diagnostic_state`` RPC (bounded post-task
span/counter/fault-registry/lockwatch rings kept in
exec/worker_main.py), never shipped on the healthy path — heartbeat
payloads are byte-identical with bundles armed.

Obs contract (PRs 3-18): everything here is host bookkeeping — zero
kernel launches, no mid-query device syncs. Off
(``spark.tpu.obs.bundles`` false) is structurally zero overhead: call
sites gate on the module bool ``ENABLED`` (one attribute read, the
utils/faults.py discipline). Armed-but-untriggered adds one
finding-chain scan per query close and zero launches — the
``dev/validate_trace.py --bundles`` gate proves the launch-count
identity.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
from collections import OrderedDict

from ..utils import lockwatch

__all__ = ["ENABLED", "TRIGGER_KINDS", "capture", "capture_failure",
           "configure", "is_trigger", "list_bundles", "load_bundle",
           "maybe_capture", "most_recent", "on_finding", "pack_bundle",
           "record_rejection", "reset"]

# fast-path flag (utils/faults.py discipline): instrumented call sites
# read ONE module attribute before doing anything — bundles off means
# no registry, no finding scans, no capture, structurally
ENABLED = False

# finding kinds that trigger a capture (at warning/error severity —
# advisory info findings, e.g. wall-clock drift, never bundle)
TRIGGER_KINDS = frozenset({
    "obs.slo", "obs.regression", "obs.straggler", "tier.degraded",
    "exec.excluded", "serve.rejected", "query.failed",
})
_TRIGGER_SEVERITIES = ("warning", "error")

_DIR = ""
_RING = 16
_SAMPLE_N = 0

_MAX_RECENT = 16        # recently closed QueryExecutions (SLO joins)
_MAX_CAPTURED = 256     # capture-once dedup window
_MAX_HISTORY = 8        # same-key baseline profiles embedded per bundle
_MAX_WORKER_TRACE = 4   # pulled worker rings appended to trace.json
_REJECT_MIN_GAP_S = 30.0  # rejection-bundle rate limit (overload guard)

_LOCK = threading.Lock()
lockwatch.register("obs.blackbox._LOCK", sys.modules[__name__], "_LOCK")

_RECENT: "OrderedDict" = OrderedDict()   # qid -> (qe, ctx)
_PENDING: set = set()    # qids whose trigger arrived before close
_CAPTURED: "OrderedDict" = OrderedDict()  # qid -> bundle id (dedup)
_HEALTHY_SEEN = 0
_SEQ = 0
_LAST_REJECT_T = 0.0


def configure(conf) -> None:
    """Apply a session/worker conf to the process-global switches.
    Called by TpuSession.__init__ and the worker-side begin_stage_obs
    (workers arm only their bounded post-task rings — bundle assembly
    is driver-only)."""
    global ENABLED, _DIR, _RING, _SAMPLE_N

    from ..config import (
        OBS_BUNDLE_DIR, OBS_BUNDLE_RING, OBS_BUNDLE_SAMPLE_HEALTHY,
        OBS_BUNDLES,
    )

    # conf values are host data — never touches a device
    _DIR = str(conf.get(OBS_BUNDLE_DIR) or "")
    _RING = max(int(conf.get(OBS_BUNDLE_RING)), 1)
    _SAMPLE_N = max(int(conf.get(OBS_BUNDLE_SAMPLE_HEALTHY)), 0)
    ENABLED = bool(conf.get(OBS_BUNDLES)) and bool(_DIR)


def reset() -> None:
    """Per-test re-init: drop the in-memory registries (the on-disk
    ring is the test's own tmpdir to manage)."""
    global ENABLED, _HEALTHY_SEEN, _LAST_REJECT_T, _SEQ
    with _LOCK:
        _RECENT.clear()
        _PENDING.clear()
        _CAPTURED.clear()
        _HEALTHY_SEEN = 0
        _LAST_REJECT_T = 0.0
        _SEQ = 0
    ENABLED = False


def is_trigger(finding: dict) -> bool:
    return (finding.get("kind") in TRIGGER_KINDS
            and finding.get("severity") in _TRIGGER_SEVERITIES)


def most_recent() -> tuple | None:
    """The most recently closed (qe, ctx), for explicit
    session.capture_diagnostics() with no DataFrame (None when the
    layer is unarmed or nothing closed yet)."""
    with _LOCK:
        if _RECENT:
            return next(reversed(_RECENT.values()))
    return None


# ---------------------------------------------------------------------------
# trigger evaluation
# ---------------------------------------------------------------------------

def maybe_capture(qe, ctx) -> str | None:
    """Query-close sweep (QueryExecution.execute, after the flight
    recorder closed): register the execution for post-close triggers,
    scan the finding chain, capture on any trigger, else apply the
    deterministic healthy sample. Returns the bundle id or None.
    Armed-but-untriggered cost: one findings read + dict upkeep — zero
    kernel launches."""
    global _HEALTHY_SEEN
    if not ENABLED:
        return None
    qid = getattr(ctx, "query_id", None)
    with _LOCK:
        if qid is not None:
            _RECENT[qid] = (qe, ctx)
            while len(_RECENT) > _MAX_RECENT:
                _RECENT.popitem(last=False)
        pending = qid in _PENDING
        _PENDING.discard(qid)
        if qid in _CAPTURED:
            return _CAPTURED[qid]
    live = getattr(qe.session, "live_obs", None)
    findings = live.findings_for(qid) if (live is not None and qid) else []
    trigger = next((f for f in findings if is_trigger(f)), None)
    if trigger is not None or pending:
        return capture(qe.session, qe=qe, ctx=ctx, reason="anomaly",
                       trigger=trigger)
    if _SAMPLE_N > 0:
        with _LOCK:
            _HEALTHY_SEEN += 1
            hit = (_HEALTHY_SEEN % _SAMPLE_N) == 0
        if hit:
            return capture(qe.session, qe=qe, ctx=ctx, reason="sampled")
    return None


def on_finding(session, qid: str | None, finding: dict) -> str | None:
    """LiveObs finding sink: a trigger finding landing AFTER the query
    closed (the obs.slo verdict is raised on ticket release) captures
    against the recently closed QueryExecution; one landing mid-query
    marks the qid pending for the close sweep."""
    if not ENABLED or qid is None or not is_trigger(finding):
        return None
    with _LOCK:
        if qid in _CAPTURED:
            return None
        ent = _RECENT.get(qid)
        if ent is None:
            # query still executing: the close sweep will capture
            _PENDING.add(qid)
            return None
    qe, ctx = ent
    return capture(session, qe=qe, ctx=ctx, reason="anomaly",
                   trigger=finding)


def capture_failure(qe, ctx, error: BaseException) -> str | None:
    """Failed-query capture (chaos retry exhaustion, stage-regeneration
    limit, any fatal execution error): synthesize the query.failed
    finding and bundle the partial evidence before the error
    propagates."""
    if not ENABLED:
        return None
    finding = {
        "severity": "error", "kind": "query.failed",
        "error_class": getattr(error, "error_class", None)
        or type(error).__name__,
        "msg": f"query failed: {type(error).__name__}: "
               f"{str(error)[:300]}"}
    return capture(qe.session, qe=qe, ctx=ctx, reason="failure",
                   trigger=finding, extra_findings=[finding])


def _rejection_analysis(qe) -> dict | None:
    """Predicted-HBM summary of a REJECTED plan from the serving
    pre-flight's AnalysisReport (stashed on the QueryExecution as
    `_preflight_report`): the bundle shows what the admission gate
    believed — predicted peak and the largest stage — without paying a
    second whole-plan analysis at capture time."""
    rep = getattr(qe, "_preflight_report", None) if qe is not None else None
    if rep is None:
        return None
    try:
        stages = list(getattr(rep, "stages", None) or [])
        largest = None
        for s in stages:
            hb = s.get("hbm_bytes")
            if hb and (largest is None
                       or hb > largest.get("hbm_bytes", 0)):
                detail = " ".join(str(s.get("detail") or "").split())
                largest = {"detail": detail[:160], "hbm_bytes": hb}
        return {
            "predicted_peak_hbm": getattr(rep, "predicted_peak_hbm", None),
            "memory_exact": getattr(rep, "memory_exact", None),
            "memory_notes": list(getattr(rep, "memory_notes", None) or []),
            "largest_stage": largest,
        }
    except Exception:
        return None


def record_rejection(session, error: BaseException,
                     pool: str | None = None, qe=None) -> str | None:
    """Admission-rejection capture (PoolQueueFull / AdmissionTimeout /
    memory-budget pre-flight): no query ran, so the bundle carries the
    serving/metrics state that explains the rejection — plus, when the
    rejected QueryExecution is handed over, the pre-flight analysis
    report that drove the verdict. Rate-limited — a saturated pool
    rejecting hundreds of queries must not turn the capture layer into
    its own overload."""
    global _LAST_REJECT_T
    if not ENABLED:
        return None
    now = time.monotonic()
    with _LOCK:
        if now - _LAST_REJECT_T < _REJECT_MIN_GAP_S and _LAST_REJECT_T:
            return None
        _LAST_REJECT_T = now
    analysis = _rejection_analysis(qe)
    finding = {
        "severity": "error", "kind": "serve.rejected",
        "pool": pool,
        "error_class": getattr(error, "error_class", None)
        or type(error).__name__,
        "msg": f"admission rejected: {type(error).__name__}: "
               f"{str(error)[:300]}"}
    if analysis is not None:
        finding["rejection_analysis"] = analysis
        peak = analysis.get("predicted_peak_hbm")
        big = analysis.get("largest_stage") or {}
        if peak:
            finding["msg"] += (
                f" | predicted peak HBM {peak} B"
                + (f", largest stage: {big.get('detail')} "
                   f"({big.get('hbm_bytes')} B)" if big else ""))
    return capture(session, qe=qe, reason="rejection", trigger=finding,
                   extra_findings=[finding],
                   extra_manifest=None if analysis is None
                   else {"rejection_analysis": analysis})


# ---------------------------------------------------------------------------
# bundle assembly (driver-only; every input is host-side metadata)
# ---------------------------------------------------------------------------

def _json_default(o):
    if isinstance(o, (set, frozenset)):
        return sorted(map(str, o))
    if isinstance(o, bytes):
        return o.decode("utf-8", "replace")
    return str(o)


def _query_trace(session, qe, ctx, workers: dict) -> dict | None:
    """Chrome trace of the query's spans: the driver tracer's raw spans
    tagged with this query (worker task spans were ingested there with
    worker:<eid>/ track prefixes during execution), plus the pulled
    worker post-task rings as their own trace processes."""
    from .tracing import to_chrome_trace

    tracer = getattr(session, "tracer", None)
    qid = getattr(ctx, "query_id", None) if ctx is not None else None
    if tracer is None or qid is None:
        return None
    raw = [s for s in tracer.spans() if len(s) > 7 and s[7] == qid]
    trace = to_chrome_trace(raw, process_name="driver", pid=1)
    pid = 2
    for eid in sorted(workers)[:_MAX_WORKER_TRACE]:
        wspans = [tuple(s)
                  for t in (workers[eid] or {}).get("tasks", [])
                  for s in (t.get("spans") or [])]
        if not wspans:
            continue
        sub = to_chrome_trace(wspans, process_name=f"executor {eid}",
                              pid=pid)
        trace["traceEvents"].extend(sub["traceEvents"])
        pid += 1
    return trace


def _analyze_text(qe, ctx, findings: list) -> str:
    """EXPLAIN ANALYZE rendered from the ALREADY-RECORDED run: the
    measured per-operator metrics (plan_graph) and the finding chain.
    Never calls analyzed_report() — that re-executes the query, and
    capture must launch zero kernels."""
    lines = ["== Physical Plan ==", qe.physical.tree_string(), "",
             "== Measured Operator Metrics (recorded run) =="]
    try:
        for n in qe.plan_graph():
            pad = "  " * int(n.get("depth") or 0)
            bits = [f"rows={n.get('rows')}", f"ms={n.get('ms')}"]
            if n.get("launches") is not None:
                bits.append(f"launches={n.get('launches')}")
            lines.append(f"{pad}{n.get('op')}  "
                         f"[{', '.join(bits)}]  {n.get('detail') or ''}")
    except Exception as e:
        lines.append(f"(operator metrics unavailable: {e})")
    lines.append("")
    lines.append("== Findings ==")
    if findings:
        for f in findings:
            lines.append(f"[{f.get('severity')}] {f.get('kind')}: "
                         f"{f.get('msg')}")
    else:
        lines.append("(none)")
    return "\n".join(lines) + "\n"


def _profile_section(qe, session) -> tuple:
    """The close-time QueryProfile plus its same-key baseline history,
    EMBEDDED so diagnose.py renders counter drift with no access to
    the live profile store."""
    profile = getattr(qe, "_last_profile", None) if qe is not None else None
    history: list = []
    if profile is not None:
        from ..config import OBS_PROFILE_DIR, OBS_PROFILE_RING
        from .history import ProfileStore

        root = str(session.conf.get(OBS_PROFILE_DIR) or "")
        if root and os.path.isdir(root):
            try:
                store = ProfileStore(
                    root, ring=int(session.conf.get(OBS_PROFILE_RING)))
                history = store.profiles(profile["query_key"])
                # the fresh profile is the store's newest line — history
                # for drift rendering is everything before it
                history = [p for p in history
                           if p.get("ts") != profile.get("ts")
                           or p.get("query_id") != profile.get("query_id")
                           ][-_MAX_HISTORY:]
            except Exception:
                history = []
    return profile, history


def _pull_workers(session) -> dict:
    """Pull-on-anomaly: the diagnostic_state RPC fan-out, called ONLY
    here (bundle time). Unreachable workers are skipped — a postmortem
    of a sick fleet must capture the healthy remainder."""
    cluster = getattr(session, "_sql_cluster", None)
    pull = getattr(cluster, "diagnostic_state", None)
    if pull is None:
        return {}
    try:
        return pull() or {}
    except Exception:
        return {}


def capture(session, qe=None, ctx=None, reason: str = "manual",
            trigger: dict | None = None,
            extra_findings: list | None = None,
            bundle_dir: str | None = None,
            extra_manifest: dict | None = None) -> str | None:
    """Assemble one self-contained diagnostic bundle. Pure host work at
    capture time: plan/trace/metrics/profile state already recorded,
    worker rings pulled over RPC, everything serialized under the
    flock-safe retention ring. Returns the bundle id (None when no
    bundle dir is configured)."""
    global _SEQ
    from ..utils.diskstore import JsonlRing
    from . import export as _export
    from .resources import GLOBAL_LEDGER

    conf = session.conf
    if bundle_dir is None:
        from ..config import OBS_BUNDLE_DIR

        bundle_dir = _DIR or str(conf.get(OBS_BUNDLE_DIR) or "")
    if not bundle_dir:
        return None
    os.makedirs(bundle_dir, exist_ok=True)
    qid = getattr(ctx, "query_id", None) if ctx is not None else None
    live = getattr(session, "live_obs", None)

    # finding chain: everything the live store holds for this query,
    # plus synthetic findings (query.failed / serve.rejected)
    chain: list = []
    if live is not None and qid:
        try:
            chain = list(live.findings_for(qid))
        except Exception:
            chain = []
    chain.extend(extra_findings or [])
    if trigger is None:
        trigger = next((f for f in chain if is_trigger(f)), None)

    workers = _pull_workers(session)

    with _LOCK:
        _SEQ += 1
        seq = _SEQ
    bid = f"{int(time.time() * 1000):013d}-{os.getpid()}-{seq:03d}"
    bdir = os.path.join(bundle_dir, f"bundle-{bid}")
    os.makedirs(bdir, exist_ok=True)

    files = ["bundle.json"]

    # Chrome trace (driver + ingested worker tracks + pulled rings)
    trace = _query_trace(session, qe, ctx, workers)
    if trace is not None:
        with open(os.path.join(bdir, "trace.json"), "w") as f:
            json.dump(trace, f, default=_json_default)
        files.append("trace.json")

    # plan reports — host-only renders of already-computed state
    explains = {}
    if qe is not None:
        for mode, fname in (("simple", "explain_simple.txt"),
                            ("analysis", "explain_analysis.txt")):
            try:
                txt = qe.explain_string(
                    "formatted" if mode == "simple" else mode)
            except Exception as e:
                txt = f"(explain {mode} failed: {e})\n"
            with open(os.path.join(bdir, fname), "w") as f:
                f.write(txt)
            explains[mode] = fname
            files.append(fname)
        try:
            txt = _analyze_text(qe, ctx, chain)
        except Exception as e:
            txt = f"(explain analyze failed: {e})\n"
        with open(os.path.join(bdir, "explain_analyze.txt"), "w") as f:
            f.write(txt)
        explains["analyze"] = "explain_analyze.txt"
        files.append("explain_analyze.txt")

    # metrics plane: the scrape + the time-series ring window
    try:
        prom = _export.REGISTRY.render_prometheus()
    except Exception:
        prom = ""
    with open(os.path.join(bdir, "metrics.prom"), "w") as f:
        f.write(prom)
    files.append("metrics.prom")
    try:
        timeseries = _export.timeseries_snapshot()
    except Exception:
        timeseries = {}

    profile, history = _profile_section(qe, session)

    hbm: dict = {}
    try:
        hbm["ledger"] = GLOBAL_LEDGER.snapshot()
        if qid:
            hbm["query"] = GLOBAL_LEDGER.query_record(qid)
    except Exception:
        pass

    live_snap = None
    if live is not None:
        try:
            live_snap = live.snapshot()
        except Exception:
            live_snap = None

    manifest = {
        "v": 1,
        "id": bid,
        "ts": round(time.time(), 3),
        "reason": reason,
        "query_id": qid,
        "trigger": trigger,
        "findings": chain,
        "conf_overrides": {k: str(v)
                           for k, v in sorted(conf.overrides().items())},
        "plan": {
            "detail": (qe.physical.simple_string()[:200]
                       if qe is not None
                       and hasattr(qe.physical, "simple_string")
                       else None),
            "phases": {k: round(v * 1000, 3)
                       for k, v in (qe.phase_times if qe is not None
                                    else {}).items()},
            "fingerprint": (profile or {}).get("fingerprint"),
            "query_key": (profile or {}).get("query_key"),
        } if qe is not None else None,
        "profile": profile,
        "profile_history": history,
        "metrics": {"export_enabled": _export.ENABLED,
                    "timeseries": timeseries},
        "hbm": hbm,
        "live": live_snap,
        "workers": workers,
        "explain": explains,
        "files": files,
    }
    if extra_manifest:
        manifest.update(extra_manifest)
    with open(os.path.join(bdir, "bundle.json"), "w") as f:
        json.dump(manifest, f, default=_json_default)

    # flock-safe retention ring: index append + oldest-dir pruning run
    # under one sidecar lock, so concurrent capturing processes agree
    index = JsonlRing(os.path.join(bundle_dir, "index.jsonl"),
                      ring=_RING)
    entry = {"id": bid, "ts": manifest["ts"], "reason": reason,
             "query_id": qid,
             "trigger_kind": (trigger or {}).get("kind"),
             "severity": (trigger or {}).get("severity"),
             "findings": len(chain), "dir": f"bundle-{bid}"}
    with index.locked():
        index.append(entry)
        keep = {e.get("id") for e in index.load()[-_RING:]}
        for name in sorted(os.listdir(bundle_dir)):
            if not name.startswith("bundle-"):
                continue
            if name[len("bundle-"):] not in keep:
                shutil.rmtree(os.path.join(bundle_dir, name),
                              ignore_errors=True)

    if qid is not None:
        with _LOCK:
            _CAPTURED[qid] = bid
            while len(_CAPTURED) > _MAX_CAPTURED:
                _CAPTURED.popitem(last=False)
        # surface the bundle id where operators already look: EXPLAIN
        # ANALYZE findings and pool-status slo_findings both render the
        # live store's finding chain
        if live is not None:
            try:
                live.add_finding(qid, {
                    "severity": "info", "kind": "obs.bundle",
                    "bundle_id": bid,
                    "msg": f"diagnostic bundle {bid} captured "
                           f"({reason}) under {bundle_dir}"})
            except Exception:
                pass
    return bid


# ---------------------------------------------------------------------------
# offline readers (history server /bundles, dev/diagnose.py)
# ---------------------------------------------------------------------------

def list_bundles(bundle_dir: str) -> list[dict]:
    """Index entries whose bundle directory still exists, newest first.
    Lockless (JSONL lines are self-delimiting; a torn tail is
    skipped)."""
    from ..utils.diskstore import JsonlRing

    path = os.path.join(bundle_dir, "index.jsonl")
    if not os.path.isfile(path):
        return []
    out = []
    for e in JsonlRing(path).load():
        d = e.get("dir")
        if d and os.path.isdir(os.path.join(bundle_dir, d)):
            out.append(e)
    out.reverse()
    return out


def load_bundle(bundle_dir: str, bundle_id: str) -> dict | None:
    """One bundle's manifest by id (None when unknown/pruned)."""
    path = os.path.join(bundle_dir, f"bundle-{bundle_id}", "bundle.json")
    if not os.path.isfile(path):
        return None
    with open(path) as f:
        return json.load(f)


def pack_bundle(bundle_dir: str, bundle_id: str,
                out: str | None = None) -> str:
    """Pack one bundle directory into a single .tar.gz for attaching to
    a ticket / shipping off-host (dev/diagnose.py --tar). The archive
    root is the bundle directory name, so unpacking next to a bundle dir
    round-trips into something list_bundles/load_bundle/diagnose can
    read directly. Returns the archive path."""
    import tarfile

    bdir = os.path.join(bundle_dir, f"bundle-{bundle_id}")
    if not os.path.isdir(bdir):
        raise FileNotFoundError(f"no such bundle: {bundle_id}")
    if out is None:
        out = os.path.join(bundle_dir, f"bundle-{bundle_id}.tar.gz")
    tmp = out + ".tmp"
    with tarfile.open(tmp, "w:gz") as tf:
        tf.add(bdir, arcname=f"bundle-{bundle_id}")
    os.replace(tmp, out)   # readers never see a torn archive
    return out
