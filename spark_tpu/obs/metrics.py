"""Per-operator SQLMetrics with kernel-launch attribution + EXPLAIN ANALYZE.

Role of the reference's SQLMetrics + the SQL tab's per-node metric
annotations (sqlx/metric/SQLMetrics.scala, SparkPlanGraph), with the two
pieces a fusing TPU engine needs that Spark does not:

  * kernel attribution — the process-global KernelCache counts launches
    and compile-ms; a contextvar scoped to the EXECUTING operator (pushed
    by the PhysicalPlan execute wrapper, propagated into par_map lanes)
    re-buckets every launch to the physical node that dispatched it. A
    whole-stage fused operator owns its single dispatch; `fused_members`
    re-attributes that dispatch to the constituent operators the
    `FuseStages` rewrite collapsed (Flare's lesson: once a stage compiles
    to one program, per-operator attribution must be rebuilt
    deliberately).

  * sync-free row counts — output rows come from host-side batch
    metadata (`_num_rows`); batches whose live count is only on device
    park their row-mask array (bounded by a per-query byte budget) and
    are resolved ONCE per distinct mask identity at query end. Collection
    never launches a kernel and never blocks mid-query.

`AnalyzedReport` is the EXPLAIN ANALYZE surface: the executed plan
annotated with measured metrics side by side with the static analyzer's
predictions (analysis/plan_lint.py), with drift between them surfaced as
first-class findings.
"""

from __future__ import annotations

import contextlib
import contextvars
import sys
import threading
from dataclasses import dataclass, field

import numpy as np

from ..utils import lockwatch

__all__ = ["AnalyzedReport", "QueryKernelLedger", "batch_cost_scope",
           "current_op_name", "current_query_ledger",
           "export_op_records", "export_op_records_partial",
           "finalize_plan_metrics", "fused_members",
           "get_or_create_op_record", "iter_metric_nodes",
           "merge_op_records", "metric_children", "new_op_record",
           "pop_op", "pop_query_ledger", "push_op", "push_query_ledger",
           "record_compile_disk_event", "record_kernel_launch",
           "record_kernel_compile", "record_kernel_disk_hit",
           "record_kernel_miss", "scoped_submit"]


# ---------------------------------------------------------------------------
# Attribution scope: which operator is executing on this thread/lane
# ---------------------------------------------------------------------------

# (record dict | None, operator name). contextvars (not thread-locals) so
# exec/scheduler.par_map can copy the context into its lane threads and
# kernels dispatched from a lane still attribute to the dispatching node.
_SCOPE: "contextvars.ContextVar" = contextvars.ContextVar(
    "spark_tpu_op_scope", default=None)

# per-record Counter updates are read-modify-write; lanes of one operator
# share its record, so serialize the tiny increments
_ATTR_LOCK = threading.Lock()
lockwatch.register("obs.metrics._ATTR_LOCK",
                   sys.modules[__name__], "_ATTR_LOCK")

# live-row fraction of the batch currently dispatching. Captured kernel
# costs are per-kernel-identity CONSTANTS (first-invocation lowering), so
# shape buckets whose batches carry very different live row counts would
# overstate per-operator bytes/flops — and EXPLAIN ANALYZE's achieved
# GB/s — on sparse batches. Dispatch sites that know the live count
# host-side (ExprPipeline.run, the fused stage kernels) scope this
# fraction around the kernel call and record_kernel_launch scales the
# cost multiplied onto the OPERATOR record. The process-wide KernelCache
# counters stay unscaled: they mirror the cost model's per-launch bytes.
_BATCH_FRACTION: "contextvars.ContextVar" = contextvars.ContextVar(
    "spark_tpu_batch_fraction", default=None)


# ---------------------------------------------------------------------------
# Per-query kernel ledger: scope-exact launch/compile deltas
# ---------------------------------------------------------------------------

# The KernelCache counters are PROCESS-global: two queries collecting
# concurrently on one process read each other's launches into any
# snapshot-delta they take (the PR 12 `overlapped` limitation). The
# ledger fixes that at the source: QueryExecution installs one
# QueryKernelLedger in this contextvar for the execution window, the
# contextvar follows the work into par_map lanes (copied contexts) and
# scoped_submit pools, and every KernelCache launch/compile event also
# lands on the CURRENT query's ledger — so racing queries get disjoint,
# exact deltas and profiles/EXPLAIN ANALYZE stop needing an overlap
# guard. Cluster-worker launches are NOT in the ledger (separate
# processes); they keep shipping per-task deltas that the driver folds
# per query (ctx.worker_kernel_kinds).
_QUERY_LEDGER: "contextvars.ContextVar" = contextvars.ContextVar(
    "spark_tpu_query_ledger", default=None)


class QueryKernelLedger:
    """Per-query accumulator of kernel events (launches by kind, engine
    compiles, compile wall-ms, disk-served compiles). Pure host
    bookkeeping; thread-safe because one query's launches arrive from
    several par_map lanes."""

    __slots__ = ("_lock", "kinds", "launches", "compiles", "compile_ms",
                 "disk_hit_compiles", "disk_hits", "disk_misses")

    def __init__(self):
        self._lock = threading.Lock()
        self.kinds: dict = {}
        self.launches = 0
        self.compiles = 0
        self.compile_ms = 0.0
        self.disk_hit_compiles = 0
        # raw XLA persistent-cache traffic of THIS query's compiles
        # (exec/persist_cache._on_monitor_event) — distinct from
        # disk_hit_compiles, which counts KERNELS whose first
        # invocation was disk-served
        self.disk_hits = 0
        self.disk_misses = 0

    def _launch(self, kind) -> None:
        with self._lock:
            self.kinds[kind] = self.kinds.get(kind, 0) + 1
            self.launches += 1

    def _compile(self, ms: float) -> None:
        with self._lock:
            self.compile_ms += ms

    def _miss(self) -> None:
        with self._lock:
            self.compiles += 1

    def _disk_hit(self) -> None:
        with self._lock:
            self.disk_hit_compiles += 1

    def _disk_event(self, hit: bool) -> None:
        with self._lock:
            if hit:
                self.disk_hits += 1
            else:
                self.disk_misses += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"kinds": dict(self.kinds),
                    "launches": self.launches,
                    "compiles": self.compiles,
                    "compile_ms": self.compile_ms,
                    "disk_hit_compiles": self.disk_hit_compiles,
                    "disk_hits": self.disk_hits,
                    "disk_misses": self.disk_misses}


def push_query_ledger(ledger: "QueryKernelLedger"):
    """Enter a query's kernel-ledger scope; returns the reset token."""
    return _QUERY_LEDGER.set(ledger)


def pop_query_ledger(token) -> None:
    _QUERY_LEDGER.reset(token)


def current_query_ledger() -> "QueryKernelLedger | None":
    return _QUERY_LEDGER.get()


def record_kernel_miss(kind) -> None:
    """Called by KernelCache on every cache miss (= one engine compile:
    trace + jit). The ledger's `compiles` mirrors what a process-level
    KC.misses delta would read on a serial run."""
    led = _QUERY_LEDGER.get()
    if led is not None:
        led._miss()


def record_kernel_disk_hit(kind) -> None:
    """Called by KernelCache when a kernel's first invocation was served
    by the persistent XLA disk cache (exec/persist_cache.py)."""
    led = _QUERY_LEDGER.get()
    if led is not None:
        led._disk_hit()


def record_compile_disk_event(hit: bool) -> None:
    """Called by persist_cache's jax monitoring listener per raw XLA
    disk-cache hit/miss — the compile runs on the dispatching thread,
    so the event lands on the compiling query's ledger (scope-exact
    per-query compile.disk_* deltas under concurrency)."""
    led = _QUERY_LEDGER.get()
    if led is not None:
        led._disk_event(hit)


@contextlib.contextmanager
def batch_cost_scope(batch):
    """Context manager scoping the live-row fraction of `batch` (host
    metadata only — an unknown live count scales nothing). Runs once per
    kernel dispatch: module-level contextmanager, no per-call closure."""
    rows = batch._num_rows
    cap = batch.capacity
    frac = None
    if rows is not None and cap and rows < cap:
        frac = max(int(rows), 1) / cap
    token = _BATCH_FRACTION.set(frac)
    try:
        yield
    finally:
        _BATCH_FRACTION.reset(token)


def new_op_record() -> dict:
    return {"rows": 0, "rows_exact": True, "batches": 0, "ms": 0.0,
            "calls": 0, "kinds": {}, "launch_total": 0, "compile_ms": 0.0,
            "flops": 0.0, "bytes": 0.0, "pending": []}


def get_or_create_op_record(rec: dict, key) -> dict:
    """Insert-if-absent under the attribution lock. The plan_metrics
    dict is iterated under `_ATTR_LOCK` by the live-telemetry partial
    export (heartbeat thread) while operator threads create records for
    nodes reaching their first batch — an unlocked `rec[key] = ...`
    there can blow up the iterator with "dict changed size during
    iteration". Every insertion into a plan_metrics dict goes through
    here or `merge_op_records`."""
    ent = rec.get(key)
    if ent is None:
        with _ATTR_LOCK:
            ent = rec.get(key)
            if ent is None:
                ent = rec[key] = new_op_record()
    return ent


def push_op(record: dict | None, name: str):
    """Enter an operator's attribution scope; returns the reset token."""
    return _SCOPE.set((record, name))


def pop_op(token) -> None:
    _SCOPE.reset(token)


def current_op_name() -> str | None:
    scope = _SCOPE.get()
    return scope[1] if scope is not None else None


def record_kernel_launch(kind, cost: dict | None = None) -> None:
    """Called by KernelCache on every kernel invocation (pure host
    bookkeeping — never a launch or sync itself). `cost` is the kernel's
    captured per-launch cost (flops / bytes accessed — physical/compile.
    _capture_kernel_cost), multiplied out onto the executing operator's
    record so EXPLAIN ANALYZE can render per-operator FLOPs, bytes and
    achieved GB/s. Also lands the launch on the current query's kernel
    ledger (scope-exact per-query deltas under concurrent collects)."""
    led = _QUERY_LEDGER.get()
    if led is not None:
        led._launch(kind)
    scope = _SCOPE.get()
    if scope is None or scope[0] is None:
        return
    rec = scope[0]
    frac = _BATCH_FRACTION.get() if cost is not None else None
    with _ATTR_LOCK:
        rec["kinds"][kind] = rec["kinds"].get(kind, 0) + 1
        rec["launch_total"] += 1
        if cost is not None:
            if frac is not None:
                # scale the per-identity constant cost by the dispatching
                # batch's live-row fraction (PR 7 follow-on: sparse
                # batches no longer overstate achieved GB/s)
                rec["flops"] += cost["flops"] * frac
                rec["bytes"] += cost["bytes"] * frac
            else:
                rec["flops"] += cost["flops"]
                rec["bytes"] += cost["bytes"]


def record_kernel_compile(kind, ms: float) -> None:
    """Called by KernelCache for builder time and first-invocation (XLA
    lazy compile) time."""
    led = _QUERY_LEDGER.get()
    if led is not None:
        led._compile(ms)
    scope = _SCOPE.get()
    if scope is None or scope[0] is None:
        return
    rec = scope[0]
    with _ATTR_LOCK:
        rec["compile_ms"] += ms


def scoped_submit(pool, fn, *args):
    """Submit `fn` to a concurrent.futures pool under a COPY of the
    caller's contextvars Context, taken at submit time — the same
    discipline `exec/scheduler.par_map` applies to its lane threads.
    Pool worker threads start with an empty context, so a bare
    `pool.submit` silently re-buckets every kernel launch the task
    dispatches to "unattributed" and drops its spans' query tag; this is
    the one sanctioned way to hand obs-scoped work to a thread pool.
    One Context copy per submit (a Context cannot be entered
    concurrently)."""
    ctx = contextvars.copy_context()
    return pool.submit(ctx.run, fn, *args)


# ---------------------------------------------------------------------------
# Sync-free row accounting
# ---------------------------------------------------------------------------

# Device-memory ceiling for row masks parked until query end. Parking
# holds a strong reference (the mask cannot be freed mid-query), so the
# budget bounds the extra HBM metrics-on can pin on huge queries: beyond
# it, rows degrade to a lower bound (rows_exact=False) instead of
# risking an OOM a metrics-off run would not hit. The special "_parked"
# key in the plan_metrics dict carries the query's remaining budget.
PARKED_MASK_BUDGET_BYTES = 64 << 20
_PARKED_KEY = "_parked"


def count_batch(rec: dict, record: dict, batch) -> None:
    """Account one output batch against an operator record using only
    host-side metadata. Device masks are parked (within the per-query
    byte budget) for query-end resolution — never pulled here."""
    record["batches"] += 1
    n = getattr(batch, "_num_rows", None)
    if n is not None:
        record["rows"] += n
        return
    mask = getattr(batch, "row_mask", None)
    if mask is None:
        record["rows_exact"] = False
        return
    if isinstance(mask, np.ndarray):  # already host data — free to count
        record["rows"] += int(mask.sum())
        return
    budget = rec.get(_PARKED_KEY)
    if budget is None:
        # locked insert — the live-telemetry flush iterates this dict
        # under _ATTR_LOCK (export_op_records_partial) concurrently
        with _ATTR_LOCK:
            budget = rec.get(_PARKED_KEY)
            if budget is None:
                budget = rec[_PARKED_KEY] = \
                    [PARKED_MASK_BUDGET_BYTES, set()]
    remaining, charged = budget
    if id(mask) in charged:
        # already pinned by another operator's park this query: sharing
        # a mask costs one pull and one ref — charge the budget once
        record["pending"].append(mask)
        return
    nbytes = int(getattr(mask, "nbytes", 0) or 0)
    if remaining - nbytes < 0:
        record["rows_exact"] = False  # budget spent: lower bound only
        return
    budget[0] = remaining - nbytes
    charged.add(id(mask))
    record["pending"].append(mask)


def _op_records(rec: dict):
    return (ent for k, ent in rec.items() if k != _PARKED_KEY)


def metric_key(node) -> int:
    """Stable metric-record key: the pre-assigned `_metric_id` (survives
    the stage builder's exchange copies) or the object id."""
    k = getattr(node, "_metric_id", None)
    return id(node) if k is None else k


def iter_metric_nodes(physical):
    """Every node that can own a metric record, INCLUDING a whole-query
    wrapper's inner plan (its child_fields=() hides the inner tree from
    the schedulable walk, but a runtime tier degrade executes those
    operators directly — they need pre-assigned metric ids so the
    records land under keys the renderers know)."""
    def walk(node):
        yield node
        for c in metric_children(node, degraded_only=False):
            yield from walk(c)

    yield from walk(physical)


def metric_children(node, degraded_only: bool = True) -> list:
    """A node's children for metric/graph rendering. A whole-query
    wrapper that DEGRADED to the stage tier at runtime contributes its
    inner plan as a rendered child (per-member attribution through the
    wrapper — degraded profiles read like stage-tier profiles); a
    healthy wrapper keeps its single-dispatch fused_members view.
    `degraded_only=False` (metric-id assignment) always descends: the
    ids must exist BEFORE execution decides whether to degrade."""
    kids = list(node.children)
    inner = getattr(node, "degraded_inner", None)
    if inner is not None:
        inner_plan = inner() if degraded_only else inner(always=True)
        if inner_plan is not None:
            kids = [inner_plan] + kids
    return kids


def iter_plan_metrics(physical, rec: dict):
    """Depth-first (node, depth, key, metric-fields) over the executed
    plan — the single walker both plan_graph and EXPLAIN ANALYZE consume,
    so a new metric field reaches every renderer at once. Descends into
    a runtime-degraded whole-query wrapper's inner plan (see
    metric_children)."""
    out = []

    def walk(node, depth):
        key = metric_key(node)
        out.append((node, depth, key, op_metric_fields(rec.get(key))))
        for c in metric_children(node):
            walk(c, depth + 1)

    walk(physical, 0)
    return out


def op_metric_fields(ent: dict | None) -> dict:
    """One operator record → the per-node metric fields every renderer
    shares (plan_graph, EXPLAIN ANALYZE, history server). Single place to
    extend when records grow new counters — the walkers only add their
    own identity/topology fields around this."""
    if not ent:
        return {"rows": None, "rows_exact": True, "ms": None,
                "batches": None, "launches": None, "compile_ms": None,
                "flops": None, "bytes": None, "gbps": None}
    ms = ent["ms"]
    by = ent.get("bytes") or 0.0
    return {"rows": ent["rows"], "rows_exact": ent["rows_exact"],
            "ms": round(ms, 3),
            "batches": ent["batches"] or None,
            "launches": dict(ent["kinds"]) if ent["kinds"] else None,
            "compile_ms": round(ent["compile_ms"], 3)
            if ent["compile_ms"] else None,
            "flops": round(ent.get("flops") or 0.0, 1) or None,
            "bytes": round(by, 1) or None,
            # achieved device bandwidth: captured bytes over INCLUSIVE
            # wall-ms (an understatement for parents that time their
            # children — still the roofline-facing number per leaf/stage)
            "gbps": round(by / (ms / 1000.0) / 1e9, 3)
            if by and ms > 0 else None}


def finalize_plan_metrics(rec: dict | None) -> None:
    """Resolve parked row masks at query end: one host pull per DISTINCT
    mask identity, deduped QUERY-LOCALLY so masks shared across operators
    (reorder projections, rewrapped union batches) sync once. A local
    dict — not the bounded utils/device_memo LRU — because parked masks
    are per-query temporaries: pushing them through the shared memo could
    evict the dense-range seeds and cause real kernel re-launches. This
    is the only device read the metrics layer performs, and it happens
    after the query's last dispatch."""
    if not rec:
        return
    counts: dict[int, int] = {}  # id(mask) -> live rows, this query only
    for ent in _op_records(rec):
        pending = ent.get("pending")
        if not pending:
            continue
        ent["pending"] = []
        for mask in pending:
            try:
                n = counts.get(id(mask))
                if n is None:
                    n = counts[id(mask)] = int(np.asarray(mask).sum())
                ent["rows"] += n
            except Exception:
                ent["rows_exact"] = False
    with _ATTR_LOCK:  # size-changing pop vs the live flush's iteration
        rec.pop(_PARKED_KEY, None)


def discard_pending(rec: dict | None) -> None:
    """Drop parked masks without resolving (failed queries)."""
    if not rec:
        return
    for ent in _op_records(rec):
        if ent.get("pending"):
            ent["pending"] = []
            ent["rows_exact"] = False
    with _ATTR_LOCK:  # size-changing pop vs the live flush's iteration
        rec.pop(_PARKED_KEY, None)


# ---------------------------------------------------------------------------
# Cross-process shipping (cluster workers → driver)
# ---------------------------------------------------------------------------

def export_op_records(rec: dict | None) -> dict:
    """Worker-side: resolve parked masks and strip device references so
    the per-operator records can ride the stage-task result back to the
    driver. Keys are the plan nodes' pre-assigned `_metric_id`s, which
    survive cloudpickle into the worker — the driver merges by the same
    key. Resolving here adds no extra sync: the task result path has
    already pulled every output batch to the host for Arrow-IPC block
    storage, so the stage's last dispatch is long done."""
    if not rec:
        return {}
    finalize_plan_metrics(rec)
    return {key: {f: v for f, v in ent.items() if f != "pending"}
            for key, ent in rec.items() if key != _PARKED_KEY}


def export_op_records_partial(rec: dict | None) -> dict:
    """Live-telemetry snapshot of in-flight per-operator records: host
    counters only, parked row-masks STAY PARKED (resolving them is a
    device sync the mid-query contract forbids — they resolve once at
    task end). Rows with pending masks degrade to a lower bound
    (rows_exact=False) in the snapshot; the final task-return record
    supersedes with exact values. Never touches a device array."""
    if not rec:
        return {}
    out = {}
    with _ATTR_LOCK:
        for key, ent in rec.items():
            if key == _PARKED_KEY:
                continue
            out[key] = {
                "rows": ent["rows"],
                "rows_exact": ent["rows_exact"] and not ent["pending"],
                "batches": ent["batches"], "ms": round(ent["ms"], 3),
                "calls": ent["calls"], "kinds": dict(ent["kinds"]),
                "launch_total": ent["launch_total"],
                "compile_ms": round(ent["compile_ms"], 3),
                "flops": round(ent.get("flops", 0.0), 1),
                "bytes": round(ent.get("bytes", 0.0), 1)}
    return out


def merge_op_records(dst: dict, shipped: dict) -> None:
    """Driver-side: fold a worker's shipped per-operator records into the
    query's plan_metrics dict (same key space — `_metric_id`). Counters
    accumulate; rows_exact degrades monotonically. Lanes of one query
    may merge from several map tasks concurrently, so the increments
    serialize on the shared attribution lock."""
    with _ATTR_LOCK:
        for key, src in shipped.items():
            ent = dst.get(key)
            if ent is None:
                ent = dst[key] = new_op_record()
            ent["rows"] += src.get("rows", 0)
            ent["rows_exact"] = ent["rows_exact"] and \
                src.get("rows_exact", True)
            ent["batches"] += src.get("batches", 0)
            ent["ms"] += src.get("ms", 0.0)
            ent["calls"] += src.get("calls", 0)
            ent["launch_total"] += src.get("launch_total", 0)
            ent["compile_ms"] += src.get("compile_ms", 0.0)
            ent["flops"] += src.get("flops", 0.0)
            ent["bytes"] += src.get("bytes", 0.0)
            for kind, n in (src.get("kinds") or {}).items():
                ent["kinds"][kind] = ent["kinds"].get(kind, 0) + n


# ---------------------------------------------------------------------------
# Fused-stage re-attribution (the FuseStages mapping, inverted)
# ---------------------------------------------------------------------------

def pipeline_member_names(filters, outputs) -> list[str]:
    """Filter/Project member descriptions of a fused pipeline (shared by
    the fused operators' `fused_members` implementations)."""
    out = []
    if filters:
        out.append("Filter[" + " AND ".join(
            f.simple_string() for f in filters)[:80] + "]")
    out.append("Project[" + ", ".join(
        o.simple_string() for o in outputs)[:80] + "]")
    return out


def fused_members(node) -> list[str]:
    """Constituent operators a whole-stage fused node subsumes, in
    produce→consume order — the single fused dispatch per batch is
    re-attributed to these (the reference renders member operators inside
    their WholeStageCodegen cluster). Fused nodes expose the FuseStages
    mapping via their `fused_members()` method; anything else has none."""
    fn = getattr(node, "fused_members", None)
    return fn() if fn is not None else []


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE report
# ---------------------------------------------------------------------------

@dataclass
class AnalyzedReport:
    """Measured steady-state execution annotated onto the physical plan,
    reconciled against the static analyzer's predictions."""

    nodes: list = field(default_factory=list)       # rendered rows
    predicted: dict = field(default_factory=dict)   # kind -> launches
    measured: dict = field(default_factory=dict)    # kind -> launches
    prediction_exact: bool = True
    findings: list = field(default_factory=list)    # {severity, kind?, msg}
    counter_deltas: dict = field(default_factory=dict)
    wall_ms: float = 0.0
    # HBM accounting: predicted per-stage peaks (plan_lint memory model)
    # reconciled against the device ledger's measured watermarks
    # (obs/resources.py) — {"predicted_peak", "measured_peak",
    # "per_stage": [...], "remote": {executor: peak}, "peak_gbps"}
    memory: dict = field(default_factory=dict)

    @property
    def drift_kinds(self) -> list[str]:
        kinds = set(self.predicted) | set(self.measured)
        return sorted(k for k in kinds
                      if self.predicted.get(k, 0) != self.measured.get(k, 0))

    @property
    def has_unexplained_drift(self) -> bool:
        return any(f["severity"] == "error" for f in self.findings)

    def to_dict(self) -> dict:
        return {"nodes": list(self.nodes),
                "predicted": dict(self.predicted),
                "measured": dict(self.measured),
                "prediction_exact": self.prediction_exact,
                "findings": list(self.findings),
                "counter_deltas": dict(self.counter_deltas),
                "wall_ms": round(self.wall_ms, 3),
                "memory": dict(self.memory)}

    def render(self) -> str:
        out = ["== EXPLAIN ANALYZE (measured steady-state run, "
               f"{self.wall_ms:.1f} ms) =="]
        for nd in self.nodes:
            pad = "  " * nd["depth"]
            rows = nd["rows"]
            rows_s = "?" if rows is None else (
                str(rows) if nd.get("rows_exact", True) else f">={rows}")
            kinds = nd.get("launches") or {}
            ks = ",".join(f"{k}:{v}" for k, v in sorted(kinds.items()))
            peak_gbps = self.memory.get("peak_gbps")
            gbps = nd.get("gbps")
            gbps_s = ""
            if gbps is not None:
                gbps_s = f", {gbps:g} GB/s"
                if peak_gbps:
                    gbps_s += f" ({100.0 * gbps / peak_gbps:.0f}% of peak)"
            line = (f"{pad}{nd['detail']}  "
                    f"[rows={rows_s}"
                    + (f", {nd['ms']:.2f} ms" if nd["ms"] is not None else "")
                    + (f", batches={nd['batches']}" if nd.get("batches")
                       else "")
                    + (f", launches={{{ks}}}" if ks else "")
                    + (f", flops={nd['flops']:g}" if nd.get("flops")
                       else "")
                    + (f", bytes={_fmt_bytes(nd['bytes'])}"
                       if nd.get("bytes") else "")
                    + gbps_s
                    + (f", hbm_peak={_fmt_bytes(nd['hbm_peak'])}"
                       if nd.get("hbm_peak") else "")
                    + (f", compile={nd['compile_ms']:.1f} ms"
                       if nd.get("compile_ms") else "")
                    + "]")
            out.append(line)
            for m in nd.get("fused", ()):
                out.append(f"{pad}  + fused: {m} (shares the stage's "
                           "single dispatch per batch)")
        out.append("-- kernel launches: predicted vs measured "
                   + ("(prediction EXACT) --" if self.prediction_exact
                      else "(prediction approximate) --"))
        kinds = sorted(set(self.predicted) | set(self.measured))
        for k in kinds:
            p, m = self.predicted.get(k, 0), self.measured.get(k, 0)
            mark = "ok" if p == m else "DRIFT"
            out.append(f"  {k:<18} predicted={p:<5} measured={m:<5} {mark}")
        out.append(f"  {'total':<18} predicted="
                   f"{sum(self.predicted.values()):<5} measured="
                   f"{sum(self.measured.values()):<5}")
        mem = self.memory
        if mem:
            pred = mem.get("predicted_peak")
            meas = mem.get("measured_peak")
            out.append("-- memory (HBM, per-stage peaks) --")
            out.append(
                "  query peak: predicted~"
                + (_fmt_bytes(pred) if pred is not None else "?")
                + "  measured watermark="
                + (_fmt_bytes(meas) if meas is not None else "?")
                + ("" if not mem.get("remote") else
                   "  workers={"
                   + ", ".join(f"{e}:{_fmt_bytes(v.get('peak', 0))}"
                               for e, v in sorted(mem["remote"].items()))
                   + "}"))
            for st in mem.get("per_stage", ()):
                tag = st["op"] if st.get("instances", 1) == 1 \
                    else f"{st['op']} ×{st['instances']}"
                out.append(f"  {tag:<22} predicted~"
                           f"{_fmt_bytes(st['predicted'])}"
                           + (f"  measured peak="
                              f"{_fmt_bytes(st['measured'])}"
                              if st.get("measured") is not None else ""))
            if mem.get("xla_temp_peak"):
                out.append("  xla temp scratch (peak per dispatch): "
                           + _fmt_bytes(mem["xla_temp_peak"])
                           + " — outside the engine-tile ledger")
        if self.findings:
            out.append("-- findings --")
            for f in self.findings:
                out.append(f"  [{f['severity']}] {f['msg']}")
        else:
            out.append("-- findings: none (zero drift) --")
        return "\n".join(out)


def _fmt_bytes(n) -> str:
    n = float(n)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if n >= div:
            return f"{n / div:.1f}{unit}"
    return f"{n:.0f}B"


def _memory_section(physical, prediction, resources: dict | None,
                    peak_gbps: float | None, nodes: list,
                    findings: list) -> dict:
    """Reconcile the analyzer's per-stage predicted HBM against the
    device ledger's measured watermarks: annotate nodes with their
    operator's measured peak, build the report's memory dict, and raise
    drift findings when a measured watermark exceeds the model (the
    model is an upper bound on engine-held tiles — overshooting it means
    the model and the execution layer diverged)."""
    mem: dict = {}
    pred_stages = [s for s in getattr(prediction, "stages", ())
                   if s.get("hbm_bytes") is not None]
    measured_ops = (resources or {}).get("ops") or {}
    if pred_stages:
        # the ledger buckets by creator-operator CLASS, so a measured
        # watermark covers every instance of that class in the query —
        # compare it against the class-summed prediction, not a single
        # instance's (two ComputeExec stages ≠ each one doubling the
        # model)
        by_cls: dict = {}
        per_stage = []
        for s in pred_stages:
            ent = by_cls.get(s["op"])
            if ent is None:
                ent = by_cls[s["op"]] = {
                    "op": s["op"], "detail": s["detail"][:80],
                    "predicted": 0, "instances": 0}
                per_stage.append(ent)
            ent["predicted"] += s["hbm_bytes"]
            ent["instances"] += 1
        for ent in per_stage:
            ent["measured"] = measured_ops.get(ent["op"], {}).get("peak")
        mem["per_stage"] = per_stage
        mem["predicted_peak"] = getattr(prediction, "predicted_peak_hbm",
                                        None)
    if resources is not None:
        mem["measured_peak"] = resources.get("peak")
        if resources.get("remote"):
            mem["remote"] = resources["remote"]
    if peak_gbps:
        mem["peak_gbps"] = peak_gbps
    # per-node annotation: the creator-op's measured HBM watermark
    by_name: dict[str, int] = {}
    for nd in nodes:
        op = nd["op"]
        m = measured_ops.get(op)
        if m is not None and op not in by_name:
            by_name[op] = 1
            nd["hbm_peak"] = m.get("peak")
    pred = mem.get("predicted_peak")
    meas = mem.get("measured_peak")
    if pred and meas is not None and meas > pred:
        exact = getattr(prediction, "memory_exact", False)
        findings.append({
            "severity": "warning" if exact else "info",
            "kind": "hbm-drift",
            "msg": f"measured HBM watermark {_fmt_bytes(meas)} exceeds "
                   f"the memory model's predicted peak {_fmt_bytes(pred)}"
                   + ("" if exact else
                      " (model approximate: "
                      + "; ".join(getattr(prediction, "memory_notes",
                                          [])[:2]) + ")")})
    return mem


def _xla_temp_section(measured: dict, mem: dict,
                      findings: list) -> None:
    """Fold captured XLA temp (scratch) bytes into the memory
    reconciliation (PR 7 follow-on): the device ledger tracks
    engine-held tiles only, so a fused kernel's scratch is invisible to
    both the predicted and the measured watermark — with
    spark.tpu.metrics.kernelMemory on, the cost table's
    memory_analysis() capture names that headroom explicitly instead of
    leaving it as unexplained drift (and as surprise OOM room under
    spark.tpu.memory.budget). Scratch lives only inside one kernel, so
    the concurrent peak is the max over the kinds this query launched."""
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    per_kind = {}
    for kind in measured:
        tb = (KC.cost_by_kind.get(kind) or {}).get("temp_bytes")
        if tb:
            per_kind[kind] = int(tb)
    if not per_kind:
        return
    peak = max(per_kind.values())
    mem["xla_temp_by_kind"] = per_kind
    mem["xla_temp_peak"] = peak
    pred = mem.get("predicted_peak")
    meas = mem.get("measured_peak")
    if pred and meas is not None and meas <= pred and meas + peak > pred:
        findings.append({
            "severity": "info", "kind": "xla-temp",
            "msg": f"XLA kernel scratch (up to {_fmt_bytes(peak)} of "
                   "temp per dispatch, memory_analysis capture) pushes "
                   "true peak HBM past the engine-tile model's "
                   f"{_fmt_bytes(pred)} — the ledger only sees "
                   "engine-held tiles, so this headroom is real but "
                   "invisible to the measured watermark"})


def build_analyzed_report(physical, plan_metrics: dict | None,
                          prediction, measured: dict,
                          counter_deltas: dict,
                          wall_ms: float,
                          resources: dict | None = None,
                          peak_gbps: float | None = None) -> AnalyzedReport:
    """Assemble the EXPLAIN ANALYZE report from the executed plan's
    per-operator records, the measured per-kind launch deltas, the
    static analyzer's AnalysisReport, and the device ledger's HBM
    accounting for the measured query (`resources` — obs/resources.py
    query_record)."""
    rec = plan_metrics or {}
    finalize_plan_metrics(rec)
    nodes = []
    for node, depth, _key, fields in iter_plan_metrics(physical, rec):
        detail = node.simple_string() if hasattr(node, "simple_string") \
            else type(node).__name__
        detail = " ".join(detail.split())  # multi-line details flatten
        nodes.append({"op": type(node).__name__, "detail": detail[:140],
                      "depth": depth, **fields,
                      "fused": fused_members(node)})

    predicted = dict(prediction.predicted_launches)
    findings: list[dict] = []
    kinds = sorted(set(predicted) | set(measured))
    for k in kinds:
        p, m = predicted.get(k, 0), measured.get(k, 0)
        if p == m:
            continue
        if prediction.exact:
            findings.append({
                "severity": "error", "kind": k,
                "msg": f"unexplained drift on kernel kind '{k}': analyzer "
                       f"predicted {p} launches (and claimed exactness), "
                       f"measured {m} — the plan_lint launch model and the "
                       "execution layer have diverged"})
        else:
            findings.append({
                "severity": "info", "kind": k,
                "msg": f"drift on kernel kind '{k}' (predicted {p}, "
                       f"measured {m}) — analyzer declared itself "
                       "approximate: "
                       + "; ".join(prediction.inexact_reasons[:3])})
    # runtime minRows gate decisions are first-class findings
    gate_notes = {n for s in prediction.stages for n in s.get("notes", ())
                  if "minRows" in n}
    for n in sorted(gate_notes):
        findings.append({"severity": "info", "kind": "minRows-gate",
                         "msg": f"runtime fusion gate: {n}"})
    retries = counter_deltas.get("join.capacity_retry", 0)
    if retries:
        findings.append({
            "severity": "warning", "kind": "capacity-retry",
            "msg": f"{retries} join probe capacity retr"
                   f"{'y' if retries == 1 else 'ies'}: the probe kernel "
                   "re-launched with a doubled output bucket "
                   "(value-dependent cache key — extra dispatch + compile)"})
    stage_retries = counter_deltas.get("scheduler.stage_retries", 0)
    if stage_retries:
        findings.append({
            "severity": "warning", "kind": "stage-retry",
            "msg": f"{stage_retries} stage retr"
                   f"{'y' if stage_retries == 1 else 'ies'} during the "
                   "measured run (lineage re-execution inflates measured "
                   "launches)"})
    memory = _memory_section(physical, prediction, resources, peak_gbps,
                             nodes, findings)
    _xla_temp_section(measured, memory, findings)
    return AnalyzedReport(nodes=nodes, predicted=predicted,
                          measured=dict(measured),
                          prediction_exact=prediction.exact,
                          findings=findings,
                          counter_deltas=dict(counter_deltas),
                          wall_ms=wall_ms, memory=memory)
