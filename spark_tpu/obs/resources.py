"""Device-resource ledger: HBM occupancy, kernel cost, memory budgets.

Role of the reference's task memory accounting — UnifiedMemoryManager's
ExecutionMemoryPool/StorageMemoryPool bookkeeping (core/memory/
ExecutionMemoryPool.scala acquireMemory/releaseMemory) plus the
peak-execution-memory task metric the UI renders — re-shaped for the XLA
allocation model. XLA owns the actual HBM allocator, so the engine does
not *reserve* bytes here; it keeps an attributed shadow ledger of every
device buffer the ENGINE holds (columnar batches: column data + validity
planes + row masks — which is also what shuffle reduce tiles, join build
inputs and agg state are made of), so queries, operators and executors
can be charged for the HBM they pin.

Contract (same as the rest of obs/): everything in this module is pure
host bookkeeping — ZERO kernel launches, no device syncs. Sizes come
from array shape/dtype metadata (`.shape`/`.dtype`/`.nbytes` never touch
device data), attribution comes from the existing contextvar scopes
(obs.tracing query scope, obs.metrics operator scope — both of which
already propagate into par_map lanes, scoped_submit pools and cluster
worker tasks), and deregistration rides weakref finalizers so the ledger
never extends a buffer's lifetime.

Three public legs:

  * `GLOBAL_LEDGER` — process-global `DeviceLedger`. Buffers register by
    ARRAY IDENTITY with a refcount, so ten ColumnarBatch wrappers over
    one device column charge the ledger once; per-query and per-operator
    buckets track live bytes and watermarks (peaks). Worker processes
    run their own instance; their per-task peaks ship back with the
    stage obs payload and on the executor heartbeat (exec/worker_main),
    so cluster live status shows HBM per executor.

  * kernel cost — the KernelCache (physical/compile.py) captures each
    compiled kernel's XLA `cost_analysis()` (flops, bytes accessed) at
    first invocation via the *lowering* (no second backend compile) with
    an argument/output-metadata fallback, and feeds it to the operator
    attribution scope per launch. EXPLAIN ANALYZE and plan_graph render
    per-operator flops/bytes and achieved GB/s against
    `device_peak_gbps()`.

  * memory budget — `check_memory_budget` pre-flights the plan
    analyzer's memory model (analysis/plan_lint.py) against
    `spark.tpu.memory.budget` BEFORE dispatch and raises
    `MemoryBudgetExceeded` naming the offending stage, instead of
    letting XLA OOM opaquely mid-query — the admission-control primitive
    the serving direction needs.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

__all__ = ["DeviceLedger", "GLOBAL_LEDGER", "MemoryBudgetExceeded",
           "check_memory_budget", "configure", "device_peak_gbps",
           "kernel_cost_enabled", "kernel_memory_enabled",
           "ledger_enabled"]

_MAX_QUERIES = 64   # retained per-query records (ring, matches LiveObs)


# ---------------------------------------------------------------------------
# process-wide switches (config-driven; flipped by configure())
# ---------------------------------------------------------------------------

# module flags rather than per-call conf reads: registration runs on the
# ColumnarBatch constructor and kernel cost capture on the KernelCache
# first-invocation path — both too hot for a conf dict lookup + parse
_LEDGER_ON = True
_KERNEL_COST_ON = True
_KERNEL_MEMORY_ON = False


def configure(conf) -> None:
    """Apply a session/worker conf to the process-global switches
    (spark.tpu.memory.ledger, spark.tpu.metrics.kernelCost/kernelMemory).
    Called by TpuSession.__init__ and the worker-side begin_stage_obs —
    the ledger itself stays process-global like the KernelCache."""
    global _LEDGER_ON, _KERNEL_COST_ON, _KERNEL_MEMORY_ON

    from ..config import KERNEL_COST, KERNEL_MEMORY, MEMORY_LEDGER

    # conf values are host data — bool() here never touches device
    _LEDGER_ON = bool(conf.get(MEMORY_LEDGER))  # tpulint: ignore[host-sync]
    _KERNEL_COST_ON = bool(conf.get(  # tpulint: ignore[host-sync]
        KERNEL_COST))
    _KERNEL_MEMORY_ON = bool(conf.get(  # tpulint: ignore[host-sync]
        KERNEL_MEMORY))


def ledger_enabled() -> bool:
    return _LEDGER_ON


def kernel_cost_enabled() -> bool:
    return _KERNEL_COST_ON


def kernel_memory_enabled() -> bool:
    """XLA memory_analysis() temp-bytes capture (off by default: the AOT
    lowering compile it needs is not shared with the dispatch path on
    this jax version — one extra backend compile per distinct kernel)."""
    return _KERNEL_MEMORY_ON


# ---------------------------------------------------------------------------
# peak-bandwidth reference (achieved-vs-peak GB/s in EXPLAIN ANALYZE)
# ---------------------------------------------------------------------------

# published HBM bandwidth per chip generation (GB/s); the conf override
# spark.tpu.memory.peakGbps wins when set (>0)
_PEAK_GBPS_BY_KIND = (
    ("v6", 1640.0), ("v5p", 2765.0), ("v5e", 819.0), ("v5", 819.0),
    ("v4", 1228.0), ("v3", 900.0), ("v2", 700.0),
)


def device_peak_gbps(conf=None) -> float | None:
    """Peak HBM GB/s of the local accelerator, or None when unknown
    (CPU backends have no meaningful HBM roofline). Reads only the jax
    device *descriptor* — never device memory."""
    if conf is not None:
        try:
            from ..config import MEMORY_PEAK_GBPS

            v = float(conf.get(MEMORY_PEAK_GBPS))
            if v > 0:
                return v
        except Exception:
            pass
    try:
        import jax

        kind = jax.local_devices()[0].device_kind.lower()
    except Exception:
        return None
    for tag, gbps in _PEAK_GBPS_BY_KIND:
        if tag in kind:
            return gbps
    return None


# ---------------------------------------------------------------------------
# the ledger
# ---------------------------------------------------------------------------

def _new_bucket() -> dict:
    return {"bytes": 0, "peak": 0, "registered": 0, "released": 0}


class DeviceLedger:
    """Attributed shadow ledger of engine-held device bytes.

    Registration is by array identity with a refcount: wrappers sharing
    device arrays (rewrapped batches, trivial projections, shared row
    masks) charge once, and the charge releases when the LAST owner is
    garbage-collected. Each identity is charged to the (query, operator)
    scope active at first registration — the creator owns the buffer,
    the reference's per-task peak-execution-memory discipline.

    Thread-safe; every operation is O(arrays in one batch) dict work.
    """

    def __init__(self):
        # REENTRANT: a GC cycle can run a batch's _release finalizer on
        # whatever thread happens to allocate — including one already
        # inside a ledger method holding this lock (dict growth inside
        # _register can trigger collection). A plain Lock would deadlock
        # that thread against itself; with an RLock the nested release
        # runs as a complete, consistent sequence.
        self._lock = threading.RLock()
        # id(array) -> [nbytes, refs, qid, op]
        self._arrays: dict[int, list] = {}
        self.bytes = 0              # live engine-held device bytes
        self.peak = 0               # process-lifetime watermark
        self.registered_total = 0   # cumulative bytes ever charged
        self.released_total = 0     # cumulative bytes ever released
        self._win_peak = 0          # window watermark (begin_window)
        # qid (None = unattributed) -> bucket + per-op buckets + remote
        self._queries: "OrderedDict" = OrderedDict()

    # -- buckets ----------------------------------------------------------
    def _qrec(self, qid) -> dict:
        q = self._queries.get(qid)
        if q is None:
            q = self._queries[qid] = {**_new_bucket(), "ops": {},
                                      "remote": {}}
            while len(self._queries) > _MAX_QUERIES:
                self._queries.popitem(last=False)
        return q

    # -- writes -----------------------------------------------------------
    def register_batch(self, batch) -> None:
        """Charge one ColumnarBatch's device planes (column data,
        validity masks, row mask) to the current query/operator scope and
        arm a finalizer that releases the charge when the batch dies.
        Metadata only — never reads device data."""
        if not _LEDGER_ON:
            return
        pairs = []
        rm = batch.row_mask
        if rm is not None and hasattr(rm, "shape"):
            pairs.append((rm, int(rm.size)))     # bool plane: 1 B/row
        for c in batch.columns:
            d = getattr(c, "data", None)
            if d is not None and hasattr(d, "dtype"):
                pairs.append((d, int(d.size) * d.dtype.itemsize))
            v = getattr(c, "validity", None)
            if v is not None and hasattr(v, "shape"):
                pairs.append((v, int(v.size)))
        if pairs:
            self._register(pairs, batch)

    def charge_arrays(self, arrays) -> list:
        """Explicitly charge raw device arrays (mesh staging send
        buffers — parallel/mesh_fusion.py) to the current scope. Returns
        the token for `release_arrays`; the caller owns the lifetime
        (donated buffers release at dispatch, undonated ones after the
        outputs register). Metadata only — never reads device data."""
        if not _LEDGER_ON:
            return []
        pairs = []
        for a in arrays:
            if a is None or not hasattr(a, "dtype"):
                continue
            pairs.append((a, int(a.size) * a.dtype.itemsize))
        return self._charge(pairs) if pairs else []

    def release_arrays(self, token: list) -> None:
        """Release a `charge_arrays` token (idempotent per token use)."""
        if token:
            self._release(token)

    def _register(self, pairs, owner) -> None:
        keys = self._charge(pairs)
        # the finalizer closes over ids + the ledger only — it must not
        # keep the arrays (or the batch) alive
        weakref.finalize(owner, self._release, keys)

    def _charge(self, pairs) -> list:
        from .metrics import current_op_name
        from .tracing import current_query

        qid = current_query()
        op = current_op_name()
        keys = []
        with self._lock:
            for obj, nb in pairs:
                key = id(obj)
                keys.append(key)
                ent = self._arrays.get(key)
                if ent is not None:
                    ent[1] += 1           # shared plane: one charge
                    continue
                self._arrays[key] = [nb, 1, qid, op]
                self.bytes += nb
                self.registered_total += nb
                if self.bytes > self.peak:
                    self.peak = self.bytes
                if self.bytes > self._win_peak:
                    self._win_peak = self.bytes
                q = self._qrec(qid)
                q["bytes"] += nb
                q["registered"] += nb
                if q["bytes"] > q["peak"]:
                    q["peak"] = q["bytes"]
                if op is not None:
                    o = q["ops"].get(op)
                    if o is None:
                        o = q["ops"][op] = _new_bucket()
                    o["bytes"] += nb
                    o["registered"] += nb
                    if o["bytes"] > o["peak"]:
                        o["peak"] = o["bytes"]
        return keys

    def _release(self, keys) -> None:
        with self._lock:
            for key in keys:
                ent = self._arrays.get(key)
                if ent is None:
                    continue
                ent[1] -= 1
                if ent[1] > 0:
                    continue
                nb, _, qid, op = self._arrays.pop(key)
                self.bytes -= nb
                self.released_total += nb
                q = self._queries.get(qid)
                if q is None:
                    continue
                q["bytes"] -= nb
                q["released"] += nb
                if op is not None and op in q["ops"]:
                    q["ops"][op]["bytes"] -= nb
                    q["ops"][op]["released"] += nb

    def merge_remote(self, qid, executor: str, shipped: dict) -> None:
        """Fold a worker task's shipped HBM accounting into the query
        record (worker HBM is a DIFFERENT device's memory — it reports
        side by side with the driver's, never summed into `bytes`)."""
        if not shipped:
            return
        with self._lock:
            rem = self._qrec(qid)["remote"]
            cur = rem.get(executor)
            if cur is None:
                rem[executor] = dict(shipped)
            else:
                cur["peak"] = max(cur.get("peak", 0),
                                  shipped.get("peak", 0))
                cur["bytes"] = shipped.get("bytes", cur.get("bytes", 0))

    # -- windows (bench measurement) --------------------------------------
    def begin_window(self) -> None:
        """Reset the window watermark to the current occupancy; read it
        back with window_peak() after the measured region."""
        with self._lock:
            self._win_peak = self.bytes

    def window_peak(self) -> int:
        with self._lock:
            return self._win_peak

    # -- reads ------------------------------------------------------------
    def snapshot(self) -> dict:
        """Executor-level occupancy (rides the heartbeat payload)."""
        with self._lock:
            return {"bytes": self.bytes, "peak": self.peak,
                    "arrays": len(self._arrays)}

    def query_record(self, qid) -> dict | None:
        """Deep-ish copy of one query's HBM accounting: live bytes,
        watermark, per-operator buckets, per-executor remote peaks."""
        with self._lock:
            q = self._queries.get(qid)
            if q is None:
                return None
            return {"bytes": q["bytes"], "peak": q["peak"],
                    "registered": q["registered"],
                    "released": q["released"],
                    "ops": {k: dict(v) for k, v in q["ops"].items()},
                    "remote": {k: dict(v) for k, v in q["remote"].items()}}

    def verify(self) -> list[str]:
        """Internal-consistency check (dev/validate_trace.py resource
        gate): non-negative balances everywhere, attribution sums never
        exceeding the global ledger, identity table reconciling with the
        byte counter."""
        issues = []
        with self._lock:
            if self.bytes < 0:
                issues.append(f"global balance negative: {self.bytes}")
            table = sum(e[0] for e in self._arrays.values())
            if table != self.bytes:
                issues.append(f"identity table {table} B != balance "
                              f"{self.bytes} B")
            if self.registered_total - self.released_total != self.bytes:
                issues.append("registered - released != balance")
            attributed = 0
            for qid, q in self._queries.items():
                if q["bytes"] < 0:
                    issues.append(f"query {qid} balance negative: "
                                  f"{q['bytes']}")
                attributed += max(q["bytes"], 0)
                for op, o in q["ops"].items():
                    if o["bytes"] < 0:
                        issues.append(
                            f"op {op} of query {qid} negative: "
                            f"{o['bytes']}")
            # evicted query records release against the global counter
            # but not their popped bucket — attribution can only be <=
            if attributed > self.bytes:
                issues.append(f"attributed {attributed} B > global "
                              f"{self.bytes} B")
        return issues


GLOBAL_LEDGER = DeviceLedger()


# ---------------------------------------------------------------------------
# memory budget pre-flight (admission control)
# ---------------------------------------------------------------------------

class MemoryBudgetExceeded(RuntimeError):
    """The plan analyzer's memory model predicts peak HBM above
    spark.tpu.memory.budget — raised BEFORE any dispatch, naming the
    offending stage, instead of an opaque XLA OOM mid-query."""


def check_memory_budget(physical, conf, report=None,
                        cluster: bool = False) -> None:
    """Pre-flight the memory model against spark.tpu.memory.budget
    (0 = unlimited). Pure host work — nothing executes on device."""
    from ..config import MEMORY_BUDGET

    budget = int(conf.get(MEMORY_BUDGET))
    if budget <= 0:
        return
    if report is None:
        from ..analysis.plan_lint import analyze_plan

        report = analyze_plan(physical, conf, cluster=cluster)
    peak = report.predicted_peak_hbm
    if peak is None or peak <= budget:
        return
    staged = [s for s in report.stages if s.get("hbm_bytes")]
    worst = max(staged, key=lambda s: s["hbm_bytes"]) if staged else None
    where = (f"largest stage: {worst['op']} "
             f"[{worst['detail'][:80]}] holding "
             f"~{worst['hbm_bytes'] / (1 << 20):.1f} MiB"
             if worst else "no per-stage breakdown available")
    raise MemoryBudgetExceeded(
        f"query predicted peak HBM ~{peak / (1 << 20):.1f} MiB exceeds "
        f"spark.tpu.memory.budget={budget} bytes "
        f"({budget / (1 << 20):.1f} MiB); {where}. Raise the budget, "
        "lower spark.tpu.batch.capacity, or repartition so less of the "
        "plan is resident at once (nothing was dispatched).")
