"""Live telemetry: heartbeat-streamed worker obs, stragglers, progress.

Role of the reference's periodic executor Heartbeater + the driver-side
machinery it feeds (core/executor/Executor.scala startDriverHeartbeater
→ HeartbeatReceiver → accumulator updates into the live AppStatusStore;
ConsoleProgressBar; the TaskSetManager's speculatable-task scan): PRs
3–5 made every query fully observable but only after the fact — worker
spans/metrics/kernel deltas ship with the task RESULT, so a
long-running or stuck stage is dark until it finishes. This module
closes that gap on the driver side:

  * `LiveObs` aggregates the partial obs records that worker tasks
    flush on the executor heartbeat (exec/worker_main.collect_live_obs
    → heartbeat payload → exec/cluster.LocalCluster._on_heartbeat →
    `on_heartbeat`) per (query, stage, task), with monotonic merge
    semantics: deltas apply in sequence order, the final task-return
    record supersedes and reconciles the partials
    (`task_finished`, wired from ClusterDAGScheduler._run_remote), and
    late heartbeats arriving after task completion are dropped.

  * a straggler detector over the same store: a running task whose
    progress rate (rows+batches+launches per second) falls below a
    configurable fraction of the stage median, or whose telemetry goes
    silent past a deadline, is flagged as an `obs.straggler` finding —
    surfaced in live status, EXPLAIN ANALYZE
    (QueryExecution.analyzed_report), and the `active_stragglers`
    signal hook the speculative-execution path consumes
    (exec/cluster.LocalCluster.speculation_signal).

  * `ConsoleProgressReporter` (the reference's ConsoleProgressBar
    analog, spark.tpu.progress.console) renders live stage bars from
    the same store, and `start_query_flusher` gives LOCAL-mode queries
    the same live feed by sampling the driver's plan_metrics from a
    flush thread (spawned through obs.metrics.scoped_submit so the
    query-scope contextvar follows the work — a bare thread would
    publish every sample untagged).

Contract (same as the rest of obs/): everything here is host
bookkeeping — zero kernel launches, zero device syncs. Partial metric
snapshots ship parked row-masks NOT AT ALL (they stay parked on the
worker until task end; see obs.metrics.export_op_records_partial).
"""

from __future__ import annotations

import sys
import threading
import time
from collections import OrderedDict

from ..config import (
    PROGRESS_UPDATE_INTERVAL, STRAGGLER_ENABLED,
    STRAGGLER_HEARTBEAT_DEADLINE, STRAGGLER_MIN_SECONDS,
    STRAGGLER_RATE_FRACTION, STRAGGLER_RATE_WEIGHTS,
)

__all__ = ["ConsoleProgressReporter", "LiveObs", "start_query_flusher"]

_MAX_QUERIES = 64          # retained finished queries (ring)
_MAX_TASK_SPANS = 64       # recent closed spans kept per task
_EXECUTOR_TTL = 60.0       # drop executor resource rows this long silent
#                            (an executor that re-registered under a new
#                            eid would otherwise leave a ghost row whose
#                            cumulative overflow count double-counts its
#                            process)


def _new_task() -> dict:
    now = time.time()
    # seq_by/rows_by are PER-EXECUTOR: speculative execution races two
    # copies of one task on the same (query, stage, task) key, each with
    # its own monotonic seq counter — comparing them against a single
    # stored seq would interleave-drop whichever copy is behind
    return {"executor": None, "seq": -1, "seq_by": {}, "rows_by": {},
            "first_seen": now,
            "last_heartbeat": now, "rows": 0, "rows_exact": True,
            "batches": 0, "launches": 0, "compile_ms": 0.0,
            "kernel_kinds": {}, "op_records": {}, "open_spans": [],
            "spans": [], "partials": 0, "done": False, "duration": None,
            "reconciled": None}


class LiveObs:
    """Driver-side aggregator of streamed observability partials.

    Thread-safe: heartbeats arrive on gRPC server threads, final
    records on scheduler map-task threads, reads from the UI/console
    reporter/EXPLAIN ANALYZE. Merge semantics are monotonic per task:
    out-of-order heartbeats (seq <= last seen) and heartbeats after the
    final record are dropped, so the store converges to the task-return
    truth regardless of arrival order.
    """

    def __init__(self, conf=None):
        self._conf = conf
        self._lock = threading.Lock()
        self._queries: "OrderedDict[str, dict]" = OrderedDict()
        self.late_dropped = 0     # heartbeats discarded after task end
        self.partials_seen = 0    # mid-stage deltas accepted
        # finished-query ring evictions: under serving load the 64-query
        # ring silently drops the oldest query's findings/progress — an
        # invisible telemetry gap until counted here (exported as
        # obs.live.evictions through the metrics registry)
        self.evictions = 0
        # post-store finding hook (obs/blackbox.on_finding): called
        # OUTSIDE self._lock with (qid, finding) after every
        # add_finding, so post-close findings (the SLO verdict lands on
        # ticket release, after execute() returned) can still trigger a
        # diagnostic-bundle capture. Never raises into the caller.
        self.finding_sink = None
        # heartbeat-sink exceptions the cluster swallowed to protect
        # liveness (exec/cluster._on_heartbeat counts them here so a
        # sink bug is visible in live status instead of silently eaten)
        self.telemetry_errors = 0
        # executor-level resource telemetry (rides every heartbeat, even
        # idle ones): eid -> {"hbm_bytes", "hbm_peak", "overflows", "at"}
        self.executors: dict[str, dict] = {}
        # host-granular exclusion state (HealthTracker escalation):
        # host -> {"excluded_until", "executors", "at"}
        self.hosts: dict[str, dict] = {}
        # straggler-scan memo: every heartbeat, UI snapshot, and
        # speculative wait polls check_stragglers — rescanning the whole
        # store each time is wasted work AND lock contention. A scan is
        # reused until a write bumps the version or the TTL lapses (the
        # clock matters even without writes: silence detection)
        self._version = 0
        self._scan_cache: tuple = (-1, 0.0, [])  # (version, at, active)
        self._rate_weights_memo = None  # (raw conf string, parsed)

    # -- config -----------------------------------------------------------
    def _cfg(self, entry, default):
        if self._conf is None:
            return default
        try:
            return entry.value_type(self._conf.get(entry))
        except Exception:
            return default

    # -- writes -----------------------------------------------------------
    def _query(self, qid: str) -> dict:
        q = self._queries.get(qid)
        if q is None:
            q = self._queries[qid] = {
                "stages": {}, "findings": [], "flagged": set(),
                "abandoned": set(), "done": False,
                "started": time.time()}
            while len(self._queries) > _MAX_QUERIES:
                self._queries.popitem(last=False)
                self.evictions += 1
        return q

    def _task(self, qid: str, stage: str, task) -> dict:
        stages = self._query(qid)["stages"]
        st = stages.get(stage)
        if st is None:
            st = stages[stage] = {"tasks": {}}
        t = st["tasks"].get(task)
        if t is None:
            t = st["tasks"][task] = _new_task()
        return t

    def on_heartbeat(self, executor_id: str, deltas: list,
                     hbm: dict | None = None,
                     overflows: int | None = None,
                     metrics: dict | None = None) -> None:
        """Fold one executor heartbeat's live obs deltas into the store.
        Each delta is a cumulative snapshot of one running stage task
        (see exec/worker_main.collect_live_obs): snapshots replace, so
        a lost heartbeat never loses counts — the next one carries
        them. Closed spans ride incrementally, carried until the worker
        acks delivery (at-least-once across failed beats; a beat whose
        reply was lost may repeat a span in the display ring).

        `hbm` is the executor's device-ledger snapshot (live HBM bytes +
        process watermark) and `overflows` its cumulative flush-budget
        trim count — executor-level facts that ride every beat, task
        deltas or not. `metrics` is the worker's metrics-registry
        counter snapshot (obs/export.executor_payload, only attached
        with spark.tpu.metrics.export on): cumulative totals that
        REPLACE the stored row, so the driver scrape's worker-labeled
        series converge regardless of lost beats."""
        now = time.time()
        if hbm is not None or overflows is not None or metrics is not None:
            with self._lock:
                ent = self.executors.setdefault(executor_id, {})
                if hbm is not None:
                    ent["hbm_bytes"] = hbm.get("bytes", 0)
                    ent["hbm_peak"] = hbm.get("peak", 0)
                if overflows is not None:
                    ent["overflows"] = overflows
                if metrics is not None:
                    ent["metrics"] = dict(metrics)
                ent["at"] = now
                for eid in [eid for eid, e in self.executors.items()
                            if now - e.get("at", now) > _EXECUTOR_TTL]:
                    del self.executors[eid]
        if not deltas:
            return
        with self._lock:
            self._version += 1
            for d in deltas:
                qid = d.get("query") or "?"
                stage = d.get("stage") or "?"
                # a heartbeat straggling in after stage_abandoned must
                # not resurrect the popped entry (it would never be
                # closed and would trip the silence deadline forever)
                if stage in self._query(qid)["abandoned"]:
                    self.late_dropped += 1
                    continue
                t = self._task(qid, stage, d.get("task", 0))
                if t["done"]:
                    self.late_dropped += 1
                    continue
                seq = d.get("seq", 0)
                if seq <= t["seq_by"].get(executor_id, -1):
                    continue            # stale/reordered snapshot
                t["seq_by"][executor_id] = seq
                t["last_heartbeat"] = now
                t["partials"] += 1
                self.partials_seen += 1
                if "rows" in d:
                    t["rows_by"][executor_id] = d["rows"]
                # speculative copies race on one task key: the
                # further-along copy owns the DISPLAYED counters
                # (snapshots are cumulative per copy, so replacing from
                # the laggard would make progress appear to move
                # backwards); with a single executor this is always true
                wr, wb, wl = self._rate_weights()
                units = (wr * d.get("rows", 0)
                         + wb * d.get("batches", 0)
                         + wl * d.get("launches", 0))
                if t["executor"] not in (None, executor_id) \
                        and units < self._units(t):
                    continue
                t["seq"] = max(t["seq"], seq)
                t["executor"] = executor_id
                for f in ("rows", "batches", "launches", "compile_ms"):
                    if f in d:
                        t[f] = d[f]
                if "rows_exact" in d:
                    t["rows_exact"] = d["rows_exact"]
                if d.get("kernel_kinds") is not None:
                    t["kernel_kinds"] = dict(d["kernel_kinds"])
                if d.get("op_records") is not None:
                    t["op_records"] = d["op_records"]
                if d.get("open_spans") is not None:
                    t["open_spans"] = d["open_spans"]
                for sp in d.get("spans_closed") or ():
                    t["spans"].append(sp)
                del t["spans"][:-_MAX_TASK_SPANS]
        self.check_stragglers(now)

    def local_update(self, qid: str | None, op_records: dict,
                     open_spans: list | None = None) -> None:
        """Local-mode feed: the driver-side flush thread samples the
        running query's plan_metrics (host counters only) into the same
        store, stage 'local'."""
        if qid is None:
            return
        rows = sum(e.get("rows", 0) for e in op_records.values())
        batches = sum(e.get("batches", 0) for e in op_records.values())
        launches = sum(e.get("launch_total", 0)
                       for e in op_records.values())
        # the driver is an "executor" too: publish its device-ledger
        # occupancy so local-mode consoles show the same HBM rows the
        # cluster heartbeats feed (host counters only)
        from .resources import GLOBAL_LEDGER

        hbm = GLOBAL_LEDGER.snapshot()
        with self._lock:
            ent = self.executors.setdefault("driver", {})
            ent["hbm_bytes"] = hbm["bytes"]
            ent["hbm_peak"] = hbm["peak"]
            ent["at"] = time.time()
        with self._lock:
            self._version += 1
            t = self._task(qid, "local", 0)
            if t["done"]:
                self.late_dropped += 1
                return
            t["seq"] += 1
            t["executor"] = "driver"
            t["last_heartbeat"] = time.time()
            t["partials"] += 1
            self.partials_seen += 1
            t["rows"], t["batches"], t["launches"] = rows, batches, launches
            t["op_records"] = op_records
            if open_spans is not None:
                t["open_spans"] = open_spans

    def task_finished(self, qid: str | None, stage: str, task,
                      final: dict | None, rows: int | None = None,
                      executor: str | None = None,
                      started: float | None = None) -> None:
        """The task's RETURN record supersedes every partial: counters
        are replaced with the exact task-end values (parked masks were
        resolved on the worker after the last dispatch), the task is
        closed to further heartbeats, and the reconciliation verdict
        (did the last partial already agree?) is recorded.

        `started` is the scheduler's launch time for the task: a fast
        task may finish before its first heartbeat ever creates the
        entry, and without the true start its duration would collapse to
        ~0 and its completed-peer rate would explode — inflating the
        straggler bar for every sibling still running. `executor` is the
        WINNING copy under speculation: reconciliation compares the
        final rows against that copy's own partials, not whichever
        copy last touched the display."""
        if qid is None:
            return
        now = time.time()
        with self._lock:
            self._version += 1
            if stage in self._query(qid)["abandoned"]:
                return  # the attempt failed; its final record is moot
            t = self._task(qid, stage, task)
            if started is not None and started < t["first_seen"]:
                t["first_seen"] = started
            had_partials = t["partials"]
            if executor is not None and t["rows_by"]:
                partial_rows = t["rows_by"].get(executor, 0)
            else:
                partial_rows = t["rows"]
            if executor is not None:
                t["executor"] = executor
            t["done"] = True
            t["duration"] = now - t["first_seen"]
            t["last_heartbeat"] = now
            t["open_spans"] = []
            if final is not None:
                recs = final.get("op_records") or {}
                t["op_records"] = recs
                t["rows"] = sum(e.get("rows", 0) for e in recs.values())
                t["rows_exact"] = all(e.get("rows_exact", True)
                                      for e in recs.values())
                t["batches"] = sum(e.get("batches", 0)
                                   for e in recs.values())
                t["launches"] = final.get("kernel_launches",
                                          t["launches"])
                t["compile_ms"] = final.get("kernel_compile_ms",
                                            t["compile_ms"])
                if final.get("kernel_kinds") is not None:
                    t["kernel_kinds"] = dict(final["kernel_kinds"])
            elif rows is not None:
                t["rows"] = rows
            # exact reconciliation only claimable when partials arrived
            # and the final record agrees with (or extends) them
            # monotonically — partial rows can never exceed the final
            t["reconciled"] = (had_partials > 0
                               and partial_rows <= t["rows"])

    def query_finished(self, qid: str | None) -> None:
        if qid is None:
            return
        with self._lock:
            self._version += 1
            q = self._queries.get(qid)
            if q is not None:
                q["done"] = True

    def executor_excluded(self, eid: str, until: float | None,
                          failures: int) -> None:
        """Stamp excludeOnFailure state onto the executor's live row
        (ClusterDAGScheduler hooks this to the HealthTracker): console
        executor rows and the live UI render EXCLUDED until the timed
        re-inclusion horizon passes."""
        with self._lock:
            ent = self.executors.setdefault(eid, {})
            ent["excluded_until"] = until if until is not None \
                else float("inf")
            ent["failures"] = failures
            ent.setdefault("at", time.time())

    def host_excluded(self, host: str, until: float | None,
                      eids: list) -> None:
        """Stamp a host-granular exclusion (every executor on the host
        tripped the failure window — HealthTracker escalated to the box):
        live status shows the host row beside the member executors' own
        EXCLUDED rows until the synchronized re-inclusion horizon."""
        with self._lock:
            self.hosts[host] = {
                "excluded_until": until if until is not None
                else float("inf"),
                "executors": list(eids),
                "at": time.time()}

    def add_finding(self, qid: str | None, finding: dict) -> None:
        """Append a non-straggler finding (executor exclusion, tier
        degradation, ...) to the query's finding list — the same list
        EXPLAIN ANALYZE and live status already surface."""
        if qid is None:
            return
        with self._lock:
            self._version += 1
            self._query(qid)["findings"].append(finding)
        sink = self.finding_sink
        if sink is not None:
            try:
                sink(qid, finding)     # outside _lock: the sink may read
            except Exception:          # back through this store
                self.telemetry_errors += 1

    def stage_abandoned(self, qid: str | None, stage: str) -> None:
        """A failed stage attempt retries under a NEW shuffle id (the
        attempt number is part of the sid); the abandoned attempt's task
        entries would otherwise sit done=False forever and trip the
        heartbeat-silence deadline for the rest of the query — a
        permanently-truthy straggler signal. Drop them (the retry
        supersedes their partials). Findings already raised stay: a
        straggler flagged on the failed attempt is historical truth
        EXPLAIN ANALYZE should still report."""
        if qid is None:
            return
        with self._lock:
            self._version += 1
            q = self._queries.get(qid)
            if q is None:
                return
            q["stages"].pop(stage, None)
            q["abandoned"].add(stage)  # late heartbeats must not revive
            q["flagged"] = {k for k in q["flagged"] if k[0] != stage}

    # -- straggler detection ----------------------------------------------
    def _rate_weights(self) -> tuple:
        """(rows, batches, launches) weights of the progress-rate unit
        (spark.tpu.straggler.rateWeights — PR 6's equal weighting stays
        the default; cost-skewed stages tune it instead of false-
        flagging). Parsed once per distinct conf string (the scan loop
        is too hot for a parse per task)."""
        raw = str(self._cfg(STRAGGLER_RATE_WEIGHTS, "1,1,1"))
        memo = getattr(self, "_rate_weights_memo", None)
        if memo is not None and memo[0] == raw:
            return memo[1]
        try:
            parts = [float(p) for p in raw.split(",")]
            weights = tuple((parts + [0.0, 0.0, 0.0])[:3])
            if all(w == 0 for w in weights):
                weights = (1.0, 1.0, 1.0)
        except Exception:
            weights = (1.0, 1.0, 1.0)
        self._rate_weights_memo = (raw, weights)
        return weights

    def _units(self, t: dict) -> float:
        wr, wb, wl = self._rate_weights()
        return (wr * t["rows"] + wb * t["batches"]
                + wl * t["launches"])

    def check_stragglers(self, now: float | None = None) -> list[dict]:
        """Scan running stages for straggling tasks; newly-flagged
        tasks append a finding (kept for the life of the query, so
        EXPLAIN ANALYZE sees flags raised mid-run). Returns the
        CURRENTLY-active straggler findings."""
        if not self._cfg(STRAGGLER_ENABLED, True):
            return []
        frac = self._cfg(STRAGGLER_RATE_FRACTION, 0.2)
        min_s = self._cfg(STRAGGLER_MIN_SECONDS, 1.0)
        deadline = self._cfg(STRAGGLER_HEARTBEAT_DEADLINE, 30.0)
        now = time.time() if now is None else now
        # verdicts also flip with the CLOCK (silence, elapsed>minSeconds)
        # — the reuse window must stay well under those thresholds
        ttl = min(0.25, deadline / 4.0, max(min_s, 0.01) / 4.0)
        with self._lock:
            ver, at, cached = self._scan_cache
            if ver == self._version and now - at < ttl:
                return list(cached)
        active: list[dict] = []
        with self._lock:
            for qid, q in self._queries.items():
                if q["done"]:
                    continue
                for stage, st in q["stages"].items():
                    tasks = st["tasks"]

                    def rate(t):
                        el = t["duration"] if t["done"] \
                            else now - t["first_seen"]
                        return self._units(t) / max(el, 1e-6)

                    # reference discipline (TaskSetManager
                    # checkSpeculatableTasks): completed peers set the
                    # bar; before any completes, the stage-wide median
                    # does (equal-progress peers keep ratio ≈ 1)
                    done_rates = sorted(rate(t) for t in tasks.values()
                                        if t["done"])
                    base = done_rates or sorted(rate(t)
                                                for t in tasks.values())
                    median = base[len(base) // 2] if base else 0.0
                    for task, t in tasks.items():
                        if t["done"]:
                            continue
                        elapsed = now - t["first_seen"]
                        silent = now - t["last_heartbeat"] > deadline
                        slow = (len(tasks) >= 2 and elapsed > min_s
                                and median > 0.0
                                and self._units(t) / max(elapsed, 1e-6)
                                < frac * median)
                        if not (silent or slow):
                            continue
                        why = ("telemetry silent "
                               f"{now - t['last_heartbeat']:.1f}s > "
                               f"{deadline:.1f}s deadline" if silent else
                               f"progress rate under {frac:.0%} of the "
                               f"stage median after {elapsed:.1f}s")
                        finding = {
                            "severity": "warning", "kind": "obs.straggler",
                            "query": qid, "stage": stage, "task": task,
                            "executor": t["executor"],
                            "msg": f"straggler: task {task} of stage "
                                   f"{stage} ({t['executor']}): {why} "
                                   f"(rows so far {t['rows']})"}
                        active.append(finding)
                        key = (stage, task)
                        if key not in q["flagged"]:
                            q["flagged"].add(key)
                            q["findings"].append(finding)
            self._scan_cache = (self._version, now, list(active))
        return active

    def active_stragglers(self) -> list[tuple]:
        """(query, stage, task) keys of currently-straggling tasks —
        the signal hook the speculative-execution path consumes
        (LocalCluster.speculation_signal): a flagged straggler launches
        the backup copy without waiting out the duration-history
        threshold."""
        return [(f["query"], f["stage"], f["task"])
                for f in self.check_stragglers()]

    def findings_for(self, qid: str | None) -> list[dict]:
        """Straggler findings raised during one query (live OR already
        finished — EXPLAIN ANALYZE reads this after the measured run)."""
        self.check_stragglers()
        if qid is None:
            return []
        with self._lock:
            q = self._queries.get(qid)
            return list(q["findings"]) if q is not None else []

    def recent_findings(self, qids, limit: int = 8) -> list[dict]:
        """Newest findings across a set of query ids — the per-pool SLO
        view the serving status renders (stragglers, regressions,
        exclusions raised for the queries a fair-scheduler pool
        admitted). Pure host bookkeeping."""
        self.check_stragglers()
        out: list[dict] = []
        with self._lock:
            for qid in qids:
                q = self._queries.get(qid)
                if q is not None:
                    out.extend(q["findings"])
        return out[-max(int(limit), 0):]

    # -- reads ------------------------------------------------------------
    def query_progress(self, qid: str) -> dict | None:
        """In-flight progress of one query: per stage, tasks done/total,
        rows/batches/launches so far, per-task last-heartbeat age."""
        now = time.time()
        with self._lock:
            q = self._queries.get(qid)
            if q is None:
                return None
            stages = {}
            for stage, st in q["stages"].items():
                tasks = st["tasks"]
                stages[stage] = {
                    "tasks_total": len(tasks),
                    "tasks_done": sum(1 for t in tasks.values()
                                      if t["done"]),
                    "rows": sum(t["rows"] for t in tasks.values()),
                    "rows_exact": all(t["rows_exact"]
                                      for t in tasks.values()),
                    "batches": sum(t["batches"] for t in tasks.values()),
                    "launches": sum(t["launches"]
                                    for t in tasks.values()),
                    "kernel_kinds": _sum_kinds(
                        t["kernel_kinds"] for t in tasks.values()),
                    "partials": sum(t["partials"]
                                    for t in tasks.values()),
                    "tasks": {
                        task: {"executor": t["executor"],
                               "rows": t["rows"], "batches": t["batches"],
                               "launches": t["launches"],
                               "done": t["done"],
                               "partials": t["partials"],
                               "reconciled": t["reconciled"],
                               "open_spans": list(t["open_spans"]),
                               "heartbeat_age_s": round(
                                   now - t["last_heartbeat"], 3)}
                        for task, t in tasks.items()},
                }
            return {"done": q["done"], "stages": stages,
                    "findings": list(q["findings"])}

    def task_record(self, qid: str, stage: str, task) -> dict | None:
        with self._lock:
            q = self._queries.get(qid)
            if q is None:
                return None
            st = q["stages"].get(stage)
            if st is None:
                return None
            t = st["tasks"].get(task)
            return dict(t) if t is not None else None

    def executor_utilization(self) -> dict:
        """Per-executor live utilization: progress rate of the RUNNING
        tasks it owns (rows+batches+launches per second — the straggler
        detector's unit) plus its latest heartbeat-shipped HBM occupancy
        and flush-budget overflow count. Feeds the console reporter's
        per-executor rows and the live UI."""
        now = time.time()
        with self._lock:
            out = {eid: {"rows": 0, "rate": 0.0, "tasks": 0,
                         "hbm_bytes": e.get("hbm_bytes"),
                         "hbm_peak": e.get("hbm_peak"),
                         "overflows": e.get("overflows", 0),
                         "excluded": e.get("excluded_until", 0) > now,
                         "failures": e.get("failures", 0)}
                   for eid, e in self.executors.items()}
            for q in self._queries.values():
                if q["done"]:
                    continue
                for st in q["stages"].values():
                    for t in st["tasks"].values():
                        if t["done"] or t["executor"] is None:
                            continue
                        e = out.setdefault(
                            t["executor"],
                            {"rows": 0, "rate": 0.0, "tasks": 0,
                             "hbm_bytes": None, "hbm_peak": None,
                             "overflows": 0, "excluded": False,
                             "failures": 0})
                        e["tasks"] += 1
                        e["rows"] += t["rows"]
                        e["rate"] += self._units(t) / max(
                            now - t["first_seen"], 1e-6)
        return out

    def flush_overflow_total(self) -> int:
        with self._lock:
            return sum(e.get("overflows", 0)
                       for e in self.executors.values())

    def snapshot(self) -> dict:
        """Whole-store view for the live UI: running queries with stage
        progress, straggler findings, merge-discipline counters, and
        per-executor utilization/HBM rows."""
        with self._lock:
            qids = [qid for qid, q in self._queries.items()
                    if not q["done"]]
            finished = len(self._queries) - len(qids)
        now = time.time()
        with self._lock:
            excluded_hosts = {
                h: {"until": e["excluded_until"],
                    "executors": list(e.get("executors", []))}
                for h, e in self.hosts.items()
                if e.get("excluded_until", 0) > now}
        out = {"running": {}, "finished_queries": finished,
               "partials_seen": self.partials_seen,
               "late_dropped": self.late_dropped,
               "evictions": self.evictions,
               "telemetry_errors": self.telemetry_errors,
               "stragglers": self.check_stragglers(),
               "executors": self.executor_utilization(),
               "excluded_hosts": excluded_hosts,
               "flush_overflows": self.flush_overflow_total()}
        for qid in qids:
            p = self.query_progress(qid)
            if p is not None:
                out["running"][qid] = p
        return out


def _sum_kinds(dicts) -> dict:
    out: dict = {}
    for d in dicts:
        for k, v in (d or {}).items():
            out[k] = out.get(k, 0) + v
    return out


# ---------------------------------------------------------------------------
# Local-mode flush thread (driver-side sampler)
# ---------------------------------------------------------------------------

def start_query_flusher(live: LiveObs, ctx, interval: float = 0.25):
    """Periodically publish the running query's driver-side plan_metrics
    into the live store so LOCAL stages get the same in-flight feed
    cluster tasks stream over heartbeats. The loop is handed to its
    thread through obs.metrics.scoped_submit: the flush thread runs in a
    COPY of the caller's contextvars context, so current_query() inside
    the loop resolves to the query being collected (a bare thread starts
    with an empty context and would publish untagged samples). Samples
    read host counters only — parked masks are never resolved here.

    Returns a zero-argument stop() that joins the flusher."""
    from concurrent.futures import ThreadPoolExecutor

    from .metrics import export_op_records_partial, scoped_submit
    from .tracing import current_query

    stop_event = threading.Event()
    pool = ThreadPoolExecutor(1, thread_name_prefix="obs-flush")

    def loop():
        qid = current_query()
        while not stop_event.wait(interval):
            live.local_update(qid,
                              export_op_records_partial(ctx.plan_metrics))
        # final sample so short queries still register one partial
        live.local_update(qid,
                          export_op_records_partial(ctx.plan_metrics))

    fut = scoped_submit(pool, loop)

    def stop():
        stop_event.set()
        try:
            fut.result(timeout=10)
        except Exception:
            pass
        pool.shutdown(wait=False)

    return stop


# ---------------------------------------------------------------------------
# Console progress (ConsoleProgressBar role)
# ---------------------------------------------------------------------------

class ConsoleProgressReporter:
    """Renders live stage bars to a terminal from the LiveObs store
    (reference: core/ui/ConsoleProgressBar.scala — a \\r-rewritten
    status line while stages run, cleared when they finish)."""

    BAR = 20

    def __init__(self, live: LiveObs, stream=None,
                 interval: float | None = None, conf=None):
        self.live = live
        self.stream = stream if stream is not None else sys.stderr
        if interval is None:
            interval = PROGRESS_UPDATE_INTERVAL.default if conf is None \
                else float(conf.get(PROGRESS_UPDATE_INTERVAL))
        self.interval = interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._last_len = 0

    def start(self) -> "ConsoleProgressReporter":
        # race-lint: ignore[bare-submit] — console repaint loop: renders
        # EVERY live query from the registry, must not pin one scope
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="spark-tpu-progress")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._clear()

    # ------------------------------------------------------------------
    def render_line(self) -> str:
        """One status line over every running query's stages, followed
        by per-executor utilization rows (running tasks, progress rate,
        live HBM occupancy from the device ledger — streamed on the
        heartbeat for workers, read directly for the driver)."""
        snap = self.live.snapshot()
        parts = []
        for qid, q in snap["running"].items():
            for stage, st in q["stages"].items():
                total = max(st["tasks_total"], 1)
                done = st["tasks_done"]
                fill = int(self.BAR * done / total)
                bar = "=" * fill + ">" * (1 if fill < self.BAR else 0)
                extra = ""
                flagged = [f for f in q["findings"]
                           if f["stage"] == stage]
                if flagged:
                    extra = f" STRAGGLERS={len(flagged)}"
                parts.append(
                    f"[{qid[:8]} {stage}] {done}/{total} tasks "
                    f"[{bar:<{self.BAR}}] rows={st['rows']} "
                    f"launches={st['launches']}{extra}")
        if parts:
            from .metrics import _fmt_bytes

            for eid, e in sorted(snap.get("executors", {}).items()):
                seg = f"{eid}: {e['tasks']} task" \
                      f"{'s' if e['tasks'] != 1 else ''}"
                if e["rate"]:
                    seg += f" {e['rate']:.0f}/s"
                if e.get("hbm_bytes") is not None:
                    seg += f" hbm={_fmt_bytes(e['hbm_bytes'])}"
                if e.get("overflows"):
                    seg += f" obs-trims={e['overflows']}"
                if e.get("excluded"):
                    seg += f" EXCLUDED({e.get('failures', 0)} fails)"
                parts.append(f"<{seg}>")
        return "  ".join(parts)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            line = self.render_line()
            if line:
                pad = max(self._last_len - len(line), 0)
                try:
                    self.stream.write("\r" + line + " " * pad)
                    self.stream.flush()
                except Exception:
                    return
                self._last_len = len(line)
            elif self._last_len:
                self._clear()

    def _clear(self) -> None:
        if self._last_len:
            try:
                self.stream.write("\r" + " " * self._last_len + "\r")
                self.stream.flush()
            except Exception:
                pass
            self._last_len = 0
