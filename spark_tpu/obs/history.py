"""Query flight recorder: plan fingerprints, persistent run profiles,
and deterministic perf-regression detection.

Role of the reference's SQLAppStatusStore + event-log-based history
(sqlx/execution/ui/SQLAppStatusStore.scala, the history server's replay
of per-execution metrics), re-keyed for an engine whose dominant costs
are COMPILES and DISPATCHES rather than task wall-time: every byte of
in-process observability PRs 3/4/6/7 built (attributed traces, live
telemetry, HBM/roofline accounting) dies with the process, so nothing
identifies "the same query" across runs — a restarted server cannot
know a compile is cold (Flare's central lesson: native compilation
makes compile cost the dominant latency tax), and the bench trajectory
cannot gate on the counters we predict exactly.

Four legs, all under the obs contract (ZERO kernel launches, no
mid-query device syncs — every input is host-side metadata the obs
layer already holds, and assembly runs at query close, after the
query's last device interaction):

  * **Plan fingerprinting** — two canonical structural hashes per query.
    `plan_fingerprint` hashes the executed physical plan (operator tree
    with expression/literal detail, input schemas + leaf row counts,
    capacity/partition shape, tier-relevant config) with per-stage
    sub-fingerprints cut at exchange boundaries: the exact key a
    persistent compile cache / result cache reuses (ROADMAP direction
    1 — same plan, same signatures, same tier ⇒ same programs).
    `query_key` hashes the optimized LOGICAL plan + workload-shape
    config only, deliberately EXCLUDING execution strategy (compile
    tier, fusion, encoding): it identifies "the same query" across
    strategy changes, so a tier flip — or a code change that flips the
    tier chooser — lands on the same baseline and surfaces as counter
    drift instead of vanishing under a fresh fingerprint.

  * **QueryProfile** — one JSON record assembled at query close from
    stores that already exist: per-operator metric records, per-kind
    kernel launch/compile deltas (driver + shipped worker totals in
    cluster mode), the tier decision incl. fallback/degrade reasons,
    retry/fault/exclusion counters, straggler/degrade/wasted-work
    findings, HBM watermarks (device ledger + captured XLA temp
    scratch), and per-stage runtime output stats (rows, key spans from
    shuffle col stats, dictionary-domain cardinalities) — the carrier
    ROADMAP direction 3's runtime re-admission reads.

  * **ProfileStore** — append-only JSONL under
    `spark.tpu.obs.profileDir`, one file per structural query key, each
    line one profile stamped with its full fingerprint. Appends are
    process-safe (flock) and the file is a bounded ring
    (`spark.tpu.obs.profileRing`): once it doubles the bound it
    compacts to the newest N. The driver owns all writes — worker
    processes never touch the store.

  * **Regression detection** — at query close the fresh profile
    compares against the MEDIAN of the last N stored profiles for the
    same query key. Deterministic counters (kernel launches by kind,
    compile count, retry/fault attempts) raise severity-`error`
    `obs.regression` findings when they EXCEED the baseline (cold→warm
    improvements never fire); wall-clock and HBM drift raise advisory
    `info` findings (noisy on a shared box — never an error). Findings
    land in the live store, so EXPLAIN ANALYZE and live status surface
    them; `dev/perfcheck.py` runs the same comparison across commits
    against a committed baseline.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import statistics
import time

__all__ = ["DETERMINISTIC_COUNTERS", "ProfileStore", "build_profile",
           "close_query_profile", "detect_regressions", "plan_fingerprint",
           "query_key"]


# Concurrency note (PR 15, supersedes the PR 12 overlap guard): profile
# deltas are no longer process-snapshot differences. Kernel events come
# from the per-query QueryKernelLedger (obs/metrics.py, carried by a
# contextvar through par_map lanes and scoped_submit pools) and counter
# deltas from ExecContext's ScopedMetrics, so two queries collecting
# concurrently on one process read DISJOINT, exact deltas. Profiles
# recorded under load are therefore baseline-eligible and
# regression-checked like any other — the `overlapped` mark and its
# guard are gone.


# ---------------------------------------------------------------------------
# canonicalization
# ---------------------------------------------------------------------------

# volatile tokens that must not reach a cross-process fingerprint:
# expression ids (#12 and the bare `ids=(0, 1)` tuples logical leaves
# print — both allocated from a per-process counter), memory addresses
# (0x7f..., "at 0x..."), and long hex ids (uuids in shuffle/cache
# names). The hex-id rule requires at least one [a-f]: a pure-decimal
# 12+ digit literal (epoch millis in a WHERE clause) is query IDENTITY
# and must stay in the hash, or two such queries would collide.
_VOLATILE = re.compile(
    r"#\d+|\bids=\([0-9,\s]*\)|0x[0-9a-fA-F]+"
    r"|\b(?=[0-9a-f]*[a-f])[0-9a-f]{12,}\b")


def _sanitize(s: str) -> str:
    return _VOLATILE.sub("#", " ".join(str(s).split()))


def _hash(s: str) -> str:
    return hashlib.sha256(s.encode("utf-8", "replace")).hexdigest()[:16]


def _node_detail(node) -> str:
    d = node.simple_string() if hasattr(node, "simple_string") \
        else type(node).__name__
    return _sanitize(d)[:200]


def _node_schema(node) -> tuple:
    try:
        return tuple((a.name, str(a.dtype), bool(a.nullable))
                     for a in node.output)
    except Exception:
        return ()


def _leaf_rows(node):
    """Exact plan-time leaf row count when known (the same statistics the
    tier chooser reads) — part of the fingerprint's 'input capacities'."""
    from ..physical.whole_query import _leaf_rows as lr

    try:
        return lr(node)
    except Exception:
        return None


def _canon_children(node) -> list:
    """A node's structural children INCLUDING through the whole-query
    wrapper (child_fields=() makes its inner plan invisible to the plan
    walkers, but two different queries wrapped whole must not collide)."""
    kids = list(node.children)
    inner = getattr(node, "plan", None)
    if not kids and inner is not None and hasattr(inner, "children"):
        kids = [inner]
    return kids


def _tier_conf(conf) -> list:
    """Execution-strategy config that changes WHICH programs compile —
    part of the full fingerprint (a compile cache keyed without these
    would serve a stage-tier program to a whole-tier session)."""
    from ..config import (
        AGG_BLOCK_ROWS, BATCH_CAPACITY, COMPILE_TIER, ENCODING_ENABLED,
        FUSION_DENSE_KEYS, FUSION_ENABLED, FUSION_EXCHANGE, FUSION_MESH,
        FUSION_MIN_ROWS, MESH_ENABLED, SHUFFLE_PARTITIONS, WHOLE_MIN_ROWS,
    )

    entries = (COMPILE_TIER, FUSION_ENABLED, FUSION_EXCHANGE, FUSION_MESH,
               FUSION_MIN_ROWS, FUSION_DENSE_KEYS, WHOLE_MIN_ROWS,
               ENCODING_ENABLED, MESH_ENABLED, BATCH_CAPACITY,
               SHUFFLE_PARTITIONS, AGG_BLOCK_ROWS)
    return [(e.key, str(conf.get(e))) for e in entries]


def _workload_conf(conf) -> list:
    """Workload-SHAPE config (tile capacity, partition fan-out) — part
    of the structural query key: changing these changes how much work
    the same query is, so profiles across them must not compare. The
    strategy knobs (tier/fusion/encoding) are deliberately excluded —
    see module docstring."""
    from ..config import BATCH_CAPACITY, SHUFFLE_PARTITIONS

    return [(e.key, str(conf.get(e)))
            for e in (BATCH_CAPACITY, SHUFFLE_PARTITIONS)]


def plan_fingerprint(physical, conf) -> dict:
    """Canonical structural hash of an executed physical plan, with
    per-stage sub-fingerprints cut at exchange boundaries (the stage =
    the compile unit, so the sub-fingerprint is the per-stage compile
    cache key). Pure host work over plan metadata — memoized on the
    plan root keyed by the tier-relevant conf (the persistent-cache
    paths fingerprint the same plan several times per query: the
    result-cache probe, the manifest seed lookup, plan_lint's mirrors,
    and the close-time profile)."""
    from ..physical.exchange import (
        BroadcastExchangeExec, ShuffleExchangeExec,
    )

    memo_key = json.dumps(_tier_conf(conf), sort_keys=True)
    memo = getattr(physical, "_fp_memo", None)
    if memo is not None and memo[0] == memo_key:
        return memo[1]

    stages: list[dict] = []
    leaves: list[tuple] = []

    def canon(node) -> str:
        parts = [type(node).__name__, _node_detail(node),
                 repr(_node_schema(node))]
        kids = _canon_children(node)
        if not kids:
            rows = _leaf_rows(node)
            leaves.append((type(node).__name__, _node_schema(node), rows))
            parts.append(f"rows={rows}")
        for c in kids:
            if isinstance(c, (ShuffleExchangeExec, BroadcastExchangeExec)):
                parts.append(f"<stage:{canon_stage(c)}>")
            else:
                parts.append(canon(c))
        return "(" + "|".join(parts) + ")"

    def canon_stage(root) -> str:
        s = canon(root)
        fp = _hash(s)
        stages.append({"op": type(root).__name__,
                       "detail": _node_detail(root)[:120],
                       "fingerprint": fp})
        return fp

    root = canon_stage(physical)
    full = _hash(json.dumps(
        {"root": root, "stages": [s["fingerprint"] for s in stages],
         "conf": _tier_conf(conf)}, sort_keys=True))
    out = {"fingerprint": full, "root_stage": root,
           "stages": list(reversed(stages)),  # produce->consume order
           "leaves": [{"op": op, "schema": list(map(list, sch)),
                       "rows": rows} for op, sch, rows in leaves]}
    try:
        physical._fp_memo = (memo_key, out)
    except Exception:
        pass  # slotted/frozen plan node: skip the memo
    return out


def query_key(optimized_logical, conf) -> str:
    """Structural identity of 'the same query' across execution-strategy
    changes: the optimized logical plan (tier/fusion/encoding are
    physical concerns and never appear in it) plus workload-shape
    config. The regression baseline is keyed by this."""
    try:
        tree = optimized_logical.tree_string()
    except Exception:
        tree = repr(type(optimized_logical).__name__)
    return _hash(json.dumps(
        {"plan": _sanitize(tree), "conf": _workload_conf(conf)},
        sort_keys=True))


# ---------------------------------------------------------------------------
# QueryProfile assembly
# ---------------------------------------------------------------------------

# per-query counter deltas whose values are DETERMINISTIC given the plan
# and the fault schedule — exact equality is gated on, so anything noisy
# (wall, bytes) must never appear here
DETERMINISTIC_COUNTERS = (
    "join.capacity_retry",
    "whole_query.capacity_retries",
    "whole_query.runtime_degraded",
    "scheduler.stage_retries",
    "scheduler.fetch_failures",
    "scheduler.task_failures_salvaged",
    "shuffle.fetch_retries",
)

# counter-delta prefixes worth persisting beyond the deterministic set
# (profile forensics: what did this run actually do). "compile." and
# "result_cache." carry the persistent-cache attribution (PR 14):
# disk-served vs true cold XLA compiles, result-cache hit/miss/store.
_COUNTER_PREFIXES = ("scheduler.", "shuffle.", "join.", "whole_query.",
                     "adaptive.", "cache.", "mesh.", "compile.",
                     "result_cache.")

_MAX_PROFILE_NODES = 64
_MAX_PROFILE_FINDINGS = 16
_MAX_WASTED = 8


def _tier_section(physical) -> dict | None:
    dec = getattr(physical, "decision", None) \
        or getattr(physical, "_tier_decision", None)
    if dec is None:
        return None
    out = dec.to_dict() if hasattr(dec, "to_dict") else dict(dec)
    if getattr(physical, "_degraded", False):
        out["degraded"] = True
    return out


def _stage_stats(physical, rec: dict | None) -> list:
    """Per-stage runtime OUTPUT statistics from host-side stores that
    already exist: rows/batches from the operator records, key spans
    from the shuffle write's accumulated column stats, and
    dictionary-domain cardinalities from leaf arrow schemas — the
    runtime-readmission carrier (ROADMAP direction 3)."""
    from ..physical.exchange import ShuffleExchangeExec
    from .metrics import metric_key

    rec = rec or {}
    out = []
    for node in physical.iter_nodes():
        ent = rec.get(metric_key(node)) or {}
        st = {"op": type(node).__name__,
              "detail": _node_detail(node)[:120]}
        if ent:
            st["rows"] = ent.get("rows")
            st["batches"] = ent.get("batches")
        if isinstance(node, ShuffleExchangeExec):
            spans: dict = {}
            for cols in (getattr(node, "last_col_stats", None) or
                         {}).values():
                for ci, (lo, hi, any_v) in cols.items():
                    if not any_v:
                        continue
                    cur = spans.get(ci)
                    spans[ci] = (min(cur[0], lo), max(cur[1], hi)) \
                        if cur else (lo, hi)
            if spans:
                st["key_spans"] = {str(ci): [int(lo), int(hi)]
                                   for ci, (lo, hi) in sorted(spans.items())}
        if not node.children:
            doms = _dict_domains(node)
            if doms:
                st["dict_domains"] = doms
        if len(st) > 2:  # only stages that contributed a runtime stat
            out.append(st)
        if len(out) >= _MAX_PROFILE_NODES:
            break
    return out


def _dict_domains(leaf) -> dict:
    """Dictionary-domain cardinality per dictionary-typed leaf column
    (arrow schema metadata only — never touches column values)."""
    import pyarrow as pa

    t = getattr(leaf, "table", None)
    if t is None:
        from ..physical.whole_query import _scan_table

        t = _scan_table(leaf)
    if not isinstance(t, pa.Table):
        return {}
    out = {}
    for i, f in enumerate(t.schema):
        if pa.types.is_dictionary(f.type):
            try:
                chunk = t.column(i).chunk(0)
                out[f.name] = int(len(chunk.dictionary))
            except Exception:
                pass
    return out


def _xla_temp_peak(kinds: dict) -> int | None:
    """Peak XLA temp (scratch) bytes among the kernel kinds this query
    launched, from the cost table's memory_analysis capture
    (spark.tpu.metrics.kernelMemory). Scratch is live only inside one
    kernel, so the concurrent peak is the max, not the sum."""
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    peak = None
    for kind in kinds:
        ent = KC.cost_by_kind.get(kind)
        tb = (ent or {}).get("temp_bytes")
        if tb:
            peak = max(peak or 0, int(tb))
    return peak


def build_profile(qe, ctx, fingerprint: dict, qkey: str, wall_s: float,
                  kinds: dict, counter_deltas: dict, compiles: int,
                  compile_ms: float, compiles_disk_hit: int = 0) -> dict:
    """One QueryProfile record from the close-time state. Everything
    here is host metadata; caps keep a line small enough that the ring
    file stays cheap to compact."""
    from .metrics import iter_plan_metrics
    from .resources import GLOBAL_LEDGER

    physical = qe.physical
    rec = getattr(ctx, "plan_metrics", None)
    ops = []
    if rec:
        for node, depth, key, fields in iter_plan_metrics(physical, rec):
            ops.append({"id": key, "depth": depth,
                        "op": type(node).__name__,
                        "detail": _node_detail(node)[:120],
                        "rows": fields["rows"],
                        "batches": fields["batches"],
                        "ms": fields["ms"],
                        "launches": fields["launches"],
                        "compile_ms": fields["compile_ms"]})
            if len(ops) >= _MAX_PROFILE_NODES:
                break
    counters = {k: v for k, v in counter_deltas.items()
                if v and (k in DETERMINISTIC_COUNTERS
                          or k.startswith(_COUNTER_PREFIXES))}
    hbm: dict = {}
    res = GLOBAL_LEDGER.query_record(getattr(ctx, "query_id", None))
    if res is not None:
        hbm["peak"] = res.get("peak")
        if res.get("remote"):
            hbm["remote"] = {e: v.get("peak")
                             for e, v in res["remote"].items()}
    temp = _xla_temp_peak(kinds)
    if temp is not None:
        hbm["xla_temp_peak"] = temp
    live = getattr(ctx, "live_obs", None)
    findings = []
    if live is not None:
        findings = [
            {"severity": f.get("severity"), "kind": f.get("kind"),
             "msg": str(f.get("msg"))[:200]}
            for f in live.findings_for(getattr(ctx, "query_id", None))
        ][:_MAX_PROFILE_FINDINGS]
    wasted = [
        {k: w.get(k) for k in ("stage", "task", "executor", "error",
                               "kernel_kinds", "launches", "compile_ms",
                               "spans")}
        for w in (getattr(ctx, "failed_attempt_obs", None) or
                  [])][:_MAX_WASTED]
    profile = {
        "v": 1,
        "fingerprint": fingerprint["fingerprint"],
        "query_key": qkey,
        "stages": fingerprint["stages"],
        "ts": round(time.time(), 3),
        "query_id": getattr(ctx, "query_id", None),
        "detail": _node_detail(physical)[:140],
        "cluster": getattr(qe.session, "_sql_cluster", None) is not None,
        "wall_ms": round(wall_s * 1000, 3),
        "phases": {k: round(v * 1000, 3)
                   for k, v in qe.phase_times.items()},
        "tier": _tier_section(physical),
        "launches_by_kind": {k: int(v) for k, v in sorted(kinds.items())},
        "launch_total": int(sum(kinds.values())),
        "compiles": int(compiles),
        # engine compiles whose XLA backend compile was served from the
        # persistent disk cache (exec/persist_cache.py): a warm restart
        # shows compiles == compiles_disk_hit (zero TRUE cold compiles);
        # the per-query compile.disk_hit/miss deltas ride `counters`
        "compiles_disk_hit": int(compiles_disk_hit),
        "compile_ms": round(compile_ms, 3),
        "counters": counters,
        "ops": ops,
        "stage_stats": _stage_stats(physical, rec),
        "hbm": hbm,
    }
    if wasted:
        profile["wasted"] = wasted
    if findings:
        profile["findings"] = findings
    return profile


# ---------------------------------------------------------------------------
# persistent store
# ---------------------------------------------------------------------------

class ProfileStore:
    """Append-only JSONL store, one bounded ring file per query key.

    Writes are driver-only and process-safe; the flock-sidecar +
    ring-compaction mechanics live in the shared utils/diskstore.
    JsonlRing (one locking implementation for every on-disk metadata
    store — the persistent-cache manifest reuses it). Readers
    (HistoryReader-style APIs below, the history-server profiles page,
    dev/perfcheck.py) take no lock: JSONL lines are self-delimiting and
    a torn tail line is skipped."""

    def __init__(self, root: str, ring: int = 32):
        self.root = root
        self.ring = max(int(ring), 1)
        os.makedirs(root, exist_ok=True)

    def _path(self, qkey: str) -> str:
        safe = re.sub(r"[^0-9a-zA-Z_-]", "_", qkey)
        return os.path.join(self.root, f"{safe}.jsonl")

    def _ring(self, path: str):
        from ..utils.diskstore import JsonlRing

        return JsonlRing(path, ring=self.ring)

    def append(self, profile: dict) -> None:
        self._ring(self._path(profile["query_key"])).append(profile)

    # -- reads (no lock: lines are self-delimiting) ------------------------
    def _load(self, path: str) -> list[dict]:
        return self._ring(path).load()

    def query_keys(self) -> list[str]:
        keys = []
        for name in sorted(os.listdir(self.root)):
            if name.endswith(".jsonl"):
                keys.append(name[:-len(".jsonl")])
        return keys

    def profiles(self, qkey: str, last: int | None = None) -> list[dict]:
        """Stored profiles for one query key, oldest first."""
        out = self._load(self._path(qkey))
        return out[-last:] if last else out

    def fingerprints(self) -> dict:
        """{full fingerprint: {query_key, profiles, last_ts, detail}} —
        the application-list shape the history-server profiles page
        renders."""
        out: dict = {}
        for qk in self.query_keys():
            for p in self.profiles(qk):
                fp = p.get("fingerprint")
                ent = out.setdefault(fp, {"query_key": qk, "profiles": 0,
                                          "last_ts": 0.0,
                                          "detail": p.get("detail", "")})
                ent["profiles"] += 1
                ent["last_ts"] = max(ent["last_ts"], p.get("ts") or 0.0)
                ent["detail"] = p.get("detail", ent["detail"])
        return out

    def profiles_for_fingerprint(self, fp: str) -> list[dict]:
        for qk in self.query_keys():
            hits = [p for p in self.profiles(qk)
                    if p.get("fingerprint") == fp]
            if hits:
                return hits
        return []


# ---------------------------------------------------------------------------
# regression detection
# ---------------------------------------------------------------------------

def _median(vals) -> float:
    vals = list(vals)
    return statistics.median(vals) if vals else 0.0


def detect_regressions(fresh: dict, history: list[dict],
                       baseline_n: int = 5,
                       wall_tolerance: float = 1.5,
                       hbm_tolerance: float = 1.25) -> list[dict]:
    """Compare a fresh profile against the median of the last
    `baseline_n` stored profiles for the same query key. Deterministic
    counters fire severity-`error` findings only on INCREASE (a warm
    run re-using compiles/memos legitimately measures below a cold
    baseline); wall/HBM drift is advisory `info`. Profiles recorded
    under concurrent load are baseline-eligible: their deltas are
    scope-exact (per-query kernel ledger + ScopedMetrics), not
    process-snapshot differences. Returns findings in the EXPLAIN
    ANALYZE shape ({severity, kind, msg, ...})."""
    base = history[-baseline_n:] if baseline_n else list(history)
    if not base:
        return []
    n = len(base)
    findings: list[dict] = []

    def err(metric: str, value, baseline) -> None:
        findings.append({
            "severity": "error", "kind": "obs.regression",
            "metric": metric, "value": value, "baseline": baseline,
            "msg": f"deterministic-counter regression vs stored baseline "
                   f"(median of last {n} run(s) of this query): {metric} "
                   f"= {value} > baseline {baseline:g}"})

    # kernel launches by kind — the primary deterministic signal
    kinds = set(fresh.get("launches_by_kind") or {})
    for p in base:
        kinds |= set(p.get("launches_by_kind") or {})
    for kind in sorted(kinds):
        v = (fresh.get("launches_by_kind") or {}).get(kind, 0)
        b = _median((p.get("launches_by_kind") or {}).get(kind, 0)
                    for p in base)
        if v > b:
            err(f"kernel launches '{kind}'", v, b)
    # compile count — more compiles than the baseline means a cache key
    # stopped hitting (warm runs measuring fewer never fire)
    v = fresh.get("compiles", 0)
    b = _median(p.get("compiles", 0) for p in base)
    if v > b:
        err("kernel compiles", v, b)
    # retry / fault attempts
    for key in DETERMINISTIC_COUNTERS:
        v = (fresh.get("counters") or {}).get(key, 0)
        b = _median((p.get("counters") or {}).get(key, 0) for p in base)
        if v > b:
            err(f"counter {key}", v, b)
    # advisory drift: wall and HBM are noisy — info only
    v = fresh.get("wall_ms") or 0.0
    b = _median(p.get("wall_ms") or 0.0 for p in base)
    if b > 1.0 and v > wall_tolerance * b:
        findings.append({
            "severity": "info", "kind": "obs.regression",
            "metric": "wall_ms", "value": v, "baseline": b,
            "msg": f"wall-clock drift (advisory): {v:.1f} ms > "
                   f"{wall_tolerance:g}x baseline median {b:.1f} ms"})
    v = (fresh.get("hbm") or {}).get("peak") or 0
    b = _median((p.get("hbm") or {}).get("peak") or 0 for p in base)
    if b > 0 and v > hbm_tolerance * b:
        findings.append({
            "severity": "info", "kind": "obs.regression",
            "metric": "hbm_peak", "value": v, "baseline": b,
            "msg": f"HBM watermark drift (advisory): {v} B > "
                   f"{hbm_tolerance:g}x baseline median {b:.0f} B"})
    return findings


# ---------------------------------------------------------------------------
# close hook (called by QueryExecution.execute)
# ---------------------------------------------------------------------------

def close_query_profile(qe, ctx, baseline: dict) -> tuple:
    """Assemble, persist, and regression-check one finished query.
    `baseline` holds the recorder's start-of-query snapshots
    (KernelCache kinds/misses/compile-ms, session counters,
    perf_counter t0) taken by QueryExecution when the recorder is on.
    Returns (profile, regression findings); never raises into the
    query path (the caller guards)."""
    from ..config import (
        OBS_PROFILE_BASELINE_N, OBS_PROFILE_DIR, OBS_PROFILE_REGRESSION,
        OBS_PROFILE_RING, OBS_PROFILE_WALL_TOLERANCE,
    )
    from ..physical.compile import GLOBAL_KERNEL_CACHE as KC

    conf = qe.session.conf
    root = str(conf.get(OBS_PROFILE_DIR) or "")  # tpulint: ignore[host-sync]
    if not root:
        return None, []
    wall_s = time.perf_counter() - baseline["t0"]
    ledger = getattr(ctx, "kernel_ledger", None)
    if ledger is not None:
        # scope-exact per-query deltas (obs/metrics.QueryKernelLedger):
        # concurrent collects on one process cannot contaminate them,
        # so profiles recorded under load stay baseline-eligible
        snap = ledger.snapshot()
        kinds = {k: v for k, v in snap["kinds"].items() if v}
        compiles = snap["compiles"]
        compile_ms = snap["compile_ms"]
        compiles_disk_hit = snap["disk_hit_compiles"]
    else:
        # no ledger on the context (direct build callers): fall back to
        # the recorder's process snapshots — exact only when serial
        kinds = {k: v - baseline["kinds"].get(k, 0)
                 for k, v in KC.launches_by_kind.items()
                 if v != baseline["kinds"].get(k, 0)}
        compiles = KC.misses - baseline["misses"]
        compile_ms = KC.compile_ms - baseline["compile_ms"]
        compiles_disk_hit = KC.disk_hit_compiles \
            - baseline.get("disk_hit_compiles", 0)
    # cluster mode: worker-process deltas shipped with the task results
    # fold into the same per-kind ledger (driver + worker totals)
    for k, v in (getattr(ctx, "worker_kernel_kinds", None) or {}).items():
        kinds[k] = kinds.get(k, 0) + v
    scoped = getattr(ctx.metrics, "local_counters", None)
    if scoped is not None:
        counter_deltas = {k: v for k, v in scoped().items() if v}
    else:
        counters = qe.session._metrics.snapshot()["counters"]
        counter_deltas = {k: v - baseline["counters"].get(k, 0)
                          for k, v in counters.items()
                          if v != baseline["counters"].get(k, 0)}
    fingerprint = qe.plan_fingerprint()
    qkey = query_key(qe.optimized, conf)
    profile = build_profile(
        qe, ctx, fingerprint, qkey, wall_s, kinds, counter_deltas,
        compiles=compiles, compile_ms=compile_ms,
        compiles_disk_hit=compiles_disk_hit)
    store = ProfileStore(root, ring=int(  # tpulint: ignore[host-sync]
        conf.get(OBS_PROFILE_RING)))
    history = store.profiles(qkey)
    store.append(profile)
    findings: list[dict] = []
    if bool(conf.get(  # tpulint: ignore[host-sync]
            OBS_PROFILE_REGRESSION)):
        findings = detect_regressions(
            profile, history,
            baseline_n=int(  # tpulint: ignore[host-sync]
                conf.get(OBS_PROFILE_BASELINE_N)),
            wall_tolerance=float(  # tpulint: ignore[host-sync]
                conf.get(OBS_PROFILE_WALL_TOLERANCE)))
        live = getattr(ctx, "live_obs", None)
        if live is not None:
            for f in findings:
                live.add_finding(getattr(ctx, "query_id", None), f)
    ctx.metrics.add("obs.profiles_recorded")
    if findings:
        ctx.metrics.add("obs.profile_regressions", len(findings))
    return profile, findings
