"""Postmortem rendering over diagnostic bundles (obs/blackbox.py).

Renders a captured bundle into the operator-facing report `dev/
diagnose.py` prints and the history server's `/bundle?id=` page embeds:
the trigger timeline (what fired, in what order, with the full finding
chain), counter drift against the EMBEDDED same-key baseline history
(the bundle is self-contained — no profile store, no live process), and
the per-executor straggler/HBM map merged from the live-store snapshot
and the pulled worker diagnostic rings.

Everything here reads the bundle directory alone: a bundle copied off a
dead host renders identically. Pure host work, obviously — this module
never imports jax.
"""

from __future__ import annotations

import time

from .blackbox import list_bundles, load_bundle

__all__ = ["render_index", "render_postmortem"]


def _fmt_bytes(n) -> str:
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def render_index(bundle_dir: str) -> str:
    """The retention ring as a table, newest first."""
    entries = list_bundles(bundle_dir)
    if not entries:
        return f"no bundles under {bundle_dir}\n"
    now = time.time()
    lines = [f"{'bundle id':<28} {'reason':<10} {'trigger':<16} "
             f"{'query':<14} {'age':>8}"]
    for e in entries:
        age = now - (e.get("ts") or now)
        lines.append(
            f"{e.get('id') or '?':<28} {e.get('reason') or '?':<10} "
            f"{e.get('trigger_kind') or '-':<16} "
            f"{(e.get('query_id') or '-'):<14} {age:>7.0f}s")
    return "\n".join(lines) + "\n"


def _drift_section(profile: dict | None, history: list) -> list[str]:
    """Counter / launch / wall drift of the captured run against the
    mean of its embedded same-key baselines."""
    lines = ["== Counter drift vs same-key baseline =="]
    if not profile:
        lines.append("(no query profile in bundle — flight recorder "
                     "was off or no query ran)")
        return lines
    if not history:
        lines.append("(no baseline history embedded — first run of "
                     "this query key, or recorder store empty)")
    key_rows: list[tuple] = []

    def mean(vals):
        vals = [v for v in vals if isinstance(v, (int, float))]
        return sum(vals) / len(vals) if vals else None

    wall = profile.get("wall_ms")
    base_wall = mean([p.get("wall_ms") for p in history])
    if wall is not None:
        key_rows.append(("wall_ms", wall, base_wall))
    launches = sum((profile.get("launches_by_kind") or {}).values())
    base_launches = mean(
        [sum((p.get("launches_by_kind") or {}).values())
         for p in history])
    key_rows.append(("kernel launches", launches, base_launches))
    counters = profile.get("counters") or {}
    base_counters: dict = {}
    for p in history:
        for k, v in (p.get("counters") or {}).items():
            base_counters.setdefault(k, []).append(v)
    for k in sorted(set(counters) | set(base_counters)):
        key_rows.append((k, counters.get(k, 0),
                         mean(base_counters.get(k, []))))
    lines.append(f"{'metric':<32} {'this run':>12} {'baseline':>12} "
                 f"{'drift':>10}")
    for name, cur, base in key_rows:
        if base is None:
            drift = "(new)"
            base_s = "-"
        else:
            base_s = f"{base:.1f}"
            drift = f"{cur - base:+.1f}" if isinstance(
                cur, (int, float)) else "?"
        lines.append(f"{name:<32} {cur!s:>12} {base_s:>12} {drift:>10}")
    lines.append(f"(baselines: {len(history)} embedded same-key "
                 f"run{'s' if len(history) != 1 else ''})")
    return lines


def _executor_section(manifest: dict) -> list[str]:
    """Per-executor map: live-store utilization/HBM rows merged with the
    pulled worker diagnostic rings and straggler findings."""
    lines = ["== Per-executor straggler / HBM map =="]
    live = manifest.get("live") or {}
    executors = dict(live.get("executors") or {})
    workers = manifest.get("workers") or {}
    straggled: dict[str, int] = {}
    for f in manifest.get("findings") or []:
        if f.get("kind") == "obs.straggler" and f.get("executor"):
            eid = str(f["executor"])
            straggled[eid] = straggled.get(eid, 0) + 1
    eids = sorted(set(executors) | set(workers) | set(straggled))
    if not eids:
        lines.append("(no executor state captured — local-mode query "
                     "with no live rows)")
        return lines
    for eid in eids:
        e = executors.get(eid) or {}
        w = workers.get(eid) or {}
        bits = [f"executor {eid}:"]
        if e:
            bits.append(f"hbm={_fmt_bytes(e.get('hbm_bytes'))}"
                        f" peak={_fmt_bytes(e.get('hbm_peak'))}")
            if e.get("excluded"):
                bits.append(f"EXCLUDED({e.get('failures', 0)} fails)")
            if e.get("overflows"):
                bits.append(f"obs-trims={e['overflows']}")
        if straggled.get(eid):
            bits.append(f"stragglers={straggled[eid]}")
        tasks = w.get("tasks") or []
        if tasks:
            spans = sum(len(t.get("spans") or []) for t in tasks)
            bits.append(f"pulled ring: {len(tasks)} task(s), "
                        f"{spans} span(s)")
        faults = (w.get("faults") or {})
        fired = faults.get("fired") or {}
        if fired:
            bits.append("faults fired: " + ", ".join(
                f"{k}:{v}" for k, v in sorted(fired.items())))
        lw = w.get("lockwatch") or {}
        if lw.get("violations"):
            bits.append(f"lockwatch violations={len(lw['violations'])}")
        lines.append("  " + " ".join(bits))
    return lines


def render_postmortem(bundle_dir: str, bundle_id: str) -> str:
    """The full postmortem report for one bundle, from its directory
    alone. Raises KeyError for an unknown/pruned bundle id."""
    manifest = load_bundle(bundle_dir, bundle_id)
    if manifest is None:
        raise KeyError(bundle_id)
    lines: list[str] = []
    ts = manifest.get("ts")
    when = time.strftime("%Y-%m-%d %H:%M:%S",
                         time.localtime(ts)) if ts else "?"
    lines.append(f"DIAGNOSTIC BUNDLE {manifest.get('id')}")
    lines.append(f"captured {when}  reason={manifest.get('reason')}  "
                 f"query={manifest.get('query_id') or '(none)'}")
    lines.append("")

    # trigger timeline: the triggering finding first, then the full
    # chain in raise order (the live store appends chronologically)
    lines.append("== Trigger timeline ==")
    trigger = manifest.get("trigger")
    if trigger:
        lines.append(f"TRIGGER  [{trigger.get('severity')}] "
                     f"{trigger.get('kind')}: {trigger.get('msg')}")
    else:
        lines.append("(no trigger — sampled or manual capture)")
    chain = manifest.get("findings") or []
    for i, f in enumerate(chain):
        mark = "->" if f == trigger else f"{i:2d}"
        lines.append(f"  {mark} [{f.get('severity')}] {f.get('kind')}: "
                     f"{f.get('msg')}")
    if not chain:
        lines.append("  (finding chain empty)")
    lines.append("")

    plan = manifest.get("plan") or {}
    if plan:
        lines.append("== Query ==")
        if plan.get("detail"):
            lines.append(f"plan: {plan['detail']}")
        if plan.get("query_key"):
            lines.append(f"query key: {plan['query_key']}  "
                         f"fingerprint: {plan.get('fingerprint')}")
        phases = plan.get("phases") or {}
        if phases:
            lines.append("phases: " + "  ".join(
                f"{k}={v:.1f}ms" for k, v in phases.items()))
        lines.append("")

    lines.extend(_drift_section(manifest.get("profile"),
                                manifest.get("profile_history") or []))
    lines.append("")
    lines.extend(_executor_section(manifest))
    lines.append("")

    conf = manifest.get("conf_overrides") or {}
    if conf:
        lines.append("== Non-default config ==")
        for k, v in sorted(conf.items()):
            lines.append(f"  {k} = {v}")
        lines.append("")

    lines.append("== Bundle files ==")
    for name in manifest.get("files") or []:
        lines.append(f"  {name}")
    return "\n".join(lines) + "\n"
