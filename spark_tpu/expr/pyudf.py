"""Python UDF expression.

Role of the reference's PythonUDF + Arrow eval path (sqlx/python/
ArrowEvalPythonExec.scala, core/api/python/PythonRunner.scala:204,
python/pyspark/worker.py). In-process design: there is no JVM↔Python
boundary to cross, so the "worker protocol" collapses to vectorized host
evaluation over the batch's Arrow-side columns — device pipelines evaluate
the UDF's argument expressions, live rows cross to the host once, the
function runs vectorized (numpy in/out, np.vectorize fallback), and the
result re-enters HBM as a new column.
"""

from __future__ import annotations

import itertools
from typing import Callable, Sequence

from ..types import DataType
from .expressions import Expression

_udf_uid_counter = itertools.count(1)


def _next_udf_uid() -> int:
    return next(_udf_uid_counter)


class PythonUDF(Expression):
    child_fields = ("args",)

    def __init__(self, fn: Callable, args: Sequence[Expression],
                 return_type: DataType, name: str = "udf",
                 vectorized: bool = True, deterministic: bool = True):
        self.fn = fn
        self.args = list(args)
        self.return_type = return_type
        self.fname = name
        self.vectorized = vectorized
        # deterministic element-wise contract (the engine evaluates UDFs
        # per batch, so batch-shape-dependent functions are already out of
        # contract): licenses the dictionary-domain evaluation lane
        # (physical/python_eval.py — evaluate once per DISTINCT value of a
        # dictionary-encoded argument, map over codes)
        self.deterministic = deterministic

    @property
    def dtype(self) -> DataType:
        return self.return_type

    @property
    def nullable(self) -> bool:
        return True

    def _data_args(self):
        # a process-unique serial, NOT id(fn): the kernel cache outlives the
        # plan, and a dead function's recycled address must not resurrect a
        # stale compiled UDF
        uid = getattr(self.fn, "_sparktpu_uid", None)
        if uid is None:
            uid = getattr(self, "_fallback_uid", None)
        if uid is None:
            uid = _next_udf_uid()
            try:
                self.fn._sparktpu_uid = uid
            except (AttributeError, TypeError):
                # unsettable callable (builtin/method): pin the uid on the
                # EXPRESSION so repeated _data_args() calls stay equal
                object.__setattr__(self, "_fallback_uid", uid)
        return (("fn", uid), ("name", self.fname))

    def eval(self, ctx):
        from ..errors import ExecutionError

        raise ExecutionError(
            "PythonUDF must be extracted by the planner (ExtractPythonUDFs)")

    def simple_string(self):
        a = ", ".join(x.simple_string() for x in self.args)
        return f"{self.fname}({a})"
