"""Python UDF expression.

Role of the reference's PythonUDF + Arrow eval path (sqlx/python/
ArrowEvalPythonExec.scala, core/api/python/PythonRunner.scala:204,
python/pyspark/worker.py). In-process design: there is no JVM↔Python
boundary to cross, so the "worker protocol" collapses to vectorized host
evaluation over the batch's Arrow-side columns — device pipelines evaluate
the UDF's argument expressions, live rows cross to the host once, the
function runs vectorized (numpy in/out, np.vectorize fallback), and the
result re-enters HBM as a new column.
"""

from __future__ import annotations

from typing import Callable, Sequence

from ..types import DataType
from .expressions import Expression


class PythonUDF(Expression):
    child_fields = ("args",)

    def __init__(self, fn: Callable, args: Sequence[Expression],
                 return_type: DataType, name: str = "udf",
                 vectorized: bool = True):
        self.fn = fn
        self.args = list(args)
        self.return_type = return_type
        self.fname = name
        self.vectorized = vectorized

    @property
    def dtype(self) -> DataType:
        return self.return_type

    @property
    def nullable(self) -> bool:
        return True

    def _data_args(self):
        return (("fn", id(self.fn)), ("name", self.fname))

    def eval(self, ctx):
        from ..errors import ExecutionError

        raise ExecutionError(
            "PythonUDF must be extracted by the planner (ExtractPythonUDFs)")

    def simple_string(self):
        a = ", ".join(x.simple_string() for x in self.args)
        return f"{self.fname}({a})"
