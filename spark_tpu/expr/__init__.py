from .expressions import *  # noqa: F401,F403
from .eval import Val, HostCtx, TraceCtx, EvalCtx  # noqa: F401
