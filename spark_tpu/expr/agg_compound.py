"""Compound (multi-argument / higher-moment) aggregates built from Sum/Count.

Role of the reference's Corr/Covariance/CentralMomentAgg classes
(sqlcat/expressions/aggregate/{Corr,Covariance,CentralMomentAgg}.scala).
Design: instead of bespoke multi-column buffers, each function expands into
an expression over single-input Sums of computed terms (Σx, Σy, Σxy, Σx²,
Σx³, Σx⁴ …) — the aggregation operator already merges any number of
AggregateFunctions in one pass, and XLA fuses the term computations into
the same kernel. Null semantics: pairwise functions only count rows where
all arguments are non-null (guarded terms).
"""

from __future__ import annotations

from .expressions import (
    And, Cast, Count, Divide, Expression, GreaterThan, If, IsNotNull, Literal,
    Multiply, Sqrt, Subtract, Sum, cast_if,
)
from ..types import float64


def _f(e: Expression) -> Expression:
    return cast_if(e, float64)


def _guard2(x: Expression, y: Expression, term: Expression) -> Expression:
    """term when both x and y are non-null, else NULL (excluded from Sum)."""
    return If(And(IsNotNull(x), IsNotNull(y)), term, Literal(None, float64))


def _pair_moments(x: Expression, y: Expression):
    xf, yf = _f(x), _f(y)
    n = _f(Count(_guard2(x, y, Literal(1.0))))
    sx = Sum(_guard2(x, y, xf))
    sy = Sum(_guard2(x, y, yf))
    sxy = Sum(_guard2(x, y, Multiply(xf, yf)))
    sxx = Sum(_guard2(x, y, Multiply(xf, xf)))
    syy = Sum(_guard2(x, y, Multiply(yf, yf)))
    return n, sx, sy, sxy, sxx, syy


def corr(x: Expression, y: Expression) -> Expression:
    n, sx, sy, sxy, sxx, syy = _pair_moments(x, y)
    num = Subtract(Multiply(n, sxy), Multiply(sx, sy))
    dx = Subtract(Multiply(n, sxx), Multiply(sx, sx))
    dy = Subtract(Multiply(n, syy), Multiply(sy, sy))
    return Divide(num, Sqrt(Multiply(dx, dy)))


def covar_pop(x: Expression, y: Expression) -> Expression:
    n, sx, sy, sxy, _, _ = _pair_moments(x, y)
    return Divide(Subtract(sxy, Divide(Multiply(sx, sy), n)), n)


def covar_samp(x: Expression, y: Expression) -> Expression:
    n, sx, sy, sxy, _, _ = _pair_moments(x, y)
    return Divide(Subtract(sxy, Divide(Multiply(sx, sy), n)),
                  Subtract(n, Literal(1.0)))


def _central_moments(x: Expression):
    xf = _f(x)
    n = _f(Count(x))
    s1 = Sum(xf)
    s2 = Sum(Multiply(xf, xf))
    s3 = Sum(Multiply(Multiply(xf, xf), xf))
    s4 = Sum(Multiply(Multiply(xf, xf), Multiply(xf, xf)))
    mu = Divide(s1, n)
    m2 = Subtract(Divide(s2, n), Multiply(mu, mu))
    # m3 = E[x³] − 3μE[x²] + 2μ³
    m3 = Subtract(
        Divide(s3, n),
        Subtract(Multiply(Literal(3.0), Multiply(mu, Divide(s2, n))),
                 Multiply(Literal(2.0), Multiply(mu, Multiply(mu, mu)))))
    # m4 = E[x⁴] − 4μE[x³] + 6μ²E[x²] − 3μ⁴
    mu2 = Multiply(mu, mu)
    m4 = Subtract(
        Divide(s4, n),
        Subtract(
            Multiply(Literal(4.0), Multiply(mu, Divide(s3, n))),
            Subtract(Multiply(Literal(6.0), Multiply(mu2, Divide(s2, n))),
                     Multiply(Literal(3.0), Multiply(mu2, mu2)))))
    return n, mu, m2, m3, m4


def skewness(x: Expression) -> Expression:
    n, _, m2, m3, _ = _central_moments(x)
    return Divide(m3, Sqrt(Multiply(Multiply(m2, m2), m2)))


def kurtosis(x: Expression) -> Expression:
    """Excess kurtosis m4/m2² − 3 (Spark semantics)."""
    n, _, m2, _, m4 = _central_moments(x)
    return Subtract(Divide(m4, Multiply(m2, m2)), Literal(3.0))
