"""Dual-mode expression evaluation contexts.

Role of the reference's expression codegen (sqlcat/expressions/codegen/
CodeGenerator.scala — every Expression has interpreted `eval` + `doGenCode`).
TPU re-design: every Expression has ONE `eval(ctx)` implementation that runs
in two modes over the same traversal:

  * HOST mode (per batch, before tracing): no row data. Computes result
    *metadata* — dtype, string dictionary, validity presence — and registers
    "aux arrays": per-dictionary lookup tables (value hashes, LIKE bitmaps,
    parsed casts, transformed ranks) derived from the batch's dictionaries.
    O(|dictionary|) host work, never O(rows).
  * TRACE mode (once per kernel-cache key, inside jax.jit): row data flows as
    traced arrays; aux arrays arrive as function arguments in registration
    order; XLA fuses the whole operator pipeline (the WholeStageCodegen
    analog, sqlx/WholeStageCodegenExec.scala:47).

Aux arrays are padded to power-of-two buckets so kernels are reused across
batches whose dictionaries differ only in content/size bucket.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable

import numpy as np

from ..columnar.batch import StringDict
from ..types import DataType

__all__ = ["Val", "EvalCtx", "HostCtx", "TraceCtx", "pad_pow2"]


def pad_pow2(arr: np.ndarray, minimum: int = 8, fill=None) -> np.ndarray:
    """Pad a 1-D lookup array to a power-of-two length (bucketed so kernel
    signatures are stable across dictionary sizes)."""
    n = max(len(arr), 1)
    cap = minimum
    while cap < n:
        cap <<= 1
    if len(arr) == cap:
        return arr
    if len(arr) == 0:
        return np.zeros(cap, dtype=arr.dtype)
    out = np.empty(cap, dtype=arr.dtype)
    out[: len(arr)] = arr
    out[len(arr):] = arr[-1] if fill is None else fill
    return out


@dataclass
class Val:
    """An evaluated expression value.

    HOST mode:  data is None; validity is True (present) or None (absent);
                sdict is the real StringDict when string-typed.
    TRACE mode: data is a traced array (may be scalar for literals);
                validity is a traced bool array or None; sdict is None.
    """

    dtype: DataType
    data: Any
    validity: Any
    sdict: StringDict | None = None

    @property
    def has_validity(self) -> bool:
        return self.validity is not None


class EvalCtx:
    """Shared machinery: memoized recursion + positional aux channel."""

    is_trace: bool = False

    def __init__(self) -> None:
        # entries hold a strong ref to the keyed expression: id() values
        # recycle after GC, and eval() builds transient nodes (Coalesce →
        # CaseWhen, cast_if → Cast) whose addresses would otherwise alias a
        # dead node's memo entry and return its stale Val
        self._memo: dict[int, tuple[Any, Val]] = {}

    # --- recursion --------------------------------------------------------
    def eval(self, expr) -> Val:
        key = id(expr)
        hit = self._memo.get(key)
        if hit is not None and hit[0] is expr:
            return hit[1]
        v = expr.eval(self)
        self._memo[key] = (expr, v)
        return v

    # --- aux channel ------------------------------------------------------
    def aux(self, make: Callable[[], np.ndarray], minimum: int = 8, fill=None):
        raise NotImplementedError

    # --- validity helpers -------------------------------------------------
    def and_valid(self, *vals: "Val"):
        """Combined validity (NULL if any input NULL)."""
        present = [v.validity for v in vals if v.validity is not None]
        if not present:
            return None
        if not self.is_trace:
            return True
        out = present[0]
        for p in present[1:]:
            out = out & p
        return out

    def attribute(self, expr_id: int) -> Val:
        raise NotImplementedError


class HostCtx(EvalCtx):
    """Per-batch metadata pass. `inputs` maps attribute expr_id → Val
    (host-mode: dtype + validity presence + dictionary)."""

    is_trace = False

    def __init__(self, inputs: dict[int, Val]):
        super().__init__()
        self.inputs = inputs
        self.aux_arrays: list[np.ndarray] = []

    def aux(self, make, minimum: int = 8, fill=None):
        arr = pad_pow2(np.asarray(make()), minimum=minimum, fill=fill)
        self.aux_arrays.append(arr)
        return _HostAux(arr.shape, arr.dtype)

    def attribute(self, expr_id: int) -> Val:
        return self.inputs[expr_id]

    def signature(self) -> tuple:
        """Part of the kernel cache key: aux shapes/dtypes."""
        return tuple((a.shape, str(a.dtype)) for a in self.aux_arrays)


@dataclass(frozen=True)
class _HostAux:
    shape: tuple
    dtype: Any


class TraceCtx(EvalCtx):
    """Tracing pass (inside jax.jit). `inputs` maps attribute expr_id → Val
    with traced arrays; `aux_args` is the flat list of traced aux arrays in
    registration order."""

    is_trace = True

    def __init__(self, inputs: dict[int, Val], aux_args: list, capacity: int,
                 row_mask=None):
        super().__init__()
        self.inputs = inputs
        self._aux_args = aux_args
        self._aux_pos = 0
        self.capacity = capacity
        self.row_mask = row_mask

    def aux(self, make, minimum: int = 8, fill=None):
        a = self._aux_args[self._aux_pos]
        self._aux_pos += 1
        return a

    def attribute(self, expr_id: int) -> Val:
        return self.inputs[expr_id]
