"""Function registry: SQL/DataFrame function names → expression builders.

Role of the reference's FunctionRegistry (sqlcat/analysis/FunctionRegistry.scala)."""

from __future__ import annotations

from typing import Callable, Sequence

from ..errors import AnalysisException
from . import expressions as E

Builder = Callable[..., E.Expression]


def _lit_str(e: E.Expression) -> str:
    if isinstance(e, E.Literal) and isinstance(e.value, str):
        return e.value
    raise AnalysisException("expected a string literal argument")


def _conv_base(s: str, from_base: int, to_base: int) -> str | None:
    """conv('ff', 16, 10) → '255' (mathExpressions.scala Conv)."""
    try:
        v = int(s.strip(), from_base)
    except ValueError:
        return None
    if to_base == 10:
        return str(v)
    digits = "0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    neg = v < 0
    v = abs(v)
    out = ""
    while True:
        out = digits[v % to_base] + out
        v //= to_base
        if v == 0:
            break
    return ("-" + out) if neg else out


def _stable_hash(xs, bits: int) -> int:
    """Deterministic multi-arg hash (role of the reference's Murmur3
    `hash` / xxhash64 — same shape and stability, different constants,
    so exact hash VALUES differ from the reference by design)."""
    import hashlib

    h = hashlib.sha256(repr(tuple(xs)).encode()).digest()
    v = int.from_bytes(h[: bits // 8], "little", signed=True)
    return v


def _width_bucket(v, lo, hi, n):
    n = int(n)
    if n <= 0 or lo == hi:
        return None
    if lo < hi:
        if v < lo:
            return 0
        if v >= hi:
            return n + 1
        return int((v - lo) / (hi - lo) * n) + 1
    if v > lo:
        return 0
    if v <= hi:
        return n + 1
    return int((lo - v) / (lo - hi) * n) + 1


_REGISTRY: dict[str, Builder] = {}


def register(name: str, builder: Builder) -> None:
    _REGISTRY[name.lower()] = builder


def lookup(name: str) -> Builder | None:
    return _REGISTRY.get(name.lower())


def registered_names() -> list[str]:
    """All callable SQL function names (FunctionRegistry.listFunction
    role — backs Catalog.listFunctions and SHOW FUNCTIONS). Includes
    names special-cased in build_function rather than registered."""
    return list(_REGISTRY) + ["count"]


def filter_names(pattern: str | None) -> list[str]:
    """Sorted function names matching a SHOW FUNCTIONS pattern:
    case-insensitive, `*` wildcard, `|` alternation (reference:
    StringUtils.filterPattern)."""
    import fnmatch

    names = sorted(registered_names())
    if not pattern:
        return names
    alts = [p.strip().lower() for p in pattern.split("|") if p.strip()]
    return [n for n in names
            if any(fnmatch.fnmatch(n.lower(), a) for a in alts)]


def function_exists(name: str) -> bool:
    return name.lower() in {n.lower() for n in registered_names()}


def build_function(name: str, args: Sequence[E.Expression],
                   distinct: bool = False) -> E.Expression:
    n = name.lower()
    if n == "count":
        if len(args) == 0 or isinstance(args[0], E.UnresolvedStar):
            return E.Count(None, distinct=False)
        return E.Count(args[0], distinct=distinct)
    b = lookup(n)
    if b is None:
        raise AnalysisException(f"Undefined function: {name}",
                                error_class="UNRESOLVED_ROUTINE")
    out = b(*args)
    if distinct:
        if isinstance(out, (E.Sum, E.Average)):
            out.distinct = True  # consumed by RewriteDistinctAggregates
        else:
            raise AnalysisException(
                f"DISTINCT is not supported for {name}")
    return out


def _reg_all() -> None:
    r = register
    # aggregates
    r("sum", lambda c: E.Sum(c))
    r("min", lambda c: E.Min(c))
    r("max", lambda c: E.Max(c))
    r("avg", lambda c: E.Average(c))
    r("mean", lambda c: E.Average(c))
    r("first", lambda c, *a: E.First(c))
    r("first_value", lambda c, *a: E.First(c))
    r("any_value", lambda c, *a: E.AnyValue(c))
    r("stddev", lambda c: E.StddevSamp(c))
    r("stddev_samp", lambda c: E.StddevSamp(c))
    r("stddev_pop", lambda c: E.StddevPop(c))
    r("variance", lambda c: E.VarianceSamp(c))
    r("var_samp", lambda c: E.VarianceSamp(c))
    r("var_pop", lambda c: E.VariancePop(c))
    r("collect_set", lambda c: E.CollectSet(c))
    r("collect_list", lambda c: E.CollectList(c))
    r("array_agg", lambda c: E.CollectList(c))
    r("median", lambda c: E.Median(c))
    r("percentile", lambda c, q: E.Percentile(c, float(q.value)))
    r("percentile_approx", lambda c, q, *a: E.Percentile(c, float(q.value)))
    from . import agg_compound as AC

    r("corr", AC.corr)
    r("covar_samp", AC.covar_samp)
    r("covar_pop", AC.covar_pop)
    r("skewness", AC.skewness)
    r("kurtosis", AC.kurtosis)
    r("approx_count_distinct", lambda c, *a: E.Count(c, distinct=True))
    r("bit_and", lambda c: E.BitAndAgg(c))
    r("bit_or", lambda c: E.BitOrAgg(c))
    r("bit_xor", lambda c: E.BitXorAgg(c))
    r("mode", lambda c: E.Mode(c))
    # math
    r("abs", lambda c: E.Abs(c))
    r("sqrt", lambda c: E.Sqrt(c))
    r("exp", lambda c: E.Exp(c))
    r("ln", lambda c: E.Log(c))
    # log(x) = ln(x); log(base, x) = ln(x) / ln(base)
    r("log", lambda a, b=None: E.Log(a) if b is None
      else E.Divide(E.Log(b), E.Log(a)))
    r("pmod", lambda a, b: E.Remainder(E.Add(E.Remainder(a, b), b), b))
    r("log10", lambda c: E.Log10(c))
    r("floor", lambda c: E.Floor(c))
    r("ceil", lambda c: E.Ceil(c))
    r("ceiling", lambda c: E.Ceil(c))
    r("round", lambda c, s=None: E.Round(c, s))
    r("power", lambda a, b: E.Pow(a, b))
    r("pow", lambda a, b: E.Pow(a, b))
    r("mod", lambda a, b: E.Remainder(a, b))
    r("negative", lambda c: E.UnaryMinus(c))
    r("sin", lambda c: E.Sin(c))
    r("cos", lambda c: E.Cos(c))
    r("tan", lambda c: E.Tan(c))
    r("asin", lambda c: E.Asin(c))
    r("acos", lambda c: E.Acos(c))
    r("atan", lambda c: E.Atan(c))
    r("atan2", lambda a, b: E.Atan2(a, b))
    r("sinh", lambda c: E.Sinh(c))
    r("cosh", lambda c: E.Cosh(c))
    r("tanh", lambda c: E.Tanh(c))
    r("log2", lambda c: E.Log2(c))
    r("log1p", lambda c: E.Log1p(c))
    r("expm1", lambda c: E.Expm1(c))
    r("degrees", lambda c: E.Degrees(c))
    r("radians", lambda c: E.Radians(c))
    r("cbrt", lambda c: E.Cbrt(c))
    r("sign", lambda c: E.Signum(c))
    r("signum", lambda c: E.Signum(c))
    r("pi", lambda: E.Literal(3.141592653589793))
    r("e", lambda: E.Literal(2.718281828459045))
    r("shiftleft", lambda a, b: E.ShiftLeft(a, b))
    r("shiftright", lambda a, b: E.ShiftRight(a, b))
    r("bit_and_op", lambda a, b: E.BitwiseAnd(a, b))
    r("bit_or_op", lambda a, b: E.BitwiseOr(a, b))
    r("bit_xor_op", lambda a, b: E.BitwiseXor(a, b))
    r("bit_not", lambda c: E.BitwiseNot(c))
    # conditionals
    r("if", lambda p, a, b: E.If(p, a, b))
    r("coalesce", lambda *a: E.Coalesce(list(a)))
    r("nullif", lambda a, b: E.NullIf(a, b))
    r("nvl", lambda a, b: E.Coalesce([a, b]))
    r("ifnull", lambda a, b: E.Coalesce([a, b]))
    r("greatest", lambda *a: E.Greatest(list(a)))
    r("least", lambda *a: E.Least(list(a)))
    r("isnull", lambda c: E.IsNull(c))
    r("isnotnull", lambda c: E.IsNotNull(c))
    r("isnan", lambda c: E.IsNaN(c))
    # strings
    r("upper", lambda c: E.Upper(c))
    r("split", lambda c, d: E.Split(c, d))
    r("explode", lambda c: E.Explode(c))
    r("grouping", lambda c: E.Grouping(c))
    r("grouping_id", lambda *a: E.GroupingID(list(a)))
    r("ucase", lambda c: E.Upper(c))
    r("lower", lambda c: E.Lower(c))
    r("lcase", lambda c: E.Lower(c))
    r("trim", lambda c: E.Trim(c))
    r("ltrim", lambda c: E.LTrim(c))
    r("rtrim", lambda c: E.RTrim(c))
    r("length", lambda c: E.Length(c))
    r("char_length", lambda c: E.Length(c))
    r("substring", lambda c, p, l=None: E.Substring(c, p, l))
    r("substr", lambda c, p, l=None: E.Substring(c, p, l))
    r("concat", lambda *a: E.Concat(list(a)))
    r("replace", lambda c, s, rep: E.StringReplace(c, s, rep))
    r("lpad", lambda c, l, p=None: E.Lpad(c, l, p if p is not None else E.Literal(" "))),
    r("rpad", lambda c, l, p=None: E.Rpad(c, l, p if p is not None else E.Literal(" "))),
    r("startswith", lambda c, p: E.StartsWith(c, _lit_str(p)))
    r("endswith", lambda c, p: E.EndsWith(c, _lit_str(p)))
    r("contains", lambda c, p: E.Contains(c, _lit_str(p)))
    r("like", lambda c, p: E.Like(c, _lit_str(p)))
    r("rlike", lambda c, p: E.RLike(c, _lit_str(p)))
    r("regexp", lambda c, p: E.RLike(c, _lit_str(p)))
    r("regexp_extract", lambda c, p, g=None: E.RegexpExtract(
        c, p, g if g is not None else E.Literal(1)))
    r("date_format", lambda c, f: E.DateFormat(c, f))
    r("initcap", lambda c: E.Initcap(c))
    r("reverse", lambda c: E.Reverse(c))
    r("repeat", lambda c, n: E.Repeat(c, n))
    r("substring_index", lambda c, d, n: E.SubstringIndex(c, d, n))
    r("regexp_extract", lambda c, p, i=None: E.RegexpExtract(c, p, i))
    r("regexp_replace", lambda c, p, rp: E.RegexpReplace(c, p, rp))
    r("left", lambda c, n: E.Left(c, n))
    r("right", lambda c, n: E.Right(c, n))
    r("overlay", lambda c, rp, p, l=None: E.Overlay(c, rp, p, l))
    r("soundex", lambda c: E.Soundex(c))
    r("md5", lambda c: E.Md5(c))
    r("sha1", lambda c: E.Sha1(c))
    r("sha", lambda c: E.Sha1(c))
    r("sha2", lambda c, b: E.Sha2(c, b))
    r("base64", lambda c: E.Base64(c))
    r("unbase64", lambda c: E.Unbase64(c))
    r("levenshtein", lambda c, o: E.Levenshtein(c, o))
    r("format_number", lambda c, d: E.FormatNumber(c, d))
    r("try_divide", lambda a, b: E.If(
        E.EqualTo(b, E.Literal(0)), E.Literal(None), E.Divide(a, b)))
    r("try_add", lambda a, b: E.TryAdd(a, b))
    r("try_subtract", lambda a, b: E.TrySubtract(a, b))
    r("try_multiply", lambda a, b: E.TryMultiply(a, b))
    # arrays (dictionary-encoded; see ArrayType)
    r("size", lambda c: E.Size(c))
    r("cardinality", lambda c: E.Size(c))
    r("array_contains", lambda c, v: E.ArrayContains(c, v))
    r("array_min", lambda c: E.ArrayMin(c))
    r("array_max", lambda c: E.ArrayMax(c))
    r("sort_array", lambda c, asc=None: E.SortArray(c, asc))
    r("array_distinct", lambda c: E.ArrayDistinct(c))
    r("element_at", lambda c, i: E.build_element_at(c, i))
    r("flatten", lambda c: E.Flatten(c))
    r("slice", lambda c, s, ln: E.Slice(c, s, ln))
    r("array_remove", lambda c, v: E.ArrayRemove(c, v))
    r("array_join", lambda c, sep, nr=None: E.ArrayJoin(c, sep, nr))
    r("array_position", lambda c, v: E.ArrayPosition(c, v))
    r("get_json_object", lambda c, p: E.GetJsonObject(c, p))
    r("crc32", lambda c: E.Crc32(c))
    r("nanvl", lambda a, b: E.NanVl(a, b))
    r("bround", lambda c, s=None: E.BRound(c, s))
    r("struct", lambda *a: E.build_struct_ctor(list(a)))
    r("named_struct", lambda *a: E.build_named_struct(list(a)))
    r("map", lambda *a: E.build_map_ctor(list(a)))
    r("map_keys", lambda c: E.MapKeys(c))
    r("map_values", lambda c: E.MapValues(c))
    r("map_contains_key", lambda c, k: E.MapContainsKey(c, k))
    r("translate", lambda c, m, rep: E.Translate(c, m, rep))
    # regexp family (regexpExpressions.scala)
    r("regexp_extract_all", lambda c, p, g=None: E.RegexpExtractAll(c, p, g))
    r("regexp_substr", lambda c, p: E.RegexpSubstr(c, p))
    r("regexp_instr", lambda c, p: E.RegexpInstr(c, p))
    r("regexp_count", lambda c, p: E.RegexpCount(c, p))
    r("regexp_like", lambda c, p: E.RLike(c, _lit_str(p)))
    r("regexp", lambda c, p: E.RLike(c, _lit_str(p)))
    r("rlike", lambda c, p: E.RLike(c, _lit_str(p)))
    # number parsing (numberFormatExpressions.scala)
    r("to_number", lambda c, f: E.ToNumber(c, f, strict=True))
    r("try_to_number", lambda c, f: E.ToNumber(c, f, strict=False))
    # interval constructors (intervalExpressions.scala MakeInterval)
    r("make_interval", lambda y=None, mo=None, w=None, d=None, h=None,
        mi=None, s=None: E.build_make_interval(y, mo, w, d, h, mi, s))
    r("make_dt_interval", lambda d=None, h=None, mi=None, s=None:
        E.build_make_interval(None, None, None, d, h, mi, s))
    r("make_ym_interval", lambda y=None, mo=None:
        E.build_make_interval(y, mo, None, None, None, None, None))
    r("ascii", lambda c: E.Ascii(c))
    r("instr", lambda c, s: E.Instr(c, s))
    r("locate", lambda s, c, pos=None: E.Instr(c, s))
    r("position", lambda s, c: E.Instr(c, s))
    r("concat_ws", lambda sep, *a: E.ConcatWs(sep, list(a)))
    r("nvl2", lambda a, b, c: E.If(E.IsNotNull(a), b, c))

    # ---- breadth batch: host-evaluated scalar/array functions ----------
    # (complexTypeCreator.scala, collectionOperations.scala,
    # mathExpressions.scala, stringExpressions.scala). These ride the
    # in-process Python-eval path like map()/struct(); device pipelines
    # feed their arguments, results re-enter the columnar batch.
    from ..types import (
        ArrayType as _AT, MapType as _MT, StructField as _SF,
        StructType as _ST, boolean as _bool, float64 as _f64,
        int32 as _i32, int64 as _i64, string as _str,
    )
    from .pyudf import PythonUDF as _U

    def _et(e, default=_i64):
        dt = e.dtype
        return dt.element_type if isinstance(dt, _AT) else default

    def _strict(fn):
        def g(*a):
            if any(x is None for x in a):
                return None
            return fn(*a)
        return g

    def _seq(x, y, s=None):
        from ..errors import ExecutionError

        if s is None:
            s = 1 if y >= x else -1
        s = int(s)
        if s == 0 or (s > 0) != (y >= x) and x != y:
            raise ExecutionError(
                f"sequence: illegal step {s} for bounds {x}..{y}")
        return list(range(int(x), int(y) + (1 if s > 0 else -1), s))

    r("sequence", lambda a, b, step=None: _U(
        _strict(_seq),
        [a, b] + ([step] if step is not None else []), _AT(_i64),
        name="sequence", vectorized=False))
    r("array_repeat", lambda v, n: _U(
        lambda x, k: [] if k is None else [x] * int(k),
        [v, n], _AT(v.dtype), name="array_repeat", vectorized=False))
    r("array_union", lambda a, b: _U(
        _strict(lambda x, y: list(dict.fromkeys(list(x) + list(y)))),
        [a, b], a.dtype, name="array_union", vectorized=False))
    r("array_intersect", lambda a, b: _U(
        _strict(lambda x, y: [v for v in dict.fromkeys(x) if v in set(
            v2 for v2 in y if v2 is not None) or (
            v is None and any(v2 is None for v2 in y))]),
        [a, b], a.dtype, name="array_intersect", vectorized=False))
    r("array_except", lambda a, b: _U(
        _strict(lambda x, y: [v for v in dict.fromkeys(x)
                              if v not in set(
                                  v2 for v2 in y if v2 is not None)
                              and not (v is None and
                                       any(v2 is None for v2 in y))]),
        [a, b], a.dtype, name="array_except", vectorized=False))
    r("arrays_overlap", lambda a, b: _U(
        _strict(lambda x, y: bool(
            set(v for v in x if v is not None)
            & set(v for v in y if v is not None)) or (
            None if (None in list(x) or None in list(y)) and x and y
            else False)),
        [a, b], _bool, name="arrays_overlap", vectorized=False))
    r("array_append", lambda a, v: _U(
        lambda x, e: None if x is None else list(x) + [e],
        [a, v], a.dtype, name="array_append", vectorized=False))
    r("array_prepend", lambda a, v: _U(
        lambda x, e: None if x is None else [e] + list(x),
        [a, v], a.dtype, name="array_prepend", vectorized=False))
    r("array_insert", lambda a, p, v: _U(
        _strict(lambda x, i, e: (
            list(x[:int(i) - 1]) + [e] + list(x[int(i) - 1:]) if i > 0
            else list(x[:len(x) + int(i) + 1]) + [e]
            + list(x[len(x) + int(i) + 1:]))),
        [a, p, v], a.dtype, name="array_insert", vectorized=False))
    r("array_compact", lambda a: _U(
        lambda x: None if x is None else [v for v in x if v is not None],
        [a], a.dtype, name="array_compact", vectorized=False))
    r("arrays_zip", lambda *args: _U(
        _strict(lambda *xs: [
            {str(i): (x[j] if j < len(x) else None)
             for i, x in enumerate(xs)}
            for j in range(max(len(x) for x in xs))] if xs else []),
        list(args),
        _AT(_ST(tuple(_SF(str(i), _et(a), True)
                      for i, a in enumerate(args)))),
        name="arrays_zip", vectorized=False))
    r("map_from_arrays", lambda k, v: _U(
        _strict(lambda ks, vs: dict(zip(ks, vs))),
        [k, v], _MT(_et(k, _str), _et(v)), name="map_from_arrays",
        vectorized=False))
    r("map_from_entries", lambda a: _U(
        _strict(lambda es: {e[list(e)[0]] if isinstance(e, dict) else e[0]:
                            e[list(e)[1]] if isinstance(e, dict) else e[1]
                            for e in es}),
        [a], _MT(_str, _i64), name="map_from_entries", vectorized=False))
    r("str_to_map", lambda s, pd=None, kvd=None: _U(
        _strict(lambda x, p=",", kv=":": {
            (part.split(kv, 1) + [None])[0]:
            (part.split(kv, 1) + [None])[1]
            for part in x.split(p)} if x else {}),
        [s] + [x for x in (pd, kvd) if x is not None],
        _MT(_str, _str), name="str_to_map", vectorized=False))
    def _chr(i):
        return "" if i < 0 else chr(int(i) % 256)

    r("char", lambda c: _U(_strict(_chr), [c], _str, name="char",
                           vectorized=False))
    r("chr", lambda c: _U(_strict(_chr), [c], _str, name="chr",
                          vectorized=False))
    def _elt(n, *ss):
        et = ss[0].dtype if ss else _str
        for x in ss[1:]:
            from ..types import common_type as _ct
            et = _ct(et, x.dtype) or et
        return _U(lambda i, *xs: None if i is None or not (
            1 <= int(i) <= len(xs)) else xs[int(i) - 1],
            [n, *ss], et, name="elt", vectorized=False)

    r("elt", _elt)
    r("find_in_set", lambda s, lst: _U(
        _strict(lambda x, l: 0 if "," in x else
                ((l.split(",").index(x) + 1)
                 if x in l.split(",") else 0)),
        [s, lst], _i32, name="find_in_set", vectorized=False))
    r("format_string", lambda f, *a: _U(
        _strict(lambda fmt, *xs: fmt % xs),
        [f, *a], _str, name="format_string", vectorized=False))
    r("printf", lambda f, *a: _U(
        _strict(lambda fmt, *xs: fmt % xs),
        [f, *a], _str, name="printf", vectorized=False))
    r("bin", lambda c: _U(_strict(lambda i: bin(int(i))[2:] if i >= 0
                                  else bin(int(i) & ((1 << 64) - 1))[2:]),
                          [c], _str, name="bin", vectorized=False))
    r("hex", lambda c: _U(
        _strict(lambda v: format(int(v) & ((1 << 64) - 1), "X")
                if not isinstance(v, str)
                else v.encode().hex().upper()),
        [c], _str, name="hex", vectorized=False))
    r("unhex", lambda c: _U(
        _strict(lambda s: bytes.fromhex(s).decode(errors="replace")),
        [c], _str, name="unhex", vectorized=False))
    r("conv", lambda c, fb, tb: _U(
        _strict(lambda s, f, t: _conv_base(str(s), int(f), int(t))),
        [c, fb, tb], _str, name="conv", vectorized=False))
    r("bit_count", lambda c: _U(
        _strict(lambda i: bin(int(i) & ((1 << 64) - 1)).count("1")),
        [c], _i32, name="bit_count", vectorized=False))
    r("factorial", lambda c: _U(
        _strict(lambda i: None if i < 0 or i > 20 else
                __import__("math").factorial(int(i))),
        [c], _i64, name="factorial", vectorized=False))
    r("width_bucket", lambda v, lo, hi, n: _U(
        _strict(_width_bucket),
        [v, lo, hi, n], _i64, name="width_bucket", vectorized=False))
    r("hash", lambda *a: _U(
        lambda *xs: _stable_hash(xs, bits=32),
        list(a), _i32, name="hash", vectorized=False))
    r("xxhash64", lambda *a: _U(
        lambda *xs: _stable_hash(xs, bits=64),
        list(a), _i64, name="xxhash64", vectorized=False))
    r("hypot", lambda a, b: E.Sqrt(E.Add(E.Multiply(a, a),
                                         E.Multiply(b, b))))
    r("typeof", lambda a: E.Literal(a.dtype.simple_string()))
    r("bool_and", lambda c: E.Cast(E.Min(E.Cast(c, _i32)), _bool))
    r("every", lambda c: E.Cast(E.Min(E.Cast(c, _i32)), _bool))
    r("bool_or", lambda c: E.Cast(E.Max(E.Cast(c, _i32)), _bool))
    r("any", lambda c: E.Cast(E.Max(E.Cast(c, _i32)), _bool))
    r("some", lambda c: E.Cast(E.Max(E.Cast(c, _i32)), _bool))
    r("count_if", lambda c: E.Coalesce(
        [E.Sum(E.If(c, E.Literal(1), E.Literal(0))), E.Literal(0)]))
    r("unix_date", lambda d: E.DateDiff(
        d, E.Literal(__import__("datetime").date(1970, 1, 1))))
    def _mk_ts(a, b, c, x, e, f):
        import calendar
        import datetime as _dt

        dt = _dt.datetime(int(a), int(b), int(c), int(x), int(e),
                          int(float(f)))
        micros = calendar.timegm(dt.timetuple()) * 1_000_000 \
            + int(round((float(f) % 1) * 1e6))
        return micros      # engine-native epoch microseconds

    r("make_timestamp", lambda y, mo, d, h, mi, s: _U(
        _strict(_mk_ts), [y, mo, d, h, mi, s],
        __import__("spark_tpu.types", fromlist=["timestamp"]).timestamp,
        name="make_timestamp", vectorized=False))

    def _date_part(field, src):
        f = _lit_str(field).lower().rstrip("s")
        m = {"year": E.Year, "yr": E.Year, "month": E.Month,
             "mon": E.Month, "day": E.DayOfMonth, "d": E.DayOfMonth,
             "dayofweek": E.DayOfWeek, "dow": E.DayOfWeek,
             "doy": E.DayOfYear, "quarter": E.Quarter, "qtr": E.Quarter,
             "week": E.WeekOfYear, "hour": E.Hour, "hr": E.Hour,
             "minute": E.Minute, "min": E.Minute, "second": E.Second,
             "sec": E.Second}
        if f not in m:
            raise AnalysisException(f"date_part: unknown field {field}")
        return m[f](src)

    r("date_part", _date_part)
    r("datepart", _date_part)
    # higher-order functions (expr/higher_order.py; reference:
    # sqlcat/expressions/higherOrderFunctions.scala)
    from . import higher_order as H

    r("array", lambda *a: E.build_array_ctor(list(a)))
    r("transform", H.build_transform)
    r("filter", H.build_filter)
    r("exists", H.build_exists)
    r("forall", H.build_forall)
    r("any_match", H.build_exists)
    r("all_match", H.build_forall)
    r("aggregate", H.build_aggregate)
    r("reduce", H.build_aggregate)
    r("zip_with", H.build_zip_with)
    r("transform_keys", H.build_transform_keys)
    r("transform_values", H.build_transform_values)
    r("map_filter", H.build_map_filter)
    r("map_zip_with", H.build_map_zip_with)
    r("array_sort", lambda c, f=None: (
        H.lower_hof(H.ArraySortLambda([c], f)) if f is not None
        else E.ArraySortNullsLast(c)))
    # datetime
    r("year", lambda c: E.Year(c))
    r("month", lambda c: E.Month(c))
    r("day", lambda c: E.DayOfMonth(c))
    r("dayofmonth", lambda c: E.DayOfMonth(c))
    r("quarter", lambda c: E.Quarter(c))
    r("dayofweek", lambda c: E.DayOfWeek(c))
    r("dayofyear", lambda c: E.DayOfYear(c))
    r("weekofyear", lambda c: E.WeekOfYear(c))
    r("date_add", lambda d, n: E.DateAdd(d, n))
    r("date_sub", lambda d, n: E.DateSub(d, n))
    r("datediff", lambda a, b: E.DateDiff(a, b))
    r("trunc", lambda c, f: E.TruncDate(c, _lit_str(f)))
    r("date_trunc", lambda f, c: E.TruncDate(c, _lit_str(f),
                                             allow_day=True))
    r("make_date", lambda y, m, d: E.MakeDate(y, m, d))
    r("hour", lambda c: E.Hour(c))
    r("minute", lambda c: E.Minute(c))
    r("second", lambda c: E.Second(c))
    r("unix_timestamp", lambda c: E.UnixTimestamp(c))
    r("from_unixtime", lambda c, fmt=None: E.FromUnixtime(c))
    r("to_timestamp", lambda c, fmt=None: E.Cast(c, __import__(
        "spark_tpu.types", fromlist=["timestamp"]).timestamp))
    r("add_months", lambda d, n: E.AddMonths(d, n))
    r("months_between", lambda a, b, *x: E.MonthsBetween(a, b))
    r("last_day", lambda c: E.LastDay(c))
    r("to_date", lambda c, fmt=None: E.Cast(c, __import__(
        "spark_tpu.types", fromlist=["date"]).date))
    # window / ranking
    from .window import (
        CumeDist, DenseRank, FirstValue, Lag, LastValue, Lead, NthValue,
        NTile, PercentRank, Rank, RowNumber,
    )

    r("row_number", lambda: RowNumber())
    r("rank", lambda: Rank())
    r("dense_rank", lambda: DenseRank())
    r("percent_rank", lambda: PercentRank())
    r("cume_dist", lambda: CumeDist())
    r("ntile", lambda n: NTile(n))
    r("lag", lambda c, off=None, d=None: Lag(
        c, off if off is not None else E.Literal(1), d))
    r("lead", lambda c, off=None, d=None: Lead(
        c, off if off is not None else E.Literal(1), d))
    r("first_value", lambda c: FirstValue(c))
    r("last_value", lambda c: LastValue(c))
    r("nth_value", lambda c, n: NthValue(c, n))


_reg_all()
