"""Function registry: SQL/DataFrame function names → expression builders.

Role of the reference's FunctionRegistry (sqlcat/analysis/FunctionRegistry.scala)."""

from __future__ import annotations

from typing import Callable, Sequence

from ..errors import AnalysisException
from . import expressions as E

Builder = Callable[..., E.Expression]


def _lit_str(e: E.Expression) -> str:
    if isinstance(e, E.Literal) and isinstance(e.value, str):
        return e.value
    raise AnalysisException("expected a string literal argument")


_REGISTRY: dict[str, Builder] = {}


def register(name: str, builder: Builder) -> None:
    _REGISTRY[name.lower()] = builder


def lookup(name: str) -> Builder | None:
    return _REGISTRY.get(name.lower())


def registered_names() -> list[str]:
    """All callable SQL function names (FunctionRegistry.listFunction
    role — backs Catalog.listFunctions and SHOW FUNCTIONS). Includes
    names special-cased in build_function rather than registered."""
    return list(_REGISTRY) + ["count"]


def filter_names(pattern: str | None) -> list[str]:
    """Sorted function names matching a SHOW FUNCTIONS pattern:
    case-insensitive, `*` wildcard, `|` alternation (reference:
    StringUtils.filterPattern)."""
    import fnmatch

    names = sorted(registered_names())
    if not pattern:
        return names
    alts = [p.strip().lower() for p in pattern.split("|") if p.strip()]
    return [n for n in names
            if any(fnmatch.fnmatch(n.lower(), a) for a in alts)]


def function_exists(name: str) -> bool:
    return name.lower() in {n.lower() for n in registered_names()}


def build_function(name: str, args: Sequence[E.Expression],
                   distinct: bool = False) -> E.Expression:
    n = name.lower()
    if n == "count":
        if len(args) == 0 or isinstance(args[0], E.UnresolvedStar):
            return E.Count(None, distinct=False)
        return E.Count(args[0], distinct=distinct)
    b = lookup(n)
    if b is None:
        raise AnalysisException(f"Undefined function: {name}",
                                error_class="UNRESOLVED_ROUTINE")
    out = b(*args)
    if distinct:
        if isinstance(out, (E.Sum, E.Average)):
            out.distinct = True  # consumed by RewriteDistinctAggregates
        else:
            raise AnalysisException(
                f"DISTINCT is not supported for {name}")
    return out


def _reg_all() -> None:
    r = register
    # aggregates
    r("sum", lambda c: E.Sum(c))
    r("min", lambda c: E.Min(c))
    r("max", lambda c: E.Max(c))
    r("avg", lambda c: E.Average(c))
    r("mean", lambda c: E.Average(c))
    r("first", lambda c, *a: E.First(c))
    r("first_value", lambda c, *a: E.First(c))
    r("any_value", lambda c, *a: E.AnyValue(c))
    r("stddev", lambda c: E.StddevSamp(c))
    r("stddev_samp", lambda c: E.StddevSamp(c))
    r("stddev_pop", lambda c: E.StddevPop(c))
    r("variance", lambda c: E.VarianceSamp(c))
    r("var_samp", lambda c: E.VarianceSamp(c))
    r("var_pop", lambda c: E.VariancePop(c))
    r("collect_set", lambda c: E.CollectSet(c))
    r("collect_list", lambda c: E.CollectList(c))
    r("array_agg", lambda c: E.CollectList(c))
    r("median", lambda c: E.Median(c))
    r("percentile", lambda c, q: E.Percentile(c, float(q.value)))
    r("percentile_approx", lambda c, q, *a: E.Percentile(c, float(q.value)))
    from . import agg_compound as AC

    r("corr", AC.corr)
    r("covar_samp", AC.covar_samp)
    r("covar_pop", AC.covar_pop)
    r("skewness", AC.skewness)
    r("kurtosis", AC.kurtosis)
    r("approx_count_distinct", lambda c, *a: E.Count(c, distinct=True))
    # math
    r("abs", lambda c: E.Abs(c))
    r("sqrt", lambda c: E.Sqrt(c))
    r("exp", lambda c: E.Exp(c))
    r("ln", lambda c: E.Log(c))
    # log(x) = ln(x); log(base, x) = ln(x) / ln(base)
    r("log", lambda a, b=None: E.Log(a) if b is None
      else E.Divide(E.Log(b), E.Log(a)))
    r("pmod", lambda a, b: E.Remainder(E.Add(E.Remainder(a, b), b), b))
    r("log10", lambda c: E.Log10(c))
    r("floor", lambda c: E.Floor(c))
    r("ceil", lambda c: E.Ceil(c))
    r("ceiling", lambda c: E.Ceil(c))
    r("round", lambda c, s=None: E.Round(c, s))
    r("power", lambda a, b: E.Pow(a, b))
    r("pow", lambda a, b: E.Pow(a, b))
    r("mod", lambda a, b: E.Remainder(a, b))
    r("negative", lambda c: E.UnaryMinus(c))
    r("sin", lambda c: E.Sin(c))
    r("cos", lambda c: E.Cos(c))
    r("tan", lambda c: E.Tan(c))
    r("asin", lambda c: E.Asin(c))
    r("acos", lambda c: E.Acos(c))
    r("atan", lambda c: E.Atan(c))
    r("atan2", lambda a, b: E.Atan2(a, b))
    r("sinh", lambda c: E.Sinh(c))
    r("cosh", lambda c: E.Cosh(c))
    r("tanh", lambda c: E.Tanh(c))
    r("log2", lambda c: E.Log2(c))
    r("log1p", lambda c: E.Log1p(c))
    r("expm1", lambda c: E.Expm1(c))
    r("degrees", lambda c: E.Degrees(c))
    r("radians", lambda c: E.Radians(c))
    r("cbrt", lambda c: E.Cbrt(c))
    r("sign", lambda c: E.Signum(c))
    r("signum", lambda c: E.Signum(c))
    r("pi", lambda: E.Literal(3.141592653589793))
    r("e", lambda: E.Literal(2.718281828459045))
    r("shiftleft", lambda a, b: E.ShiftLeft(a, b))
    r("shiftright", lambda a, b: E.ShiftRight(a, b))
    r("bit_and_op", lambda a, b: E.BitwiseAnd(a, b))
    r("bit_or_op", lambda a, b: E.BitwiseOr(a, b))
    r("bit_xor_op", lambda a, b: E.BitwiseXor(a, b))
    r("bit_not", lambda c: E.BitwiseNot(c))
    # conditionals
    r("if", lambda p, a, b: E.If(p, a, b))
    r("coalesce", lambda *a: E.Coalesce(list(a)))
    r("nullif", lambda a, b: E.NullIf(a, b))
    r("nvl", lambda a, b: E.Coalesce([a, b]))
    r("ifnull", lambda a, b: E.Coalesce([a, b]))
    r("greatest", lambda *a: E.Greatest(list(a)))
    r("least", lambda *a: E.Least(list(a)))
    r("isnull", lambda c: E.IsNull(c))
    r("isnotnull", lambda c: E.IsNotNull(c))
    r("isnan", lambda c: E.IsNaN(c))
    # strings
    r("upper", lambda c: E.Upper(c))
    r("split", lambda c, d: E.Split(c, d))
    r("explode", lambda c: E.Explode(c))
    r("grouping", lambda c: E.Grouping(c))
    r("grouping_id", lambda *a: E.GroupingID(list(a)))
    r("ucase", lambda c: E.Upper(c))
    r("lower", lambda c: E.Lower(c))
    r("lcase", lambda c: E.Lower(c))
    r("trim", lambda c: E.Trim(c))
    r("ltrim", lambda c: E.LTrim(c))
    r("rtrim", lambda c: E.RTrim(c))
    r("length", lambda c: E.Length(c))
    r("char_length", lambda c: E.Length(c))
    r("substring", lambda c, p, l=None: E.Substring(c, p, l))
    r("substr", lambda c, p, l=None: E.Substring(c, p, l))
    r("concat", lambda *a: E.Concat(list(a)))
    r("replace", lambda c, s, rep: E.StringReplace(c, s, rep))
    r("lpad", lambda c, l, p=None: E.Lpad(c, l, p if p is not None else E.Literal(" "))),
    r("rpad", lambda c, l, p=None: E.Rpad(c, l, p if p is not None else E.Literal(" "))),
    r("startswith", lambda c, p: E.StartsWith(c, _lit_str(p)))
    r("endswith", lambda c, p: E.EndsWith(c, _lit_str(p)))
    r("contains", lambda c, p: E.Contains(c, _lit_str(p)))
    r("like", lambda c, p: E.Like(c, _lit_str(p)))
    r("rlike", lambda c, p: E.RLike(c, _lit_str(p)))
    r("regexp", lambda c, p: E.RLike(c, _lit_str(p)))
    r("regexp_extract", lambda c, p, g=None: E.RegexpExtract(
        c, p, g if g is not None else E.Literal(1)))
    r("date_format", lambda c, f: E.DateFormat(c, f))
    r("initcap", lambda c: E.Initcap(c))
    r("reverse", lambda c: E.Reverse(c))
    r("repeat", lambda c, n: E.Repeat(c, n))
    r("substring_index", lambda c, d, n: E.SubstringIndex(c, d, n))
    r("regexp_extract", lambda c, p, i=None: E.RegexpExtract(c, p, i))
    r("regexp_replace", lambda c, p, rp: E.RegexpReplace(c, p, rp))
    r("left", lambda c, n: E.Left(c, n))
    r("right", lambda c, n: E.Right(c, n))
    r("overlay", lambda c, rp, p, l=None: E.Overlay(c, rp, p, l))
    r("soundex", lambda c: E.Soundex(c))
    r("md5", lambda c: E.Md5(c))
    r("sha1", lambda c: E.Sha1(c))
    r("sha", lambda c: E.Sha1(c))
    r("sha2", lambda c, b: E.Sha2(c, b))
    r("base64", lambda c: E.Base64(c))
    r("unbase64", lambda c: E.Unbase64(c))
    r("levenshtein", lambda c, o: E.Levenshtein(c, o))
    r("format_number", lambda c, d: E.FormatNumber(c, d))
    r("try_divide", lambda a, b: E.If(
        E.EqualTo(b, E.Literal(0)), E.Literal(None), E.Divide(a, b)))
    r("try_add", lambda a, b: E.TryAdd(a, b))
    r("try_subtract", lambda a, b: E.TrySubtract(a, b))
    r("try_multiply", lambda a, b: E.TryMultiply(a, b))
    # arrays (dictionary-encoded; see ArrayType)
    r("size", lambda c: E.Size(c))
    r("cardinality", lambda c: E.Size(c))
    r("array_contains", lambda c, v: E.ArrayContains(c, v))
    r("array_min", lambda c: E.ArrayMin(c))
    r("array_max", lambda c: E.ArrayMax(c))
    r("sort_array", lambda c, asc=None: E.SortArray(c, asc))
    r("array_distinct", lambda c: E.ArrayDistinct(c))
    r("element_at", lambda c, i: E.build_element_at(c, i))
    r("flatten", lambda c: E.Flatten(c))
    r("slice", lambda c, s, ln: E.Slice(c, s, ln))
    r("array_remove", lambda c, v: E.ArrayRemove(c, v))
    r("array_join", lambda c, sep, nr=None: E.ArrayJoin(c, sep, nr))
    r("array_position", lambda c, v: E.ArrayPosition(c, v))
    r("get_json_object", lambda c, p: E.GetJsonObject(c, p))
    r("crc32", lambda c: E.Crc32(c))
    r("nanvl", lambda a, b: E.NanVl(a, b))
    r("bround", lambda c, s=None: E.BRound(c, s))
    r("struct", lambda *a: E.build_struct_ctor(list(a)))
    r("named_struct", lambda *a: E.build_named_struct(list(a)))
    r("map", lambda *a: E.build_map_ctor(list(a)))
    r("map_keys", lambda c: E.MapKeys(c))
    r("map_values", lambda c: E.MapValues(c))
    r("map_contains_key", lambda c, k: E.MapContainsKey(c, k))
    r("translate", lambda c, m, rep: E.Translate(c, m, rep))
    r("ascii", lambda c: E.Ascii(c))
    r("instr", lambda c, s: E.Instr(c, s))
    r("locate", lambda s, c, pos=None: E.Instr(c, s))
    r("position", lambda s, c: E.Instr(c, s))
    r("concat_ws", lambda sep, *a: E.ConcatWs(sep, list(a)))
    # datetime
    r("year", lambda c: E.Year(c))
    r("month", lambda c: E.Month(c))
    r("day", lambda c: E.DayOfMonth(c))
    r("dayofmonth", lambda c: E.DayOfMonth(c))
    r("quarter", lambda c: E.Quarter(c))
    r("dayofweek", lambda c: E.DayOfWeek(c))
    r("dayofyear", lambda c: E.DayOfYear(c))
    r("weekofyear", lambda c: E.WeekOfYear(c))
    r("date_add", lambda d, n: E.DateAdd(d, n))
    r("date_sub", lambda d, n: E.DateSub(d, n))
    r("datediff", lambda a, b: E.DateDiff(a, b))
    r("trunc", lambda c, f: E.TruncDate(c, _lit_str(f)))
    r("date_trunc", lambda f, c: E.TruncDate(c, _lit_str(f),
                                             allow_day=True))
    r("make_date", lambda y, m, d: E.MakeDate(y, m, d))
    r("hour", lambda c: E.Hour(c))
    r("minute", lambda c: E.Minute(c))
    r("second", lambda c: E.Second(c))
    r("unix_timestamp", lambda c: E.UnixTimestamp(c))
    r("from_unixtime", lambda c, fmt=None: E.FromUnixtime(c))
    r("to_timestamp", lambda c, fmt=None: E.Cast(c, __import__(
        "spark_tpu.types", fromlist=["timestamp"]).timestamp))
    r("add_months", lambda d, n: E.AddMonths(d, n))
    r("months_between", lambda a, b, *x: E.MonthsBetween(a, b))
    r("last_day", lambda c: E.LastDay(c))
    r("to_date", lambda c, fmt=None: E.Cast(c, __import__(
        "spark_tpu.types", fromlist=["date"]).date))
    # window / ranking
    from .window import (
        CumeDist, DenseRank, FirstValue, Lag, LastValue, Lead, NthValue,
        NTile, PercentRank, Rank, RowNumber,
    )

    r("row_number", lambda: RowNumber())
    r("rank", lambda: Rank())
    r("dense_rank", lambda: DenseRank())
    r("percent_rank", lambda: PercentRank())
    r("cume_dist", lambda: CumeDist())
    r("ntile", lambda n: NTile(n))
    r("lag", lambda c, off=None, d=None: Lag(
        c, off if off is not None else E.Literal(1), d))
    r("lead", lambda c, off=None, d=None: Lead(
        c, off if off is not None else E.Literal(1), d))
    r("first_value", lambda c: FirstValue(c))
    r("last_value", lambda c: LastValue(c))
    r("nth_value", lambda c, n: NthValue(c, n))


_reg_all()
